"""Streaming generators: ``num_returns="streaming"`` and ObjectRefGenerator.

Reference capability: python/ray/_raylet.pyx:281 (ObjectRefGenerator),
:1206,1263 (per-item report paths) — a remote generator task/actor method
yields items that are sealed into the object plane ONE AT A TIME; the caller
iterates ObjectRefs as they are produced, with consumer-driven backpressure
so an unbounded producer cannot flood the store.

TPU-first redesign: the stream directory lives beside the (GCS-centralized)
object directory — each produced item is a normal object (sealed + location-
registered via the existing paths) plus one stream-directory append; the
consumer's ``next`` is a single long-poll that doubles as the consumed
watermark (asking for item *i* acknowledges items < *i*), which is what the
producer's backpressure gate waits on. No extra RPC per consumed item.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Optional, TYPE_CHECKING

from ray_tpu.core.ids import ObjectID, TaskID
from ray_tpu.core.object_ref import ObjectRef

if TYPE_CHECKING:
    from ray_tpu.core.runtime import CoreRuntime

STREAMING = "streaming"

_STOP = object()  # sentinel: end-of-stream across executor boundaries


def stream_item_id(task_hex: str, index: int) -> ObjectID:
    """Object id of stream item ``index`` (0-based): return slot index+1."""
    return ObjectID.for_task_return(TaskID(bytes.fromhex(task_hex)), index + 1)


class ObjectRefGenerator:
    """Iterator over the ObjectRefs produced by a streaming task.

    Sync (``for ref in gen``) and async (``async for ref in gen``) iteration;
    each yielded ObjectRef resolves through the normal ``get`` path. Dropping
    the generator early closes the stream: the producer is unblocked (and told
    to stop) and unconsumed items are released.
    """

    def __init__(self, task_hex: str, runtime: "CoreRuntime"):
        self._task_hex = task_hex
        self._runtime = runtime
        self._index = 0
        self._total: Optional[int] = None
        self._closed = False

    @property
    def task_id_hex(self) -> str:
        return self._task_hex

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        return self._next_internal(timeout=None)

    def _next_internal(self, timeout: Optional[float]) -> ObjectRef:
        if self._total is not None and self._index >= self._total:
            raise StopIteration
        if self._closed:
            raise StopIteration
        kind, value = self._runtime.stream_next(self._task_hex, self._index, timeout)
        if kind == "end":
            self._total = value
            if self._index >= value:
                raise StopIteration
            # items can land before the end marker is observed: retry the index
            return self._next_internal(timeout)
        self._index += 1
        return ObjectRef(ObjectID.from_hex(value))

    def __aiter__(self) -> "ObjectRefGenerator":
        return self

    async def __anext__(self) -> ObjectRef:
        loop = asyncio.get_running_loop()

        def step():  # StopIteration cannot cross a Future boundary
            try:
                return self.__next__()
            except StopIteration:
                return _STOP

        ref = await loop.run_in_executor(None, step)
        if ref is _STOP:
            raise StopAsyncIteration
        return ref

    def completed(self) -> bool:
        return self._total is not None and self._index >= self._total

    def close(self) -> None:
        """Stop consuming: unblocks (and stops) the producer, releases
        unconsumed items."""
        if not self._closed:
            self._closed = True
            try:
                self._runtime.stream_close(self._task_hex)
            except Exception:  # noqa: BLE001 - runtime may already be down
                pass

    def __del__(self) -> None:
        try:
            if self._total is None or self._index < self._total:
                self.close()
        except Exception:  # noqa: BLE001
            pass

    def __repr__(self) -> str:
        return f"ObjectRefGenerator(task={self._task_hex[:16]}, next={self._index})"


def iter_async_gen(agen):
    """Drain an async generator from a sync context on a private event loop
    (used when a streaming task/actor method is an async generator)."""
    loop = asyncio.new_event_loop()
    try:
        while True:
            try:
                yield loop.run_until_complete(agen.__anext__())
            except StopAsyncIteration:
                return
    finally:
        loop.run_until_complete(agen.aclose())
        loop.close()


class LocalStreamState:
    """In-process stream directory entry (LocalRuntime backend)."""

    __slots__ = ("items", "finished", "total", "consumed", "delivered",
                 "closed", "cond")

    def __init__(self) -> None:
        self.items: dict = {}          # index -> oid hex
        self.finished = False
        self.total = 0
        self.consumed = 0              # consumer watermark: next index wanted
        self.delivered = 0             # indices actually handed out via next()
        self.closed = False
        self.cond = threading.Condition()

    # -- producer side ------------------------------------------------------
    def put(self, index: int, oid_hex: str, backpressure: int) -> bool:
        """Record item ``index``; block while too far ahead of the consumer.
        Returns False when the consumer closed the stream (producer should
        stop)."""
        with self.cond:
            self.items[index] = oid_hex
            self.cond.notify_all()
            while (
                backpressure > 0
                and (index + 1) - self.consumed >= backpressure
                and not self.closed
            ):
                self.cond.wait(0.05)
            return not self.closed

    def end(self, total: int) -> None:
        with self.cond:
            self.finished = True
            self.total = total
            self.cond.notify_all()

    # -- consumer side ------------------------------------------------------
    def next(self, index: int, timeout: Optional[float]):
        with self.cond:
            if index > self.consumed:
                self.consumed = index
                self.cond.notify_all()
            deadline = None
            if timeout is not None:
                import time as _time

                deadline = _time.monotonic() + timeout
            while True:
                if index in self.items:
                    self.delivered = max(self.delivered, index + 1)
                    return ("item", self.items[index])
                if self.finished:
                    return ("end", self.total)
                if deadline is not None:
                    import time as _time

                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"stream item {index} not produced within {timeout}s"
                        )
                    self.cond.wait(min(remaining, 0.1))
                else:
                    self.cond.wait(0.1)

    def close(self) -> None:
        with self.cond:
            self.closed = True
            self.cond.notify_all()
