"""ClusterRuntime: CoreRuntime backend over a real multi-process cluster.

Driver and worker processes both use this class; it speaks to:
- the GCS (membership, actors, objects directory, KV, placement groups)
- the LOCAL node agent (object plane, task submission)
- actor workers DIRECTLY (per-call push, the agent is off the data path —
  reference: transport/actor_task_submitter.h direct PushTask design).
"""

from __future__ import annotations

import concurrent.futures
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from ray_tpu import exceptions as exc
from ray_tpu.core import serialization
from ray_tpu.core.config import (columnar_exchange_enabled, config,
                                 gcs_recovery_enabled)
from ray_tpu.core.ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.resources import (
    DefaultSchedulingStrategy,
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SpreadSchedulingStrategy,
)
from ray_tpu.core.rpc import RpcConnectionError, RpcError, SyncRpcClient
from ray_tpu.core.runtime import CoreRuntime
from ray_tpu.core.shm_store import ShmReader, ShmWriter, segment_name
from ray_tpu.core.task_spec import TaskSpec
from ray_tpu.core.worker import Worker, global_worker
from ray_tpu.utils.logging import get_logger

logger = get_logger("cluster_runtime")


def strategy_to_dict(strategy) -> Dict[str, Any]:
    if isinstance(strategy, SpreadSchedulingStrategy):
        return {"kind": "spread"}
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return {"kind": "node_affinity", "node_id": strategy.node_id, "soft": strategy.soft}
    if isinstance(strategy, NodeLabelSchedulingStrategy):
        return {"kind": "default", "labels": dict(strategy.hard)}
    if isinstance(strategy, PlacementGroupSchedulingStrategy) and strategy.placement_group is not None:
        return {
            "kind": "placement_group",
            "pg": strategy.placement_group.id.hex(),
            "bundle": strategy.placement_group_bundle_index,
        }
    return {"kind": "default"}


class ClusterRuntime(CoreRuntime):
    is_local = False

    def __init__(
        self,
        gcs_address: str,
        agent_address: str,
        node_id: NodeID,
        is_driver: bool = True,
        namespace: str = "default",
    ):
        self.gcs_address = gcs_address
        self.agent_address = agent_address
        self.node_id = node_id
        self.node_hex = node_id.hex()
        self.namespace = namespace
        # CLIENT MODE (reference: util/client ray:// tier): when the driver
        # runs on a machine that does not share the agent's /dev/shm, the
        # object data plane rides chunked RPCs instead of shm mappings.
        # Set by connect_driver's hostname probe (or force with
        # address="client://host:port").
        self.remote_data_plane = False
        self.gcs = SyncRpcClient(gcs_address)
        self.agent = SyncRpcClient(agent_address)
        # distributed-GC identity of THIS process + batched ref sync (adds and
        # removes flushed in submission order so an add never overtakes the
        # del of the same id)
        import uuid as _uuid

        self.client_id = f"w:{_uuid.uuid4().hex[:16]}"
        self._ref_ops: List[Tuple[str, str]] = []  # ("add"|"del", oid hex)
        self._ref_lock = threading.Lock()       # guards the op queue
        self._flush_lock = threading.Lock()     # serializes drain+send ordering
        self._ref_flusher: Optional[threading.Thread] = None
        self._ref_stop = threading.Event()
        self._last_holder_hb = 0.0
        # the flusher doubles as the holder-lease heartbeat, so it must run
        # from the first moment this process can hold refs (a driver that
        # only submits tasks — no put() — still holds its task returns;
        # without heartbeats the GCS would reap them after the lease)
        self._start_ref_flusher()
        self._exported_fns: set = set()
        self._workdir_hashes: Dict[str, str] = {}
        self._actor_clients: Dict[str, SyncRpcClient] = {}
        self._actor_cache: Dict[str, Dict[str, Any]] = {}
        self._dispatchers: Dict[str, Any] = {}
        self._agent_clients: Dict[str, SyncRpcClient] = {agent_address: self.agent}
        self._lock = threading.Lock()
        self._bg = concurrent.futures.ThreadPoolExecutor(max_workers=16,
                                                         thread_name_prefix="actor-call")
        # pipelined task submission: outstanding submit-ack futures. remote()
        # only blocks when the window is full; get()/wait() barrier on all
        # acks (the agent pins deps before acking, so the ack is the moment
        # arg refs may be safely dropped — the barrier preserves that
        # guarantee at the first point the caller can observe results).
        from collections import deque

        self._submit_acks: "deque" = deque()
        self._submit_window = 64
        self._submit_lock = threading.Lock()  # user threads may race get()/remote()
        self._shutting_down = False
        # ---- pipelined control plane (ISSUE r06) ----
        from ray_tpu.core.config import inline_max_bytes, pipeline_enabled

        self.pipelined = pipeline_enabled()
        self._inline_max = inline_max_bytes()
        # submission coalescing: specs buffer here and flush as ONE
        # submit_task_batch RPC by size or a ~1 ms window
        self._submit_buf: List[Dict[str, Any]] = []
        self._submit_buf_bytes = 0
        self._submit_event = threading.Event()
        self._submit_flusher: Optional[threading.Thread] = None
        self.submit_batches_sent = 0   # observability + tests
        self.tasks_submitted = 0
        # inline completion cache: results that NEVER touched the arena
        # (actor-call replies under the inline threshold). Entries live until
        # the local ref is released; passing such a ref onward promotes the
        # payload to the agent first (_promote_inline).
        # _seal_cond guards BOTH dicts and wakes get()/wait() on any push.
        self._seal_cond = threading.Condition()
        self._inline_cache: Dict[str, Dict[str, Any]] = {}
        self._inline_promoted: set = set()
        # pushed seal events from the GCS (sealed:{client_id} channel):
        # object located cluster-wide, possibly with an in-band small payload
        self._sealed_events: Dict[str, Dict[str, Any]] = {}
        # return ids of in-flight pipelined actor calls: their completions
        # arrive through the reply/push channel (possibly inline-only, never
        # registered at the GCS), so get() must keep waiting on the channel
        # instead of falling back to the ensure path for them
        self._pending_actor_returns: set = set()
        # return ids of submitted-not-yet-sealed tasks: get() expects pushed
        # completions for these and stays RPC-free while they stream in;
        # ids NOT here (puts, borrowed refs) go straight to the ensure path
        self._pending_task_returns: Dict[str, bool] = {}
        self._actor_pipelines: Dict[str, "_ActorPipeline"] = {}
        # batched actor-call ref pins/unpins: one FIFO thread preserves
        # pin-before-unpin order per task while coalescing into pin_tasks/
        # unpin_tasks RPCs (the lockstep path pays one GCS round trip per
        # call for each)
        self._refop_buf: List[Tuple[str, Dict[str, Any]]] = []
        self._refop_event = threading.Event()
        self._refop_thread: Optional[threading.Thread] = None
        # GCS crash-restart recovery (core/recovery/envelope.py): epoch
        # observation rides the holder-heartbeat ack; the reconnect hook
        # fires the catch-up (sealed-channel poll + ref re-assertion) the
        # moment the client transparently re-dials a restarted GCS
        from ray_tpu.core.recovery import RetryEnvelope

        self._envelope = RetryEnvelope()
        self._recovery_lock = threading.Lock()
        if gcs_recovery_enabled():
            self.gcs.add_reconnect_hook(
                lambda: self._spawn_gcs_recovery("gcs client reconnected"))
        if self.pipelined:
            self._submit_flusher = threading.Thread(
                target=self._submit_flush_loop, daemon=True,
                name=f"submit-flush-{self.client_id[2:10]}")
            self._submit_flusher.start()
            self._refop_thread = threading.Thread(
                target=self._refop_flush_loop, daemon=True,
                name=f"refop-flush-{self.client_id[2:10]}")
            self._refop_thread.start()
            try:
                self.gcs.subscribe(f"sealed:{self.client_id}",
                                   self._on_sealed_event)
            except Exception:  # noqa: BLE001 - pushes are an optimization;
                # get()/wait() fall back to the polling paths without them
                logger.warning("sealed-event subscription failed", exc_info=True)

    # ------------------------------------------------------------- objects
    def _store_admission_call(self, method: str, **params):
        """A store-write RPC against the local agent, retried while the
        store is TRANSIENTLY full. Dep pinning (agent dispatch) makes a
        running task's args unevictable and unspillable for the task's
        whole dispatch, so under pressure every byte of the store can be
        pinned-or-unsealed for a few seconds at a time; a put landing in
        that window must wait the pins out (task completion and the
        busy-requeue path both unpin) instead of failing hard."""
        deadline = time.monotonic() + config.store_full_put_wait_s
        delay = 0.05
        while True:
            try:
                return self.agent.call(method, **params)
            except RpcError as e:
                if e.remote_type != "ObjectStoreFullError":
                    raise
                if time.monotonic() >= deadline:
                    raise exc.ObjectStoreFullError(str(e)) from None
            time.sleep(delay)
            delay = min(delay * 2, 0.5)

    def put(self, value: Any) -> ObjectRef:
        w = global_worker()
        oid = w.next_put_id()
        payload, refs = serialization.pack(value)
        if self.pipelined and refs:
            # refs nested inside the stored value escape this process with
            # the container: materialize any inline-only values first
            self._promote_inline([r.id.hex() for r in refs])
        self._queue_ref_op("add", oid.hex())  # this process holds the new ref
        if len(payload) <= config.max_direct_call_object_size:
            # small object: one round trip (agent writes the shm segment)
            self._store_admission_call(
                "put_object", object_id=oid.hex(), payload=bytes(payload),
                contained=[r.id.hex() for r in refs] or None,
            )
            if self.pipelined and len(payload) <= self._inline_max:
                # the putter already HAS the bytes: cache them so a local
                # get() is a dict lookup, no RPC and no arena read. Marked
                # promoted — the value is sealed in the arena already.
                with self._seal_cond:
                    self._inline_cache[oid.hex()] = {
                        "object_id": oid.hex(), "payload": bytes(payload),
                        "is_error": False,
                        "contained": [r.id.hex() for r in refs] or None,
                    }
                    self._inline_promoted.add(oid.hex())
                    self._seal_cond.notify_all()
                self._evict_inline_overflow()
            return ObjectRef(oid)
        if self.remote_data_plane:
            # CLIENT MODE (reference: ray:// Ray Client proxied data plane):
            # the driver is off-cluster, so large puts stream through the
            # agent's chunked ingest instead of writing shm directly.
            # payload stays a buffer view — per-chunk bytes() bounds the
            # extra copy to one chunk, not the whole object
            self._put_via_rpc(oid, payload,
                              [r.id.hex() for r in refs] or None)
            return ObjectRef(oid)
        resp = self._store_admission_call("create_object",
                                          object_id=oid.hex(),
                                          size=len(payload))
        offset = resp.get("offset") if isinstance(resp, dict) else None
        writer = ShmWriter(oid, len(payload), self.node_hex, offset=offset)
        writer.buffer[:] = payload
        writer.seal()
        self.agent.call(
            "seal_object", object_id=oid.hex(), size=len(payload),
            contained=[r.id.hex() for r in refs] or None,
        )
        return ObjectRef(oid)

    def _put_via_rpc(self, oid: ObjectID, payload,
                     contained: Optional[List[str]]) -> None:
        """Stream a large put into the agent store. Raw plane: chunk
        payloads ride raw frames (memoryview straight to the socket, no
        per-chunk bytes() copy or msgpack encode) with a window of sends in
        flight instead of one serial round trip per chunk; the agent's
        cached-writer ingest seals + registers once every byte lands.
        RTPU_RAW_TRANSFER=0 restores the serial in-band path."""
        from ray_tpu.core.config import raw_transfer_enabled

        size = len(payload)
        view = memoryview(payload)
        chunk = config.fetch_chunk_bytes
        if not raw_transfer_enabled():
            sent = 0
            while True:
                n = min(chunk, size - sent)
                last = sent + n >= size
                self.agent.call(
                    "receive_chunk", object_id=oid.hex(), total_size=size,
                    offset=sent, data=bytes(view[sent:sent + n]),
                    contained=contained if last else None,
                    timeout=120.0,
                )
                sent += n
                if last:
                    return
        from collections import deque

        window = max(1, int(config.transfer_window_chunks))
        inflight: "deque" = deque()

        from ray_tpu.core.node.transfer import attempt_timeout

        def send_async(off: int, attempt: int = 0):
            n = min(chunk, size - off)
            return self.agent.call_raw_send_async(
                "receive_chunk_raw", view[off:off + n],
                timeout=attempt_timeout(attempt),
                object_id=oid.hex(), total_size=size, offset=off,
                contained=contained,
            )

        offsets = list(range(0, size, chunk)) or [0]
        retried: Dict[int, int] = {}
        while offsets or inflight:
            while offsets and len(inflight) < window:
                off = offsets.pop(0)
                inflight.append((off, send_async(off, retried.get(off, 0))))
            off, fut = inflight.popleft()
            try:
                fut.result()
            except TimeoutError:
                # idempotent ingest (deduped by offset): re-send the chunk
                # instead of failing the put on one dropped frame
                retried[off] = retried.get(off, 0) + 1
                if retried[off] > 5:
                    raise
                offsets.insert(0, off)

    def start_log_stream(self) -> None:
        """Subscribe to the cluster's worker-log pubsub channel and mirror
        lines to this driver's stderr (reference: log_to_driver /
        _private/log_monitor.py — workers' prints surface at the driver)."""
        import sys

        def on_logs(msg) -> None:
            try:
                prefix = f"({msg['worker'][:8]} {msg['node']})"
                for line in msg.get("lines") or []:
                    print(f"{prefix} {line}", file=sys.stderr)
            except Exception:  # noqa: BLE001 - a bad frame must not kill pubsub
                pass

        try:
            self.gcs.subscribe("worker_logs", on_logs)
        except Exception:  # noqa: BLE001 - log mirroring is best-effort
            logger.warning("worker-log stream unavailable", exc_info=True)

    def _read_via_rpc(self, oid: ObjectID, size: int) -> bytes:
        from ray_tpu.core.config import raw_transfer_enabled

        if raw_transfer_enabled():
            return self._read_via_raw(oid, size)
        data = bytearray()
        chunk = config.fetch_chunk_bytes
        while len(data) < size:
            try:
                data += self.agent.call(
                    "read_chunk", object_id=oid.hex(), offset=len(data),
                    length=min(chunk, size - len(data)), timeout=120.0,
                )
            except RpcError as e:
                if e.remote_type == "KeyError":
                    # evicted between the metadata reply and this chunk:
                    # surface as the same transient condition the shm path
                    # raises so get()'s re-ensure retry loop handles it
                    raise FileNotFoundError(str(e)) from e
                raise
        return bytes(data)

    def _read_via_raw(self, oid: ObjectID, size: int) -> bytes:
        """Client-mode chunked read over raw frames: payload bytes land
        straight in the destination buffer (no msgpack decode, no per-chunk
        bytes accumulation), with a window of requests in flight. Short
        chunks (chaos truncation) re-request exactly the missing tail."""
        from collections import deque

        buf = bytearray(size)
        mv = memoryview(buf)
        chunk = config.fetch_chunk_bytes
        window = max(1, int(config.transfer_window_chunks))
        work = deque((off, min(chunk, size - off))
                     for off in range(0, size, chunk))
        requeues = 0
        max_requeues = 8 * (len(work) + 1)
        while work:
            batch = []
            while work and len(batch) < window:
                off, n = work.popleft()
                dest = mv[off:off + n]

                def make_sink(d):
                    return lambda meta, nbytes: d[:nbytes] if nbytes else None

                batch.append((off, n, self.agent.call_raw_async(
                    "read_chunk_raw", make_sink(dest), timeout=120.0,
                    object_id=oid.hex(), offset=off, length=n)))
            for off, n, fut in batch:
                try:
                    res = fut.result()
                except RpcError as e:
                    if e.remote_type == "KeyError":
                        raise FileNotFoundError(str(e)) from e
                    raise
                except TimeoutError:
                    res = {"nbytes": 0}
                got = int(res.get("nbytes", 0))
                if got < n:
                    requeues += 1
                    if requeues > max_requeues:
                        raise TimeoutError(
                            f"chunked read of {oid.hex()[:16]} kept losing "
                            f"frames after {requeues} re-requests")
                    work.append((off + got, n - got))
        return bytes(buf)

    def _read_local(self, oid: ObjectID, size: int, is_error: bool,
                    offset: Optional[int] = None) -> Any:
        if self.remote_data_plane:
            value = serialization.unpack(self._read_via_rpc(oid, size),
                                         zero_copy=True)
        else:
            reader = ShmReader(oid, size, self.node_hex, offset=offset)
            try:
                if (offset is not None and not is_error
                        and serialization.pinned_reads_active()
                        and columnar_exchange_enabled()):
                    # Pinned-args fast path (columnar exchange): the caller
                    # is a worker resolving task deps the agent holds
                    # pinned until the task completes, and the object lives
                    # in the arena (whose mapping is process-wide and never
                    # unmapped) — decode over the LIVE mapping so arrow
                    # columns / numpy arrays alias the arena instead of a
                    # heap copy. Post-decode revalidation catches the
                    # evicted-and-recycled race exactly like read_bytes().
                    value = serialization.unpack(
                        reader.buffer.toreadonly(), zero_copy=True)
                    if not reader.revalidate():
                        raise FileNotFoundError(
                            f"arena slot for {oid.hex()[:16]} recycled "
                            f"mid-read")
                else:
                    value = serialization.unpack(reader.read_bytes(),
                                                 zero_copy=True)
            finally:
                reader.close()
        if is_error:
            self._raise_error_value(value)
        return value

    @staticmethod
    def _raise_error_value(err: Any) -> None:
        if isinstance(err, dict) and "__rtpu_error__" in err:
            # cross-language (xlang) error envelope from a non-Python
            # submitter's task (see worker_main._store_error_returns)
            raise exc.TaskError(err.get("__rtpu_error__", "?"),
                                err.get("message", ""))
        if isinstance(err, exc.TaskError):
            raise err.as_instanceof_cause()
        raise err

    def _unpack_payload(self, payload: bytes, is_error: bool) -> Any:
        """Materialize a result from an INLINE payload (actor-call reply or
        pushed seal event) — same semantics as _read_local, no arena."""
        value = serialization.unpack(memoryview(payload), zero_copy=False)
        if is_error:
            self._raise_error_value(value)
        return value

    # ------------------------------------------------- pipelined completions
    def _on_sealed_event(self, msg: Any) -> None:
        """Pushed seals from the GCS (this process holds the objects): one
        frame carries every seal of a registration batch. Record them and
        wake parked get()/wait() ONCE. Runs on the GCS client's loop
        thread — must never block."""
        try:
            events = msg.get("events") or []
            with self._seal_cond:
                for ev in events:
                    h = ev.get("object_id")
                    if not h:
                        continue
                    self._pending_task_returns.pop(h, None)
                    self._sealed_events[h] = ev
                while len(self._sealed_events) > 20000:
                    # events are an optimization: evicting one costs a
                    # fallback RPC, never correctness (the object itself
                    # lives in the arena)
                    self._sealed_events.pop(next(iter(self._sealed_events)))
                self._seal_cond.notify_all()
        except Exception:  # noqa: BLE001 - a bad frame must not kill pubsub
            logger.exception("sealed-event handler failed")

    def _absorb_inline(self, reply: Any) -> None:
        """Cache inline results from an actor-call completion. These values
        exist NOWHERE else (the worker skipped the arena write); they are
        promoted to the agent's store the moment the ref could escape this
        process, or when the cache overflows."""
        inline = (reply or {}).get("inline_returns") or []
        if not inline:
            return
        with self._seal_cond:
            for item in inline:
                self._inline_cache[item["object_id"]] = item
            self._seal_cond.notify_all()
        self._evict_inline_overflow()

    def _evict_inline_overflow(self, cap: int = 8192) -> None:
        """Bound the inline cache: already-promoted entries (puts, passed-on
        results) just drop; inline-only entries are promoted to the agent's
        store first so the value survives eviction."""
        with self._seal_cond:
            extra = len(self._inline_cache) - cap
            if extra <= 0:
                return
            overflow = list(self._inline_cache)[:extra]
            droppable = [h for h in overflow if h in self._inline_promoted]
            to_promote = [h for h in overflow if h not in self._inline_promoted]
            for h in droppable:
                self._inline_cache.pop(h, None)
                self._inline_promoted.discard(h)
        if to_promote:
            try:
                self._promote_inline(to_promote)
            except Exception:  # noqa: BLE001 - entries stay cached; retry later
                logger.exception("inline-cache overflow promotion failed")
            else:
                with self._seal_cond:
                    for h in to_promote:
                        self._inline_cache.pop(h, None)
                        self._inline_promoted.discard(h)

    def _promote_inline(self, ids: Sequence[str]) -> None:
        """Write inline-cached results into the agent's store (idempotent).
        Called before a ref escapes this process (task/actor-call argument,
        nested inside a put) so the cluster can serve the value to anyone
        else who may hold the ref."""
        for h in ids:
            with self._seal_cond:
                ent = self._inline_cache.get(h)
                if ent is None or h in self._inline_promoted:
                    continue
                self._inline_promoted.add(h)
            try:
                self.agent.call(
                    "put_object", object_id=h, payload=ent["payload"],
                    owner=ent.get("owner") or "",
                    is_error=bool(ent.get("is_error")),
                    contained=ent.get("contained"),
                )
            except Exception:
                with self._seal_cond:
                    self._inline_promoted.discard(h)
                raise

    def _drop_cached_result(self, oid_hex: str) -> None:
        with self._seal_cond:
            self._inline_cache.pop(oid_hex, None)
            self._inline_promoted.discard(oid_hex)
            self._sealed_events.pop(oid_hex, None)
            self._pending_task_returns.pop(oid_hex, None)

    # ------------------------------------------------ batched pins/unpins
    def _queue_refop(self, kind: str, payload: Dict[str, Any]) -> None:
        with self._ref_lock:
            self._refop_buf.append((kind, payload))
        self._refop_event.set()

    def _refop_flush_loop(self) -> None:
        while not self._ref_stop.is_set():
            if not self._refop_event.wait(timeout=0.5):
                continue
            self._refop_event.clear()
            time.sleep(config.submit_batch_window_ms / 1000.0)
            try:
                self._flush_refops()
            except Exception:  # noqa: BLE001 - advisory bookkeeping
                logger.exception("actor pin/unpin flush failed")

    def _flush_refops(self) -> None:
        """Drain queued actor-call pins/unpins into batched GCS RPCs,
        preserving order (a task's unpin is enqueued strictly after its pin,
        and consecutive same-kind runs coalesce — same scheme as
        flush_refs)."""
        with self._ref_lock:
            ops, self._refop_buf = self._refop_buf, []
        if not ops:
            return
        i = 0
        while i < len(ops):
            kind = ops[i][0]
            j = i
            while j < len(ops) and ops[j][0] == kind:
                j += 1
            batch = [p for _, p in ops[i:j]]
            self.gcs.call("pin_tasks" if kind == "pin" else "unpin_tasks",
                          **({"pins": batch} if kind == "pin"
                             else {"unpins": batch}))
            i = j

    def _actor_returns_done(self, sd: Dict[str, Any]) -> None:
        """An actor call fully completed (inline absorbed / arena stored /
        error objects materialized): its returns may now resolve through the
        normal fallback paths."""
        returns = sd.get("returns") or []
        if not returns:
            return
        with self._seal_cond:
            self._pending_actor_returns.difference_update(returns)
            self._seal_cond.notify_all()

    def _resolve_cached(self, oid_hex: str, resolved: Dict[str, Any]) -> bool:
        """Serve one id from the inline cache or a pushed payload; raises for
        error results (same contract as the arena read)."""
        with self._seal_cond:
            ent = self._inline_cache.get(oid_hex)
            if ent is None:
                ev = self._sealed_events.get(oid_hex)
                if ev is None or "payload" not in ev:
                    return False
                ent = ev
        resolved[oid_hex] = self._unpack_payload(ent["payload"],
                                                 bool(ent.get("is_error")))
        return True

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float]) -> List[Any]:
        if not refs:
            return []
        self._barrier_submit_acks()
        blocked = self._notify_blocked(True)
        try:
            deadline = None if timeout is None else time.monotonic() + timeout
            ids = [r.id.hex() for r in refs]
            resolved: Dict[str, Any] = {}
            todo: List[str] = []
            seen: set = set()
            for h in ids:
                if h in seen:
                    continue
                seen.add(h)
                if not (self.pipelined and self._resolve_cached(h, resolved)):
                    todo.append(h)
            if todo and self.pipelined:
                # push phase: completions stream in over the sealed-event
                # channel (and actor-call replies); zero RPCs while they flow
                todo = self._await_pushed(todo, deadline, resolved)
            if todo:
                self._get_via_ensure(todo, deadline, resolved)
            return [resolved[h] for h in ids]
        finally:
            if blocked:
                self._notify_blocked(False)

    def _await_pushed(self, todo: List[str], deadline: Optional[float],
                      resolved: Dict[str, Any]) -> List[str]:
        """Block on pushed completions for ids we EXPECT pushes for — our
        own submitted task returns and in-flight actor calls. Everything
        else (puts, borrowed refs, objects sealed before this process held
        them) never pushes, so it goes straight to the ensure+read path.
        A stall with zero progress also falls back (lost pushes cost
        latency, never correctness — the ensure loop re-checks the inline
        cache, so even inline-only completions landing late are served).
        Returns the ids still needing the ensure+read path."""
        pending = set(todo)
        with self._seal_cond:
            if not any(h in self._pending_task_returns
                       or h in self._pending_actor_returns
                       for h in pending):
                return list(todo)
        last_progress = time.monotonic()
        while pending:
            # one lock acquisition per wake: scan, else wait — a per-id lock
            # dance here measurably starves the (co-located) control plane
            found: List[Tuple[str, bytes, bool]] = []
            give_up = False
            with self._seal_cond:
                while True:
                    for h in list(pending):
                        ent = self._inline_cache.get(h)
                        if ent is None:
                            ev = self._sealed_events.get(h)
                            if ev is None or "payload" not in ev:
                                continue
                            ent = ev
                        found.append((h, ent["payload"],
                                      bool(ent.get("is_error"))))
                        pending.discard(h)
                    if found or not pending:
                        break
                    if all(h in self._sealed_events for h in pending):
                        give_up = True  # all located — read via the agent
                        break
                    if not any(h in self._pending_task_returns
                               or h in self._pending_actor_returns
                               for h in pending):
                        # every remaining completion already landed (or was
                        # never expected): the store has whatever exists
                        give_up = True
                        break
                    now = time.monotonic()
                    if now - last_progress > 3.0:
                        give_up = True  # stalled: polling path takes over
                        break
                    remaining = None if deadline is None else deadline - now
                    if remaining is not None and remaining <= 0:
                        give_up = True  # ensure path raises GetTimeoutError
                        break
                    chunk = 0.25 if remaining is None else min(0.25, remaining)
                    self._seal_cond.wait(chunk)
            for h, payload, is_error in found:
                resolved[h] = self._unpack_payload(payload, is_error)
            if found:
                last_progress = time.monotonic()
            if give_up:
                break
        return [h for h in todo if h in pending]

    def _get_via_ensure(self, ids: List[str], deadline: Optional[float],
                        resolved: Dict[str, Any]) -> None:
        # One batched RPC: the agent pulls every object concurrently
        # (reference: plasma batched Get, src/ray/core_worker/
        # store_provider/plasma_store_provider.cc). Issued in bounded
        # chunks and re-sent on RPC timeout (ensure_local is idempotent),
        # so one dropped frame doesn't consume the whole user deadline —
        # and a timeout=None get still survives connection hiccups.
        store_full_retries = 0
        while True:
            if self.pipelined:
                # a pushed completion may land while we poll — and an
                # inline-only actor result NEVER appears in the store, so
                # this re-check is what ultimately serves it here
                ids = [h for h in ids if not self._resolve_cached(h, resolved)]
                if not ids:
                    return
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for {len(ids)} objects"
                )
            # short chunks: ensure_local can't distinguish "frame
            # dropped" from "object not ready yet", so a small window
            # bounds what one lost frame costs; re-issue is idempotent
            attempt_s = 5.0 if remaining is None else min(remaining, 5.0)
            try:
                infos = self.agent.call(
                    "ensure_local_batch", object_ids=ids,
                    timeout=attempt_s + 5.0, timeout_s=attempt_s,
                )
            except TimeoutError:
                continue
            if any(i.get("error_type") == "TimeoutError" for i in infos) and (
                remaining is None or remaining > attempt_s
            ):
                continue  # per-object timeout but user deadline remains
            if any(i.get("error_type") == "ObjectStoreFullError"
                   for i in infos) and store_full_retries < 40:
                # transient local pressure (a fragmented/pinned-out arena
                # while other pulls are in flight — e.g. a shuffle's reduce
                # outputs landing): pins drop and spill frees space as
                # tasks finish, so back off and re-ensure instead of
                # failing the get
                store_full_retries += 1
                time.sleep(min(1.0, 0.05 * store_full_retries))
                continue
            break
        for h, info in zip(ids, infos):
            if "error" in info:
                if info.get("error_type") == "TimeoutError":
                    raise exc.GetTimeoutError(
                        f"get() timed out waiting for {h[:16]}"
                    )
                if info.get("error_type") == "ObjectStoreFullError":
                    raise exc.ObjectStoreFullError(info["error"])
                raise exc.ObjectLostError(h, info["error"])
            oid = ObjectID.from_hex(h)
            for attempt in range(4):
                try:
                    resolved[h] = self._read_local(oid, info["size"],
                                                   info["is_error"],
                                                   offset=info.get("offset"))
                    break
                except FileNotFoundError:
                    # arena slot evicted between the metadata reply and
                    # the copy (or mid-copy): the object may still live
                    # in spill — re-ensure and retry with fresh metadata
                    if attempt == 3:
                        raise exc.ObjectLostError(
                            h, "evicted repeatedly during read")
                    info = self.agent.call(
                        "ensure_local", object_id=h,
                        timeout_s=10.0, timeout=15.0,
                    )

    def _notify_blocked(self, blocked: bool) -> bool:
        """Within a worker: tell the agent this worker is blocked in get()
        (its CPU lease is released while waiting). Driver: no-op."""
        import os

        worker_id = os.environ.get("RAY_TPU_WORKER_ID")
        if worker_id is None:
            return False
        try:
            self.agent.call(
                "worker_blocked" if blocked else "worker_unblocked", worker_id=worker_id
            )
            return True
        except Exception:  # noqa: BLE001
            return False

    def wait(self, refs, num_returns, timeout, fetch_local):
        self._barrier_submit_acks()
        ids = [r.id.hex() for r in refs]
        deadline = None if timeout is None else time.monotonic() + timeout
        if self.pipelined:
            ready_set = self._wait_pushed(ids, num_returns, deadline)
        else:
            ready_set = self._wait_via_rpc(ids, num_returns, deadline)
        ready, not_ready = [], []
        for r in refs:
            if r.id.hex() in ready_set and len(ready) < num_returns:
                ready.append(r)
            else:
                not_ready.append(r)
        return ready, not_ready

    def _wait_pushed(self, ids: List[str], num_returns: int,
                     deadline: Optional[float]) -> set:
        """Push-driven wait: a remote seal wakes us through the sealed-event
        channel with NO polling; a stall (lost push, or the object sealed
        before this process became a holder) falls back to one bounded
        wait_objects RPC per chunk — latency cost only, never correctness."""
        needed = min(num_returns, len(ids))
        ready: set = set()

        def _scan() -> None:
            for h in ids:
                if h in self._inline_cache or h in self._sealed_events:
                    ready.add(h)

        while True:
            with self._seal_cond:
                _scan()
                if len(ready) >= needed:
                    return ready
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return ready
                chunk = 0.5 if remaining is None else min(0.5, remaining)
                self._seal_cond.wait(chunk)
                progressed = len(ready) < needed and any(
                    h in self._inline_cache or h in self._sealed_events
                    for h in ids if h not in ready
                )
            if progressed:
                continue  # pushes are resolving OUR ids: stay RPC-free
            # no progress this chunk (lost push, or the object sealed before
            # this process became a holder): one bounded wait_objects RPC —
            # itself event-driven at the GCS, so this is a safety net, not a
            # hot poll
            pending = [h for h in ids if h not in ready]
            remaining = None if deadline is None else deadline - time.monotonic()
            attempt_s = 2.0 if remaining is None else max(0.0, min(remaining, 2.0))
            try:
                ready.update(self.agent.call(
                    "wait_objects", object_ids=pending,
                    num_returns=needed - len(ready),
                    timeout=attempt_s + 10.0, timeout_s=attempt_s,
                ))
            except TimeoutError:
                pass
            if len(ready) >= needed:
                return ready
            if remaining is not None and remaining <= attempt_s:
                return ready

    def _wait_via_rpc(self, ids: List[str], num_returns: int,
                      deadline: Optional[float]) -> set:
        # bounded chunks, like get(): one infinite RPC would hang forever if
        # its response frame is lost (agent restart, connection blip) — a
        # re-sent wait is idempotent
        while True:
            remaining = None if deadline is None else deadline - time.monotonic()
            attempt_s = 10.0 if remaining is None else max(0.0, min(remaining, 10.0))
            try:
                ready_ids = self.agent.call(
                    "wait_objects", object_ids=ids, num_returns=num_returns,
                    timeout=attempt_s + 10.0, timeout_s=attempt_s,
                )
            except TimeoutError:
                if remaining is not None and remaining <= attempt_s:
                    ready_ids = []
                    break
                continue
            if len(ready_ids) >= min(num_returns, len(ids)):
                break
            if remaining is not None and remaining <= attempt_s:
                break
        return (set(ready_ids[:num_returns]) if len(ready_ids) > num_returns
                else set(ready_ids))

    def free(self, refs: Sequence[ObjectRef]) -> None:
        for r in refs:
            self._drop_cached_result(r.id.hex())
        self.agent.call("free_objects", object_ids=[r.id.hex() for r in refs])

    def object_sizes(self, refs: Sequence[ObjectRef]) -> List[Optional[int]]:
        try:
            return self.agent.call(
                "object_sizes", object_ids=[r.id.hex() for r in refs]
            )
        except Exception:  # noqa: BLE001 - best-effort (backpressure hint)
            return [None] * len(refs)

    # ------------------------------------------------- streaming generators
    def stream_next(self, task_hex: str, index: int, timeout: Optional[float]):
        """Long-poll the GCS stream directory in bounded chunks (same pattern
        as get(): a dropped frame costs one chunk, not the whole deadline)."""
        self._barrier_submit_acks()  # a dropped submit must raise, not hang
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise exc.GetTimeoutError(
                    f"stream item {index} of {task_hex[:16]} not ready in {timeout}s"
                )
            attempt_s = 5.0 if remaining is None else min(remaining, 5.0)
            try:
                resp = self.gcs.call(
                    "stream_next", task_id=task_hex, index=index,
                    timeout=attempt_s + 5.0, timeout_s=attempt_s,
                )
            except TimeoutError:
                continue
            if resp.get("timeout"):
                continue
            if "end" in resp:
                return ("end", resp["end"])
            return ("item", resp["object_id"])

    def stream_close(self, task_hex: str) -> None:
        try:
            self.gcs.call("stream_close", task_id=task_hex)
        except Exception:  # noqa: BLE001 - teardown path
            pass

    # ------------------------------------------------- distributed ref counts
    def _start_ref_flusher(self) -> None:
        with self._ref_lock:
            if self._ref_flusher is None:
                self._ref_flusher = threading.Thread(
                    target=self._ref_flush_loop, daemon=True,
                    name=f"ref-sync-{self.client_id[2:10]}",
                )
                self._ref_flusher.start()

    def _queue_ref_op(self, op: str, oid_hex: str) -> None:
        with self._ref_lock:
            self._ref_ops.append((op, oid_hex))

    def _ref_flush_loop(self) -> None:
        while not self._ref_stop.wait(config.ref_sync_interval_s):
            try:
                self.flush_refs()
                # renew the holder lease so a crashed process (no shutdown,
                # no heartbeats) gets its holders reaped by the GCS
                now = time.monotonic()
                if now - self._last_holder_hb > min(2.5, config.object_holder_lease_s / 4):
                    self._last_holder_hb = now
                    ack = self.gcs.call("holder_heartbeat",
                                        holder=self.client_id)
                    epoch = ack.get("epoch") if isinstance(ack, dict) else None
                    if self._envelope.observe_epoch(epoch) \
                            and gcs_recovery_enabled():
                        self._spawn_gcs_recovery(
                            f"gcs epoch bumped to {epoch}")
            except Exception:  # noqa: BLE001 - sync is advisory; retry next tick
                pass

    def flush_refs(self) -> None:
        """Drain queued add/del holder updates to the GCS, preserving order.
        Workers call this before completing a task so borrows registered
        during execution land while the task pin still protects them.
        The flush lock spans drain+send: two threads draining and sending
        unserialized could land an add before the del it followed."""
        with self._flush_lock:
            with self._ref_lock:
                ops, self._ref_ops = self._ref_ops, []
            if not ops:
                return
            # coalesce consecutive same-op runs into batched RPCs, keeping order
            i = 0
            while i < len(ops):
                op = ops[i][0]
                j = i
                while j < len(ops) and ops[j][0] == op:
                    j += 1
                ids = [o for _, o in ops[i:j]]
                self.gcs.call(
                    "add_object_refs" if op == "add" else "remove_object_refs",
                    object_ids=ids, holder=self.client_id,
                )
                i = j

    # ----------------------------------------- GCS crash-restart catch-up
    def _spawn_gcs_recovery(self, reason: str) -> None:
        """Run the post-restart catch-up off-thread (the trigger sites — the
        rpc client's reconnect hook and the ref flusher — must not block)."""
        if self._shutting_down:
            return
        threading.Thread(target=self._gcs_restart_catchup, args=(reason,),
                         daemon=True,
                         name=f"gcs-catchup-{self.client_id[2:10]}").start()

    def _gcs_restart_catchup(self, reason: str) -> None:
        """Close the two gaps a GCS restart opens for THIS process:

        - pushed ``sealed:`` events that fired while we were disconnected
          are gone (the channel is re-subscribed, but pushes are not
          replayed) — one catch-up ``wait_objects_located`` poll synthesizes
          payload-less seal events for every pending return that already has
          a location, unparking ``get()``/``wait()`` onto the ensure path;
        - holder refs added after the last snapshot are missing from the
          restored state — re-assert every id this process still holds so
          the new incarnation's GC can't reap live objects.
        """
        if not self._recovery_lock.acquire(blocking=False):
            return  # one catch-up at a time; the next epoch bump re-triggers
        try:
            logger.info("GCS restart catch-up (%s)", reason)
            w = global_worker()
            if w is not None:
                held = w.ref_counter.live_ids()
                for i in range(0, len(held), 500):
                    self.gcs.call("add_object_refs",
                                  object_ids=held[i:i + 500],
                                  holder=self.client_id)
            with self._seal_cond:
                pending = [h for h in list(self._pending_task_returns)
                           if h not in self._sealed_events]
            if pending:
                located = self.gcs.call(
                    "wait_objects_located", object_ids=pending,
                    num_returns=len(pending), timeout_s=0.0)
                with self._seal_cond:
                    for h in located or []:
                        # payload-less synthetic event: get() stops waiting
                        # for a push that already happened and reads the
                        # object through the ensure path instead
                        self._pending_task_returns.pop(h, None)
                        self._sealed_events.setdefault(h, {"object_id": h})
                    self._seal_cond.notify_all()
        except Exception:  # noqa: BLE001 - catch-up is best-effort; the
            # polling fallbacks (ensure path, holder lease renewal) converge
            logger.exception("GCS restart catch-up failed")
        finally:
            self._recovery_lock.release()

    def on_borrowed_ref(self, ref: ObjectRef) -> None:
        """Deserializer hook: an ObjectRef materialized out of another object
        — register this process as a holder (reference_count.h borrow)."""
        self._queue_ref_op("add", ref.id.hex())

    def release(self, oid: ObjectID) -> None:
        """Local refcount hit zero: withdraw this process's cluster holder.
        The GCS frees the object everywhere once ALL holders (other
        processes, in-flight task pins) are gone plus a grace window."""
        self._drop_cached_result(oid.hex())
        self._queue_ref_op("del", oid.hex())

    # --------------------------------------------------------------- tasks
    def _export_function(self, function_id: str, fn: Any) -> None:
        if function_id in self._exported_fns:
            return
        if self.gcs.call("kv_get", key=f"fn:{function_id}") is None:
            self.gcs.call("kv_put", key=f"fn:{function_id}", value=cloudpickle.dumps(fn))
        self._exported_fns.add(function_id)

    def _prepare_runtime_env(self, runtime_env: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
        """Validate + canonicalize; package and upload working_dir to GCS KV
        once per content hash (agents stage it on demand)."""
        from ray_tpu.core import runtime_env as re_mod

        env = re_mod.normalize(runtime_env)
        internal = {k: v for k, v in (runtime_env or {}).items()
                    if k.startswith("__")}
        if not env:
            return internal or None
        def upload_once(cache_key, packager, path: str) -> str:
            # package once per path per driver (contents are snapshotted at
            # first use, like the reference's URI cache) — re-zipping a
            # large tree on EVERY submit would dominate submit latency
            content_hash = self._workdir_hashes.get(cache_key)
            if content_hash is None:
                content_hash, payload = packager(path)
                key = re_mod.kv_key(content_hash)
                if self.gcs.call("kv_get", key=key) is None:
                    self.gcs.call("kv_put", key=key, value=payload)
                self._workdir_hashes[cache_key] = content_hash
            return content_hash

        if "working_dir" in env:
            path = os.path.abspath(env.pop("working_dir"))
            env["working_dir_hash"] = upload_once(
                path, re_mod.package_working_dir, path)
        if "py_modules" in env:
            env["py_modules_hashes"] = [
                upload_once(("pymod", os.path.abspath(p)),
                            re_mod.package_py_module, os.path.abspath(p))
                for p in env.pop("py_modules")
            ]
        return {**env, **internal}

    def _spec_dict(self, spec: TaskSpec, args: tuple, kwargs: dict) -> Dict[str, Any]:
        payload, _refs = serialization.pack((args, kwargs))
        if self.pipelined:
            # any argument ref whose value lives only in this process's
            # inline cache must be materialized in the cluster before anyone
            # else tries to resolve it (top-level deps AND nested refs)
            self._promote_inline(
                [d.hex() for d in spec.dependencies()]
                + [r.id.hex() for r in _refs])
        sd = {
            "runtime_env": self._prepare_runtime_env(spec.runtime_env),
            "task_id": spec.task_id.binary().hex(),
            "name": spec.name,
            "function_id": spec.function.function_id,
            "args_payload": payload,
            "deps": [d.hex() for d in spec.dependencies()],
            "returns": [r.hex() for r in spec.return_ids()],
            "resources": dict(spec.resources),
            "strategy": strategy_to_dict(spec.strategy),
            "max_retries": spec.max_retries,
            "retry_exceptions": spec.retry_exceptions,
        }
        if spec.generator:
            sd["streaming"] = True
            sd["backpressure"] = spec.generator_backpressure
        return sd

    def submit_task(self, spec: TaskSpec, func: Any, args: tuple, kwargs: dict) -> List[ObjectRef]:
        self._export_function(spec.function.function_id, func)
        sd = self._spec_dict(spec, args, kwargs)
        # the agent registers this holder on the returns (and pins deps under
        # a task holder) BEFORE accepting — see agent.rpc_submit_task
        sd["holder"] = self.client_id
        self.tasks_submitted += 1
        if self.pipelined:
            if not spec.generator:
                # expected pushed completions: get() stays on the channel
                # for these instead of polling the agent
                with self._seal_cond:
                    for r in sd["returns"]:
                        self._pending_task_returns[r] = True
                    while len(self._pending_task_returns) > 200000:
                        self._pending_task_returns.pop(
                            next(iter(self._pending_task_returns)))
            # coalescing buffer: specs flush as ONE submit_task_batch RPC by
            # size or the ~1 ms window (the flusher thread)
            self._enqueue_submit(sd)
        else:
            with self._submit_lock:
                self._submit_acks.append(self.agent.call_async("submit_task", spec=sd))
        self._reap_submit_acks()
        if spec.generator:
            # dynamic returns: item holders are registered at stream_put time;
            # materializing refs here would add-then-del the submitter holder
            # on item 0 and free it before the consumer ever sees it
            return []
        return [ObjectRef(oid) for oid in spec.return_ids()]

    def _enqueue_submit(self, sd: Dict[str, Any]) -> None:
        with self._submit_lock:
            self._submit_buf.append(sd)
            self._submit_buf_bytes += len(sd.get("args_payload") or b"")
            full = (len(self._submit_buf) >= config.submit_batch_max
                    or self._submit_buf_bytes >= config.submit_batch_max_bytes)
        if full:
            self._flush_submits()
        else:
            self._submit_event.set()  # arm the window timer

    def _flush_submits(self) -> None:
        with self._submit_lock:
            batch, self._submit_buf = self._submit_buf, []
            self._submit_buf_bytes = 0
            if not batch:
                return
            self._submit_acks.append(
                self.agent.call_async("submit_task_batch", specs=batch))
            self.submit_batches_sent += 1

    def _submit_flush_loop(self) -> None:
        """Window timer: a partial batch flushes ~submit_batch_window_ms
        after the first spec buffered (size-triggered flushes happen inline
        on the submitting thread)."""
        while not self._ref_stop.is_set():
            if not self._submit_event.wait(timeout=0.5):
                continue
            self._submit_event.clear()
            time.sleep(config.submit_batch_window_ms / 1000.0)
            try:
                self._flush_submits()
            except Exception:  # noqa: BLE001 - flusher must survive; the
                # barrier path re-flushes and surfaces errors to the caller
                logger.exception("submit batch flush failed")

    def _pop_ack(self, only_done: bool) -> Optional[Any]:
        with self._submit_lock:
            acks = self._submit_acks
            if not acks:
                return None
            if only_done and not (acks[0].done() or len(acks) > self._submit_window):
                return None
            return acks.popleft()

    def _reap_submit_acks(self) -> None:
        """Harvest completed submit acks; block only when the pipeline
        window is full (keeps many submits in flight instead of one round
        trip per .remote() call)."""
        while True:
            fut = self._pop_ack(only_done=True)
            if fut is None:
                return
            fut.result()  # surfaces submit failures

    def _barrier_submit_acks(self) -> None:
        """Wait for every in-flight submit to be accepted (and its deps
        pinned). Called before get()/wait() so a dropped submit surfaces as
        an exception instead of a hang."""
        if self.pipelined:
            self._flush_submits()  # buffered specs must join the barrier
        while True:
            fut = self._pop_ack(only_done=False)
            if fut is None:
                return
            fut.result()

    def cancel(self, ref: ObjectRef, force: bool, recursive: bool) -> None:
        logger.warning("cancel() is not yet supported on the cluster backend")

    # -------------------------------------------------------------- actors
    def create_actor(self, spec: TaskSpec, cls: Any, args: tuple, kwargs: dict) -> ActorID:
        self._export_function(spec.function.function_id, cls)
        name = (spec.runtime_env or {}).get("__actor_name__", "")
        ns = (spec.runtime_env or {}).get("__actor_namespace__", self.namespace)
        sd = self._spec_dict(spec, args, kwargs)
        sd.update(
            actor_id=spec.actor_id.hex(),
            max_concurrency=spec.max_concurrency,
            max_restarts=spec.max_restarts,
        )
        self._actor_cache[spec.actor_id.hex()] = {
            "max_task_retries": spec.max_task_retries,
            "max_concurrency": spec.max_concurrency,
        }
        # The GCS owns actor scheduling AND restart (GcsActorScheduler
        # equivalent); one call registers + schedules. The envelope parks
        # the call across a GCS outage (create_actor dedupes by actor_id at
        # the GCS, so the re-send after a restart is harmless).
        self._envelope.send(
            self.gcs,
            "create_actor",
            spec=sd,
            class_name=spec.name.split(".")[0],
            name=name,
            namespace=ns,
            max_restarts=spec.max_restarts,
            options=cloudpickle.dumps({
                "options": {
                    "max_task_retries": spec.max_task_retries,
                    "max_concurrency": spec.max_concurrency,
                },
            }),
        )
        return spec.actor_id

    def _resolve_actor(self, actor_hex: str, timeout: float = 60.0) -> Dict[str, Any]:
        deadline = time.monotonic() + timeout
        while True:
            rec = self.gcs.call("get_actor", actor_id=actor_hex)
            if rec is None:
                raise exc.ActorDiedError(actor_hex, "unknown actor")
            if rec["state"] == "ALIVE":
                return rec
            if rec["state"] == "DEAD":
                raise exc.ActorDiedError(actor_hex, rec.get("death_reason") or "actor is dead")
            if time.monotonic() > deadline:
                raise exc.ActorUnavailableError(
                    f"actor {actor_hex[:8]} still {rec['state']} after {timeout}s"
                )
            time.sleep(0.02)

    def _actor_client(self, address: str) -> SyncRpcClient:
        with self._lock:
            if self._shutting_down:
                # a racing push must not mint a client that shutdown()'s
                # close sweep has already passed by (it would wait on a
                # dead cluster with no one left to fail its futures)
                raise RpcConnectionError("runtime is shut down")
            client = self._actor_clients.get(address)
            if client is None:
                client = SyncRpcClient(address)
                self._actor_clients[address] = client
            return client

    def submit_actor_task(self, actor_id: ActorID, spec: TaskSpec, args, kwargs) -> List[ObjectRef]:
        refs = [] if spec.generator else [ObjectRef(oid) for oid in spec.return_ids()]
        sd = self._spec_dict(spec, args, kwargs)
        if spec.generator:
            sd["holder"] = self.client_id
        # pin deps+returns for the in-flight call (released when the call
        # completes) and register this process's holder on the returns.
        # Client-scoped pin id: reaped with this process's holder lease if we
        # crash before removal.
        sd["task_holder"] = f"task:{sd['task_id']}@{self.client_id}"
        pin_kwargs = dict(task_holder=sd["task_holder"], deps=sd["deps"],
                          returns=sd["returns"], submitter=self.client_id,
                          spec=None)
        sd.update(actor_id=actor_id.hex(), method=spec.actor_method_name)
        rec = self._actor_cache.get(actor_id.hex())
        if rec is None:
            rec = {}
            raw = self.gcs.call("get_actor_spec", actor_id=actor_id.hex())
            if raw:
                try:
                    rec = cloudpickle.loads(raw).get("options", {})
                except Exception:  # noqa: BLE001
                    rec = {}
            self._actor_cache[actor_id.hex()] = rec
        if self.pipelined:
            # windowed pipelining: the pin rides the batched refop channel
            # (FIFO — the completion's unpin is enqueued after it and can
            # never overtake it), results at most the inline threshold ride
            # back IN the completion reply, and many calls stay in flight
            # per actor (seq-ordered on the worker side).
            if not spec.generator:
                sd["inline_max"] = self._inline_max
                with self._seal_cond:
                    self._pending_actor_returns.update(sd["returns"])
            self._queue_refop("pin", pin_kwargs)
            self._actor_pipeline(actor_id.hex()).submit(
                sd, spec.max_task_retries,
                ordered=rec.get("max_concurrency", 1) <= 1)
            return refs
        try:
            self.gcs.call("pin_task", **pin_kwargs)
        except Exception:  # noqa: BLE001 - advisory bookkeeping
            logger.exception("actor-task ref pinning failed")
        if rec.get("max_concurrency", 1) > 1:
            # threaded/async actors: unordered concurrent pushes (reference
            # semantics: ordering is only guaranteed for max_concurrency=1)
            self._bg.submit(self._push_actor_task, actor_id.hex(), sd, spec.max_task_retries)
        else:
            # ordered: one dispatcher thread per actor preserves submission
            # order end-to-end (ActorSchedulingQueue equivalent)
            self._actor_dispatcher(actor_id.hex()).put((sd, spec.max_task_retries))
        return refs

    def _actor_pipeline(self, actor_hex: str) -> "_ActorPipeline":
        with self._lock:
            if self._shutting_down:
                raise RpcConnectionError("runtime is shut down")
            p = self._actor_pipelines.get(actor_hex)
            if p is None:
                p = _ActorPipeline(self, actor_hex)
                self._actor_pipelines[actor_hex] = p
            return p

    def _actor_dispatcher(self, actor_hex: str):
        import queue as _q

        with self._lock:
            disp = self._dispatchers.get(actor_hex)
            if disp is None:
                disp = _q.Queue()
                self._dispatchers[actor_hex] = disp

                def loop() -> None:
                    while True:
                        item = disp.get()
                        if item is None:
                            return
                        sd, retries = item
                        try:
                            self._push_actor_task(actor_hex, sd, retries)
                        except Exception:  # noqa: BLE001
                            logger.exception("actor dispatch failed")

                threading.Thread(
                    target=loop, daemon=True, name=f"actor-dispatch-{actor_hex[:8]}"
                ).start()
            return disp

    def _push_actor_task(self, actor_hex: str, sd: Dict[str, Any], max_task_retries: int) -> None:
        try:
            self._push_actor_task_inner(actor_hex, sd, max_task_retries)
        finally:
            holder = sd.get("task_holder")
            if holder:
                try:
                    self.gcs.call(
                        "remove_object_refs",
                        object_ids=(sd.get("deps") or []) + (sd.get("returns") or []),
                        holder=holder,
                    )
                except Exception:  # noqa: BLE001
                    pass

    def _push_actor_task_inner(self, actor_hex: str, sd: Dict[str, Any], max_task_retries: int) -> None:
        attempts = 0
        while True:
            try:
                rec = self._resolve_actor(actor_hex)
                client = self._actor_client(rec["address"])
                while True:
                    try:
                        client.call("run_actor_task", spec=sd,
                                    caller=self.client_id,
                                    timeout=config.actor_call_deadline_s)
                        return
                    except TimeoutError:
                        # Deadline expired: never wedge this dispatcher on a
                        # hung worker (the old timeout=None did exactly that).
                        # Probe liveness — an alive worker means the call is
                        # merely long-running: re-attach (the worker dedupes
                        # by task_id and piggybacks the running execution). A
                        # dead worker fails the ping, which lands in the
                        # retry handler below.
                        client.call("ping", timeout=5.0)
                        logger.warning(
                            "actor call %s exceeded %.0fs; worker alive, "
                            "re-attaching", sd.get("name"),
                            config.actor_call_deadline_s)
            except (exc.ActorDiedError, exc.ActorUnavailableError) as e:
                self._store_error_objects(sd, str(e), "ActorDiedError")
                return
            except (ConnectionError, RpcError, TimeoutError) as e:
                # worker died mid-call or address stale
                attempts += 1
                if isinstance(e, RpcError) and e.remote_type not in (
                    "ConnectionError", "RpcConnectionError", "ActorDiedError",
                ):
                    # handler-level error: results already stored as errors
                    return
                if attempts > max(max_task_retries, 0):
                    self._store_error_objects(
                        sd, f"actor call failed after {attempts} attempts: {e}",
                        "ActorDiedError" if isinstance(e, RpcError) else "ActorUnavailableError",
                    )
                    return
                time.sleep(0.1 * attempts)

    def _store_error_objects(self, sd: Dict[str, Any], message: str, error_type: str) -> None:
        try:
            self.agent.call(
                "store_error", returns=sd["returns"], name=sd.get("name", "?"),
                message=message, error_type=error_type,
            )
        except Exception:  # noqa: BLE001
            logger.exception("failed to store error objects")

    def kill_actor(self, actor_id: ActorID, no_restart: bool) -> None:
        actor_hex = actor_id.hex()
        rec = self.gcs.call("get_actor", actor_id=actor_hex)
        self.gcs.call("kill_actor", actor_id=actor_hex, no_restart=no_restart)
        if rec and rec.get("node_id"):
            agent_addr = self._agent_addr_for(rec["node_id"])
            if agent_addr:
                try:
                    self._agent_client(agent_addr).call("kill_actor_worker", actor_id=actor_hex)
                except Exception:  # noqa: BLE001
                    pass
        self._actor_cache.pop(actor_hex, None)

    def _agent_addr_for(self, node_hex: str) -> Optional[str]:
        for info in self.gcs.call("get_nodes"):
            if info["NodeID"] == node_hex:
                return info["NodeManagerAddress"]
        return None

    def _agent_client(self, address: str) -> SyncRpcClient:
        with self._lock:
            client = self._agent_clients.get(address)
            if client is None:
                client = SyncRpcClient(address)
                self._agent_clients[address] = client
            return client

    def get_named_actor(self, name: str, namespace: Optional[str]) -> ActorID:
        actor_hex = self.gcs.call(
            "get_named_actor", name=name, namespace=namespace or self.namespace
        )
        if actor_hex is None:
            raise ValueError(f"Failed to look up actor '{name}'")
        return ActorID.from_hex(actor_hex)

    def list_named_actors(self, all_namespaces: bool = False, namespace: str = "default") -> List[str]:
        return self.gcs.call(
            "list_named_actors", all_namespaces=all_namespaces, namespace=namespace
        )

    # ------------------------------------------------------ placement groups
    def create_placement_group(self, bundles, strategy: str, name: str) -> PlacementGroupID:
        w = global_worker()
        pg_id = PlacementGroupID.of(w.job_id)
        # creation always succeeds; an unplaceable group stays PENDING at the
        # GCS, feeding the autoscaler's demand ledger until capacity arrives
        # (reference: GcsPlacementGroupManager pending queue)
        self.gcs.call(
            "create_placement_group",
            pg_id=pg_id.hex(), bundles=bundles, strategy=strategy, name=name,
        )
        return pg_id

    def remove_placement_group(self, pg_id: PlacementGroupID) -> None:
        self.gcs.call("remove_placement_group", pg_id=pg_id.hex())

    def placement_group_ready(self, pg_id: PlacementGroupID, timeout) -> bool:
        info = self.gcs.call("placement_group_info", pg_id=pg_id.hex())
        return info is not None and info.get("state") == "CREATED"

    def placement_group_table(self) -> Dict[str, Dict]:
        return self.gcs.call("placement_group_table")

    # --------------------------------------------------------------- cluster
    def nodes(self) -> List[Dict[str, Any]]:
        return self.gcs.call("get_nodes")

    def cluster_resources(self) -> Dict[str, float]:
        return self.gcs.call("cluster_resources")

    def available_resources(self) -> Dict[str, float]:
        return self.gcs.call("available_resources")

    def shutdown(self) -> None:
        self._ref_stop.set()
        self._submit_event.set()  # wake the flusher so it observes the stop
        with self._lock:
            self._shutting_down = True
            pipelines = list(self._actor_pipelines.values())
        for p in pipelines:
            p.stop()
        try:
            self._barrier_submit_acks()
        except Exception:  # noqa: BLE001
            pass
        try:
            self._flush_refops()
            self.flush_refs()
            self.gcs.call("drop_holder", holder=self.client_id)
        except Exception:  # noqa: BLE001
            pass
        for client in list(self._actor_clients.values()) + list(self._agent_clients.values()):
            if client is not self.agent:
                client.close()
        self._bg.shutdown(wait=False)
        self.agent.close()
        self.gcs.close()

    # -------------------------------------------------------------------- kv
    def kv_put(self, key: str, value: bytes) -> None:
        self.gcs.call("kv_put", key=key, value=value)

    def kv_get(self, key: str) -> Optional[bytes]:
        return self.gcs.call("kv_get", key=key)

    def kv_del(self, key: str) -> None:
        self.gcs.call("kv_del", key=key)

    def kv_keys(self, prefix: str = "") -> List[str]:
        return self.gcs.call("kv_keys", prefix=prefix)


class _ActorPipeline:
    """Windowed, seq-numbered pushes to ONE actor over the worker's
    persistent connection (reference: transport/actor_task_submitter.h —
    many calls in flight, out-of-order completion, per-actor order preserved
    by the worker's seq gate; the old design held ONE blocking call per
    dispatcher thread with an infinite deadline).

    Flow: user threads enqueue; the dispatcher thread resolves the actor,
    stamps a seq (ordered actors), and fires call_async bounded by the
    window semaphore. Completions land on the RPC client's loop thread and
    are immediately handed to the runtime's background pool (absorb inline
    results, release pins, or route failures back through this queue).
    Deadline expiries probe worker liveness: alive workers mean a merely
    long-running call (re-attach; the worker dedupes by task_id), dead ones
    route through the retry path — a hung worker can no longer wedge the
    dispatcher forever."""

    def __init__(self, runtime: "ClusterRuntime", actor_hex: str):
        import queue as _q

        self.rt = runtime
        self.actor_hex = actor_hex
        self.q: "_q.Queue" = _q.Queue()
        self.window = threading.Semaphore(max(1, int(config.actor_call_window)))
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._client: Optional[SyncRpcClient] = None  # cached route
        self.calls_pushed = 0  # observability
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"actor-pipe-{actor_hex[:8]}")
        self._thread.start()

    def submit(self, sd: Dict[str, Any], retries: int,
               ordered: bool = True) -> None:
        if ordered:
            with self._seq_lock:
                sd["seq"] = self._seq
                self._seq += 1
        self.q.put(("dispatch", sd, retries, 0))

    def stop(self) -> None:
        self.q.put(None)

    def _loop(self) -> None:
        while True:
            item = self.q.get()
            if item is None:
                return
            kind, sd, retries, attempts = item
            try:
                if kind == "probe":
                    self._probe(sd, retries, attempts)
                else:
                    self._dispatch(sd, retries, attempts)
            except Exception:  # noqa: BLE001 - the pipeline must survive
                logger.exception("actor pipeline dispatch failed")
                self._finish(sd)

    def _get_client(self) -> SyncRpcClient:
        """Resolve-once routing: the worker address is cached so steady-state
        dispatch costs ZERO control RPCs (one get_actor per call serialized
        the old dispatcher); any failure invalidates the cache and the retry
        re-resolves (actor restarts land on the new address)."""
        if self._client is None:
            rec = self.rt._resolve_actor(self.actor_hex)
            self._client = self.rt._actor_client(rec["address"])
        return self._client

    # ------------------------------------------------------------- dispatch
    def _dispatch(self, sd: Dict[str, Any], retries: int, attempts: int) -> None:
        rt = self.rt
        try:
            client = self._get_client()
        except (exc.ActorDiedError, exc.ActorUnavailableError) as e:
            self._fail(sd, str(e), "ActorDiedError")
            return
        except (ConnectionError, RpcError, TimeoutError) as e:
            self._retry_or_fail(sd, retries, attempts + 1, e)
            return
        self.window.acquire()  # backpressure: at most `window` in flight
        try:
            fut = client.call_async(
                "run_actor_task", spec=sd, seq=sd.get("seq"),
                caller=rt.client_id, timeout=config.actor_call_deadline_s)
        except Exception as e:  # noqa: BLE001 - client closed under us
            self.window.release()
            self._client = None
            self._retry_or_fail(sd, retries, attempts + 1, e)
            return
        self.calls_pushed += 1
        fut.add_done_callback(
            lambda f: self._on_done(f, sd, retries, attempts))

    def _on_done(self, fut: Any, sd: Dict[str, Any], retries: int,
                 attempts: int) -> None:
        # runs on the RPC client's event-loop thread: release the window
        # first; the success path is non-blocking (cache writes + queued
        # unpin), failures go to the background pool (they may sleep/RPC)
        self.window.release()
        try:
            reply = fut.result()
        except BaseException as e:  # noqa: BLE001
            self._submit_bg(self._handle_failure, sd, retries, attempts, e)
            return
        try:
            self.rt._absorb_inline(reply)
        except Exception:  # noqa: BLE001
            logger.exception("inline absorb failed")
        self._finish(sd)

    def _submit_bg(self, fn, *args) -> None:
        try:
            self.rt._bg.submit(fn, *args)
        except RuntimeError:  # pool shut down mid-flight
            pass

    # ------------------------------------------------------------ failures
    def _handle_failure(self, sd: Dict[str, Any], retries: int, attempts: int,
                        e: BaseException) -> None:
        if isinstance(e, TimeoutError):
            # deadline expired with the connection healthy: probe liveness
            # on the dispatcher before deciding (long-running user methods
            # are legitimate and must survive)
            self.q.put(("probe", sd, retries, attempts))
            return
        if isinstance(e, RpcError) and e.remote_type not in (
            "ConnectionError", "RpcConnectionError", "ActorDiedError",
        ):
            # handler-level error: results already stored as error objects
            self._finish(sd)
            return
        self._client = None  # route may be stale (worker died/restarted)
        self._retry_or_fail(sd, retries, attempts + 1, e)

    def _probe(self, sd: Dict[str, Any], retries: int, attempts: int) -> None:
        try:
            self._get_client().call("ping", timeout=5.0)
        except Exception as e:  # noqa: BLE001 - dead/unreachable worker
            self._client = None
            self._retry_or_fail(sd, retries, attempts + 1, e)
            return
        logger.warning(
            "actor call %s exceeded %.0fs; worker alive, re-attaching",
            sd.get("name"), config.actor_call_deadline_s)
        # no attempt consumed: the call is running, we merely re-attach
        # (the worker piggybacks the duplicate push on the live execution)
        self.q.put(("dispatch", sd, retries, attempts))

    def _retry_or_fail(self, sd: Dict[str, Any], retries: int, attempts: int,
                       e: BaseException) -> None:
        if attempts > max(retries, 0):
            self._fail(
                sd,
                f"actor call failed after {attempts} attempts: {e}",
                "ActorDiedError" if isinstance(e, RpcError)
                else "ActorUnavailableError")
            return
        time.sleep(min(0.1 * attempts, 0.5))
        self.q.put(("dispatch", sd, retries, attempts))

    def _fail(self, sd: Dict[str, Any], message: str, error_type: str) -> None:
        self.rt._store_error_objects(sd, message, error_type)
        self._finish(sd)

    def _finish(self, sd: Dict[str, Any]) -> None:
        """Release the in-flight pin exactly once — the unpin rides the SAME
        FIFO refop channel as the pin, so it can never overtake it — then
        unblock get()'s channel wait for these returns."""
        rt = self.rt
        holder = sd.get("task_holder")
        if holder:
            rt._queue_refop("unpin", {
                "holder": holder,
                "object_ids": (sd.get("deps") or []) + (sd.get("returns") or []),
            })
        rt._actor_returns_done(sd)


def connect_driver(address: str, namespace: Optional[str] = None,
                   log_to_driver: bool = True) -> Tuple[ClusterRuntime, Worker]:
    """address = GCS host:port (optionally with a client:// scheme to force
    the proxied data plane). The driver attaches to the head node's agent
    (or the first alive node) as its object/task plane; when the driver is
    on a DIFFERENT machine (no shared /dev/shm) the data plane is proxied
    through the agent via chunked RPCs (the Ray Client tier analogue)."""
    force_client = False
    if address.startswith("client://"):
        force_client = True
        address = address[len("client://"):]
    gcs = SyncRpcClient(address)
    try:
        nodes = [n for n in gcs.call("get_nodes") if n["Alive"]]
        if not nodes:
            raise RuntimeError(f"no alive nodes registered at GCS {address}")
        head = next((n for n in nodes if n.get("is_head")), nodes[0])
        job_n = gcs.call("next_job_id")
    finally:
        gcs.close()
    runtime = ClusterRuntime(
        gcs_address=address,
        agent_address=head["NodeManagerAddress"],
        node_id=NodeID.from_hex(head["NodeID"]),
        is_driver=True,
        namespace=namespace or "default",
    )
    if force_client:
        runtime.remote_data_plane = True
    else:
        # a driver on another machine cannot mmap the agent's shm — flip to
        # the proxied data plane automatically. The probe is FUNCTIONAL for
        # BOTH backends: the agent writes a nonce file into its /dev/shm at
        # startup (agent.rpc_node_info "shm_probe"); only a same-machine
        # driver can read the matching nonce. Hostname comparison is gone —
        # cloned VMs share hostnames without sharing /dev/shm (ADVICE r4).
        try:
            info = runtime.agent.call("node_info", timeout=10.0)
            probe = info.get("shm_probe") or {}
            local = False
            path, nonce = probe.get("path"), probe.get("nonce")
            if path and nonce:
                try:
                    with open(path) as f:
                        local = f.read() == nonce
                except OSError:
                    local = False
            elif "shm_probe" not in info:
                # pre-probe agent (rolling upgrade): fall back to the arena
                # file check, else assume local (the historical default)
                store = info.get("store") or {}
                if store.get("backend") == "arena":
                    from ray_tpu.core.shm_store import arena_path

                    local = os.path.exists(arena_path(runtime.node_hex))
                else:
                    local = True
            runtime.remote_data_plane = not local
        except Exception:  # noqa: BLE001 - probe is best-effort
            pass
    worker = Worker(runtime, JobID.from_int(job_n), node_id=NodeID.from_hex(head["NodeID"]),
                    is_driver=True)
    if log_to_driver:
        runtime.start_log_stream()
    return runtime, worker
