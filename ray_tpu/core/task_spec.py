"""Task and actor specifications — the unit the scheduler and lineage store
operate on (reference capability: src/ray/common/task/task_spec.h and
protobuf/common.proto TaskSpec)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID, WorkerID
from ray_tpu.core.resources import ResourceSet, SchedulingStrategy


class TaskType(Enum):
    NORMAL_TASK = 0
    ACTOR_CREATION_TASK = 1
    ACTOR_TASK = 2
    DRIVER_TASK = 3


@dataclass
class FunctionDescriptor:
    """Identifies user code. In cluster mode the pickled payload is exported
    once to the control-service KV (keyed by function_id) and loaded on demand
    by workers (reference: FunctionManager / fun-table in GCS KV)."""

    module: str
    qualname: str
    function_id: str  # sha1 of the pickled payload
    is_class: bool = False

    @property
    def repr_name(self) -> str:
        return f"{self.module}.{self.qualname}"


@dataclass
class TaskArg:
    """Either an inlined serialized value or an ObjectRef dependency."""

    is_ref: bool
    object_id: Optional[ObjectID] = None
    owner_hint: Optional[str] = None
    value: Any = None  # inlined (already-serialized in cluster mode)


@dataclass
class SchedulingClass:
    """Tasks with equal (resources, strategy, function) share worker leases
    (reference: SchedulingKey in transport/normal_task_submitter.h:53)."""

    resources_key: Tuple[Tuple[str, float], ...]
    strategy_key: str
    function_id: str

    @classmethod
    def of(cls, resources: ResourceSet, strategy: SchedulingStrategy, function_id: str) -> "SchedulingClass":
        return cls(tuple(sorted(resources.items())), repr(strategy), function_id)

    def __hash__(self) -> int:
        return hash((self.resources_key, self.strategy_key, self.function_id))


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    task_type: TaskType
    name: str
    function: FunctionDescriptor
    args: List[TaskArg]
    kwargs: Dict[str, "TaskArg"]
    num_returns: int
    resources: ResourceSet
    strategy: SchedulingStrategy
    # ownership
    owner_worker: Optional[WorkerID] = None
    owner_node: Optional[NodeID] = None
    # fault tolerance
    max_retries: int = 0
    retry_exceptions: bool = False
    # actor fields
    actor_id: Optional[ActorID] = None
    actor_method_name: str = ""
    actor_seq_no: int = -1
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    max_pending_calls: int = -1
    # environment / placement
    runtime_env: Optional[Dict[str, Any]] = None
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    # observability
    submitted_at: float = field(default_factory=time.time)
    # streaming generators (num_returns="streaming"): yield items sealed
    # one at a time; backpressure = max unconsumed items before the producer
    # blocks (0 = unlimited)
    generator: bool = False
    generator_backpressure: int = 0

    def return_ids(self) -> List[ObjectID]:
        return [ObjectID.for_task_return(self.task_id, i + 1) for i in range(self.num_returns)]

    def dependencies(self) -> List[ObjectID]:
        deps = [a.object_id for a in self.args if a.is_ref and a.object_id is not None]
        deps += [a.object_id for a in self.kwargs.values() if a.is_ref and a.object_id is not None]
        return deps

    def scheduling_class(self) -> SchedulingClass:
        return SchedulingClass.of(self.resources, self.strategy, self.function.function_id)
