"""Agent transfer plane: zero-copy pipelined object-byte movement.

Reference capability: src/ray/object_manager/ (object_manager.h:117 —
PullManager/PushManager with 64MB chunks over dedicated transfer streams).
This module owns the agent side of the raw-frame data plane (rpc.py RAW
frames):

- ``TransferManager.pull``: a real PullManager — windowed pipelined chunk
  requests (``transfer_window_chunks`` in flight per source instead of one
  serial await-per-chunk), STRIPED across every GCS-known holder
  (work-stealing: each source's fetchers pop chunk ranges off one shared
  queue, so a fast source naturally carries more), mid-object FAILOVER that
  resumes from the chunks already landed instead of restarting, and a
  global in-flight-bytes budget shared by every transfer on the node.
- ``TransferManager.open_ingest``: the receive side for pushes and
  streaming driver puts — ONE cached ShmWriter per in-flight ingest keyed
  by object id (not one per chunk), chunk payloads received socket->arena
  with no intermediate buffer, sealed + GCS-registered when all bytes land.
- per-transfer stats (bytes/s, stripe sources, stalls, retries, failovers,
  resumes) served through ``rpc_transfer_stats`` and the agent's metrics.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.config import config
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.rpc import RpcConnectionError, RpcError, spawn
from ray_tpu.core.shm_store import ShmWriter
from ray_tpu.utils.logging import get_logger

logger = get_logger("transfer")


def stripe_enabled() -> bool:
    return config.pull_stripe_enabled


def attempt_timeout(attempt: int) -> float:
    """Per-attempt deadline for one chunk transfer: short first (a chaos/
    network-dropped frame costs seconds, not transfer_chunk_timeout_s),
    doubling per retry so a legitimately slow link still gets the full
    window before the chunk fails over."""
    base = max(2.0, 2 * config.rpc_retry_attempt_timeout_s)
    return float(min(config.transfer_chunk_timeout_s,
                     base * (2 ** max(0, attempt))))


class _ByteBudget:
    """Global in-flight transfer byte budget (backpressure): chunk requests
    wait here instead of over-committing memory/network. A single request
    larger than the cap is still admitted when nothing else is in flight."""

    def __init__(self, cap: int):
        self.cap = max(1, int(cap))
        self.used = 0
        self._cond = asyncio.Condition()

    async def acquire(self, n: int) -> bool:
        """Returns True if the acquire had to WAIT (a stall)."""
        stalled = False
        async with self._cond:
            while self.used > 0 and self.used + n > self.cap:
                stalled = True
                await self._cond.wait()
            self.used += n
        return stalled

    async def release(self, n: int) -> None:
        async with self._cond:
            self.used -= n
            self._cond.notify_all()


class _Ingest:
    """One in-flight chunked ingest (push/stream-put receive side): the
    ShmWriter is created ONCE and cached for the whole transfer."""

    __slots__ = ("writer", "total", "done", "is_error", "owner", "contained",
                 "last_active")

    def __init__(self, writer: ShmWriter, total: int):
        self.writer = writer
        self.total = total
        self.done: Dict[int, int] = {}  # offset -> bytes landed there
        self.is_error = False
        self.owner = ""
        self.contained: Optional[List[str]] = None
        self.last_active = time.monotonic()

    def received(self) -> int:
        return sum(self.done.values())


class _PullState:
    """Resumable progress of one in-flight (or interrupted) pull."""

    __slots__ = ("size", "writer", "work", "done_bytes", "fetched_bytes",
                 "meta", "failed_sources", "sources_used", "started",
                 "last_active", "resumed")

    def __init__(self, size: int, writer: ShmWriter, work: "deque"):
        self.size = size
        self.writer = writer
        self.work = work                 # deque[(offset, length)] still needed
        self.done_bytes = 0
        self.fetched_bytes = 0           # includes re-fetched tails
        self.meta: Optional[Dict[str, Any]] = None
        self.failed_sources: set = set()
        self.sources_used: set = set()
        self.started = time.monotonic()
        self.last_active = time.monotonic()
        self.resumed = False


class _RegistrationBatcher:
    """Coalesces GCS registrations of pulled/ingested objects into batched
    ``register_objects`` RPCs (one per ``transfer_register_batch_ms``
    window). A shuffle reduce landing its N-block partition set registers
    the whole set in one control frame instead of N round trips. Callers
    still await completion — semantics match the per-object RPC exactly,
    only the framing is shared."""

    def __init__(self, agent) -> None:
        self.agent = agent
        self._pending: List[Dict[str, Any]] = []
        self._waiters: List[asyncio.Future] = []
        self._wake: Optional[asyncio.Event] = None
        self._drainer: Optional[asyncio.Task] = None
        self.batches_sent = 0

    async def register(self, **reg: Any) -> None:
        fut = asyncio.get_event_loop().create_future()
        self._pending.append(reg)
        self._waiters.append(fut)
        # ONE persistent drainer per agent, started lazily and never exited:
        # a spawn-per-batch flusher has an orphan window (a registration
        # landing while the previous batch's GCS call is in flight would
        # wait for a flush nobody schedules, wedging its pull forever)
        if self._wake is None:
            self._wake = asyncio.Event()
        if self._drainer is None or self._drainer.done():
            self._drainer = spawn(self._drain_loop())
        self._wake.set()
        await fut

    async def _drain_loop(self) -> None:
        while True:
            await self._wake.wait()
            self._wake.clear()
            await asyncio.sleep(
                max(0.0, config.transfer_register_batch_ms / 1000.0))
            pending, waiters = self._pending, self._waiters
            self._pending, self._waiters = [], []
            if not pending:
                continue
            self.batches_sent += 1
            parked_until: Optional[float] = None
            while True:
                try:
                    await self.agent.gcs.call("register_objects", regs=pending)
                    for fut in waiters:
                        if not fut.done():
                            fut.set_result(True)
                    break
                except (RpcConnectionError, TimeoutError) as e:
                    # GCS restarted mid-drain: PARK the batch and re-send
                    # against the new incarnation instead of failing every
                    # waiter's pull/ingest (register_objects is idempotent,
                    # so an ambiguous timeout re-send is harmless)
                    from ray_tpu.core.config import gcs_recovery_enabled

                    if not gcs_recovery_enabled():
                        self._fail_waiters(waiters, e)
                        break
                    now = time.monotonic()
                    if parked_until is None:
                        parked_until = now + config.recovery_park_timeout_s
                        logger.warning(
                            "transfer registration batch parked across GCS "
                            "outage (%d objects)", len(pending))
                    if now >= parked_until:
                        self._fail_waiters(waiters, e)
                        break
                    await asyncio.sleep(0.2)
                except BaseException as e:  # noqa: BLE001 - fan the failure out
                    self._fail_waiters(waiters, e)
                    break

    @staticmethod
    def _fail_waiters(waiters: List[asyncio.Future], e: BaseException) -> None:
        for fut in waiters:
            if not fut.done():
                fut.set_exception(e)


class TransferManager:
    def __init__(self, agent) -> None:
        self.agent = agent
        self.budget = _ByteBudget(config.transfer_inflight_max_bytes)
        self._ingests: Dict[str, _Ingest] = {}
        self._progress: Dict[str, _PullState] = {}
        self._registrar = _RegistrationBatcher(agent)
        self.stats: Dict[str, Any] = {
            "pulls": 0, "pull_bytes": 0, "pull_failovers": 0,
            "pull_retries": 0, "pull_resumes": 0, "stripe_pulls": 0,
            "stalls": 0, "ingests": 0, "ingest_bytes": 0,
            "chunks_out": 0, "bytes_out": 0,
            "last_pull": {},
        }

    # ------------------------------------------------------------ pull side
    async def pull(self, oid: ObjectID, size: int, locations: List[str],
                   owner_hint: str = "") -> Optional[Dict[str, Any]]:
        """Materialize the object locally by striped, windowed chunk pulls.
        Returns the piggybacked metadata dict ({} if none) on success, None
        on failure (progress is KEPT for a later resume). Callers serialize
        per object via the agent's pull lock."""
        agent = self.agent
        object_id = oid.hex()
        self._sweep_stale()
        st = self._progress.get(object_id)
        if st is not None and st.size != size:
            self._drop_progress(object_id, abort=True)
            st = None
        if st is None:
            state = agent._reserve_idempotent(oid, size)
            if state == "sealed":
                return {}
            arena_off = agent.store.offset(oid)
            try:
                writer = ShmWriter(oid, size, agent.hex, offset=arena_off)
            except FileNotFoundError:
                agent.store.abort(oid)
                return None
            chunk = max(64 * 1024, int(config.fetch_chunk_bytes))
            work = deque((off, min(chunk, size - off), 0)
                         for off in range(0, size, chunk))
            if not work:
                work.append((0, 0, 0))  # zero-size: one empty chunk (meta)
            st = _PullState(size, writer, work)
            self._progress[object_id] = st
        else:
            st.resumed = True
            st.failed_sources.clear()  # a new attempt may retry old sources
            self.stats["pull_resumes"] += 1
        ok = await self._run_pull(object_id, st, locations)
        if not ok:
            st.last_active = time.monotonic()
            return None  # progress retained: the next attempt resumes
        try:
            st.writer.seal()
            agent.store.seal(oid)
        except FileNotFoundError:
            self._drop_progress(object_id, abort=True)
            return None
        meta = st.meta or {}
        owner = meta.get("owner") or owner_hint or ""
        contained = meta.get("contained") or None
        if meta.get("is_error"):
            agent.error_objects.add(object_id)
        agent._remember_meta(object_id, owner, contained)
        # the meta rode the first chunk reply, so the pull costs exactly its
        # data frames — no post-transfer object_info round trip; the
        # registration itself coalesces with sibling pulls into one batched
        # RPC (partition-set pulls register as a set)
        await self._registrar.register(
            object_id=object_id, size=size,
            node_id=agent.hex, owner=owner, contained=contained,
        )
        dt = max(1e-9, time.monotonic() - st.started)
        self.stats["pulls"] += 1
        self.stats["pull_bytes"] += size
        if len(st.sources_used) > 1:
            self.stats["stripe_pulls"] += 1
        self.stats["last_pull"] = {
            "object": object_id[:16], "bytes": size,
            "seconds": round(dt, 4), "mbps": round(size / dt / 1e6, 2),
            "sources": sorted(s[:8] for s in st.sources_used),
            "resumed": st.resumed,
            "refetched_bytes": max(0, st.fetched_bytes - size),
        }
        self._drop_progress(object_id, abort=False)
        return meta

    async def _run_pull(self, object_id: str, st: _PullState,
                        locations: List[str]) -> bool:
        """Rounds of striped fetching until the work queue drains or no
        sources remain. Each round fans ``transfer_window_chunks`` fetchers
        out per source, all popping the shared queue."""
        agent = self.agent
        sources = [n for n in locations
                   if n != agent.hex and n not in st.failed_sources]
        for _round in range(max(3, config.object_transfer_retries)):
            if not st.work and not self._missing(st):
                return True
            if not sources:
                sources = await self._refresh_sources(object_id, st)
                if not sources:
                    return False
            if not stripe_enabled():
                active = sources[:1]
            else:
                active = sources[:max(1, int(config.transfer_max_sources))]
            window = max(1, int(config.transfer_window_chunks))
            before = st.done_bytes
            await asyncio.gather(*(
                self._source_worker(object_id, st, node, window)
                for node in active
            ))
            sources = [n for n in sources if n not in st.failed_sources]
            if not st.work and not self._missing(st):
                return True
            if st.done_bytes == before and not sources:
                # zero progress and every source burned: refresh or give up
                sources = await self._refresh_sources(object_id, st)
                if not sources:
                    return False
        return not st.work and not self._missing(st)

    @staticmethod
    def _missing(st: _PullState) -> bool:
        return st.done_bytes < st.size

    async def _refresh_sources(self, object_id: str,
                               st: _PullState) -> List[str]:
        """Mid-pull holder refresh from the GCS (failover beyond the holder
        list the pull started with — e.g. a broadcast landed new replicas)."""
        try:
            rec = await self.agent.gcs.call("lookup_object",
                                            object_id=object_id, timeout=10.0)
        except (RpcError, RpcConnectionError, TimeoutError, OSError):
            return []
        if not rec or not rec.get("locations"):
            return []
        return [n for n in rec["locations"]
                if n != self.agent.hex and n not in st.failed_sources]

    async def _source_worker(self, object_id: str, st: _PullState,
                             node_id: str, window: int) -> None:
        client = await self.agent._transfer_peer(node_id)
        if client is None:
            st.failed_sources.add(node_id)
            return
        dead = [False]  # shared flag: first fetcher failure stops siblings
        await asyncio.gather(*(
            self._fetcher(object_id, st, node_id, client, dead)
            for _ in range(window)
        ))

    async def _fetcher(self, object_id: str, st: _PullState, node_id: str,
                       client, dead: List[bool]) -> None:
        while st.work and not dead[0]:
            off, ln, attempts = st.work.popleft()
            want_meta = st.meta is None
            if await self.budget.acquire(ln):
                self.stats["stalls"] += 1
            try:
                res = await client.call_raw(
                    "read_chunk_raw",
                    self._make_sink(st, off, ln),
                    timeout=attempt_timeout(attempts),
                    object_id=object_id, offset=off, length=ln,
                    want_meta=want_meta,
                )
            except TimeoutError:
                # likely a dropped frame, not a dead source: re-request with
                # a doubled window (any source may pick it up) before giving
                # up on this source
                self.stats["pull_retries"] += 1
                st.work.append((off, ln, attempts + 1))
                if attempts + 1 >= 3 and not dead[0]:
                    dead[0] = True
                    st.failed_sources.add(node_id)
                    self.stats["pull_failovers"] += 1
                    logger.warning(
                        "pull of %s: source %s timed out repeatedly; "
                        "failing over with %d/%d bytes landed",
                        object_id[:16], node_id[:8], st.done_bytes, st.size)
                    return
                continue
            except (RpcError, RpcConnectionError, OSError) as e:
                # this source is out (died, or evicted the object): hand the
                # chunk back and fail over — chunks already landed are NEVER
                # re-fetched
                st.work.appendleft((off, ln, 0))
                if not dead[0]:
                    dead[0] = True
                    st.failed_sources.add(node_id)
                    self.stats["pull_failovers"] += 1
                    logger.warning("pull of %s: source %s failed mid-object "
                                   "(%s); failing over with %d/%d bytes "
                                   "landed", object_id[:16], node_id[:8], e,
                                   st.done_bytes, st.size)
                return
            finally:
                await self.budget.release(ln)
            got = int(res.get("nbytes", 0))
            meta = res.get("meta") or {}
            if st.meta is None and meta.get("has_meta"):
                st.meta = meta
            st.sources_used.add(node_id)
            st.done_bytes += got
            st.fetched_bytes += got
            st.last_active = time.monotonic()
            if got < ln:
                # short chunk (chaos truncation / bounded sender): resume
                # from the exact received offset, possibly on another source
                self.stats["pull_retries"] += 1
                st.work.append((off + got, ln - got, 0))

    def _make_sink(self, st: _PullState, off: int, ln: int):
        writer = st.writer

        def sink(meta, nbytes: int) -> Optional[memoryview]:
            if nbytes == 0 or nbytes > ln:
                return None  # empty or protocol violation: drain
            try:
                return writer.buffer[off:off + nbytes]
            except FileNotFoundError:
                return None  # reservation aborted under us: discard

        return sink

    def _drop_progress(self, object_id: str, abort: bool) -> None:
        st = self._progress.pop(object_id, None)
        if st is not None and abort:
            try:
                self.agent.store.abort(ObjectID.from_hex(object_id))
            except Exception:  # noqa: BLE001
                pass

    # ---------------------------------------------------------- ingest side
    async def open_ingest(self, payload_len: int = 0, object_id: str = "",
                          total_size: int = 0, offset: int = 0,
                          is_error: bool = False, owner: str = "",
                          contained: Optional[List[str]] = None) -> Tuple:
        """Raw-frame ingest handler (rpc.register_raw contract): returns
        (sink, finish). The ShmWriter is cached per in-flight object — the
        old path built a fresh writer (attach + validate) for EVERY chunk."""
        agent = self.agent
        oid = ObjectID.from_hex(object_id)
        self._sweep_stale()
        if agent.store.contains(oid):
            return None, self._finish_const({"ok": True, "existing": "sealed"})
        ing = self._ingests.get(object_id)
        if ing is None:
            state = agent._reserve_idempotent(oid, total_size)
            if state == "sealed":
                return None, self._finish_const(
                    {"ok": True, "existing": "sealed"})
            arena_off = agent.store.offset(oid)
            if arena_off is None and agent.store.backend == "arena":
                raise KeyError(f"arena slot for {object_id[:16]} lost mid-push")
            writer = ShmWriter(oid, total_size, agent.hex, offset=arena_off)
            ing = _Ingest(writer, total_size)
            if offset > 0:
                if state == "reserved":
                    # continuation of an ingest whose cached state was lost
                    # (agent restart in-place / sweep) onto a surviving
                    # reservation: the pusher streams in order, so bytes
                    # before `offset` already landed
                    ing.done[0] = offset
                else:
                    # fresh reservation mid-stream: earlier bytes are GONE —
                    # fail loudly, never seal a hole-y object
                    agent.store.abort(oid)
                    raise KeyError(
                        f"ingest state for {object_id[:16]} vanished mid-push")
            self._ingests[object_id] = ing
            self.stats["ingests"] += 1
        if ing.total != total_size:
            raise KeyError(f"size mismatch mid-push for {object_id[:16]}")
        if is_error:
            ing.is_error = True
        if owner:
            ing.owner = owner
        if contained:
            ing.contained = list(contained)
        ing.last_active = time.monotonic()
        sink = ing.writer.buffer[offset:offset + payload_len] \
            if payload_len else None

        async def finish(nbytes: int) -> Dict[str, Any]:
            ing.done[offset] = max(ing.done.get(offset, 0), int(nbytes))
            ing.last_active = time.monotonic()
            self.stats["ingest_bytes"] += int(nbytes)
            if ing.received() >= ing.total:
                return await self._seal_ingest(object_id, ing)
            return {"ok": True}

        return sink, finish

    @staticmethod
    def _finish_const(result: Dict[str, Any]):
        async def finish(_nbytes: int) -> Dict[str, Any]:
            return result

        return finish

    async def _seal_ingest(self, object_id: str, ing: _Ingest) -> Dict[str, Any]:
        agent = self.agent
        oid = ObjectID.from_hex(object_id)
        ing.writer.seal()
        agent.store.seal(oid)
        self._ingests.pop(object_id, None)
        if ing.is_error:
            agent.error_objects.add(object_id)
        agent._remember_meta(object_id, ing.owner, ing.contained)
        await self._registrar.register(
            object_id=object_id, size=ing.total,
            node_id=agent.hex, owner=ing.owner,
            contained=ing.contained or None,
        )
        return {"ok": True, "complete": True}

    # ------------------------------------------------------------- plumbing
    def _sweep_stale(self) -> None:
        """Abort ingests/pull progress idle past the deadline (dead pusher /
        abandoned pull): their reservations would otherwise pin arena bytes
        forever."""
        idle = max(1.0, config.transfer_ingest_idle_s)
        now = time.monotonic()
        for object_id, ing in list(self._ingests.items()):
            if now - ing.last_active > idle:
                self._ingests.pop(object_id, None)
                try:
                    self.agent.store.abort(ObjectID.from_hex(object_id))
                except Exception:  # noqa: BLE001
                    pass
                logger.warning("swept stale ingest of %s (%d/%d bytes)",
                               object_id[:16], ing.received(), ing.total)
        for object_id, st in list(self._progress.items()):
            if now - st.last_active > idle:
                self._drop_progress(object_id, abort=True)
                logger.warning("swept stale pull progress of %s",
                               object_id[:16])

    def snapshot(self) -> Dict[str, Any]:
        out = dict(self.stats)
        out["inflight_bytes"] = self.budget.used
        out["open_ingests"] = len(self._ingests)
        out["partial_pulls"] = len(self._progress)
        out["register_batches"] = self._registrar.batches_sent
        return out
