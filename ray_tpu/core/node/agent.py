"""Node agent — the raylet equivalent.

Reference capability: src/ray/raylet/ (NodeManager node_manager.cc worker
leasing + dependency pulling + object pinning, WorkerPool worker_pool.h:174,
LocalObjectManager spilling, ObjectManager push/pull object_manager.h:117).
One asyncio process per node:

- registers the node (+TPU slice labels) with the GCS, heartbeats available
  resources;
- supervises a pool of worker processes (spawned on demand up to the CPU
  count, reused across leases, keyed by runtime env hash);
- dispatches tasks: placement via batched GCS scheduling, dependency
  ensure-local (chunked pulls from peer agents), worker lease, direct push
  to the worker; retries on worker death; failure results become error
  objects so ``get()`` raises exactly like the local runtime;
- hosts the node's shared-memory object store lifecycle (create/seal/pull/
  restore/delete) and serves chunked reads to peer agents;
- starts actors on leased-for-life workers and reports their direct RPC
  address to the GCS actor directory.
"""

from __future__ import annotations

import asyncio
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu.core.config import config, gcs_recovery_enabled, raw_transfer_enabled
from ray_tpu.core.ids import NodeID, ObjectID
from ray_tpu.core.node.transfer import TransferManager
from ray_tpu.core.rpc import (RawResult, RpcClient, RpcConnectionError,
                              RpcError, RpcServer, loop_lag_watchdog, spawn)
from ray_tpu.core.shm_store import ShmObjectStore, ShmReader, ShmWriter
from ray_tpu.utils.logging import get_logger

logger = get_logger("node_agent")


def _gauge(name: str, desc: str):
    """Get-or-create a gauge with tag support (idempotent registration)."""
    from ray_tpu.utils import metrics

    g = metrics.registry.get(name)
    if g is None:
        g = metrics.Gauge(name, desc, tag_keys=("resource",))
    return g


class _WorkerHandle:
    def __init__(self, proc: subprocess.Popen, worker_id: str):
        self.proc = proc
        self.worker_id = worker_id
        self.address: Optional[str] = None
        self.client: Optional[RpcClient] = None
        self.state = "STARTING"  # STARTING | IDLE | LEASED | ACTOR | DEAD
        self.actor_id: Optional[str] = None
        self.client_holder: Optional[str] = None  # GCS ref-holder id of the process
        self.ready = asyncio.Event()
        self.lease_token: Optional[Tuple[str, Any, Dict[str, float]]] = None
        self._actor_token: Optional[Tuple[str, Any, Dict[str, float]]] = None
        self.blocked = False
        self.tpu_chips: Optional[Tuple[int, ...]] = None  # dedicated chip subset
        self.env_hash: str = ""          # runtime-env pool key
        self.staged_cwd: Optional[str] = None
        # task currently executing on this worker (OOM kill-policy input)
        self.running_task: Optional[Dict[str, Any]] = None
        self.task_started_at: float = 0.0


class NodeAgent:
    def __init__(
        self,
        gcs_address: str,
        host: str = "127.0.0.1",
        port: int = 0,
        num_cpus: Optional[int] = None,
        num_tpus: int = 0,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        is_head: bool = False,
        session_dir: Optional[str] = None,
        object_store_memory: Optional[int] = None,
    ):
        self.node_id = NodeID.from_random()
        self.hex = self.node_id.hex()
        self.gcs_address = gcs_address
        self.rpc = RpcServer(host, port)
        self.rpc.register_object(self)
        self.is_head = is_head
        from ray_tpu.core import accelerators

        ncpus = num_cpus if num_cpus is not None else (os.cpu_count() or 1)
        self.total_resources: Dict[str, float] = {"CPU": float(ncpus), **(resources or {})}
        # TPU slice/pod model: explicit num_tpus wins; otherwise auto-detect
        # chips + slice-head resource + topology labels (accelerators.py)
        if num_tpus:
            self.total_resources["TPU"] = float(num_tpus)
        else:
            self.total_resources.update(accelerators.node_tpu_resources())
        self._total_chips = int(self.total_resources.get("TPU", 0))
        self._free_chips: List[int] = list(range(self._total_chips))
        # chip-set tuple -> idle dedicated TPU workers (libtpu stays warm)
        self._tpu_idle: Dict[Tuple[int, ...], List[_WorkerHandle]] = {}
        self.total_resources[f"node:{self.hex}"] = 1.0
        self.available: Dict[str, float] = dict(self.total_resources)
        self.labels = {**accelerators.node_tpu_labels(), **(labels or {})}
        self.session_dir = session_dir or f"/tmp/ray_tpu/{os.getpid()}"
        os.makedirs(self.session_dir, exist_ok=True)
        self.store = ShmObjectStore(
            self.hex,
            capacity_bytes=object_store_memory,
            spill_dir=os.path.join(self.session_dir, "spill", self.hex[:8]),
        )
        # shm-locality nonce (rpc_node_info "shm_probe"): proves a client is
        # on THIS machine regardless of hostname collisions across clones
        import uuid as _uuid

        self._shm_probe_nonce = _uuid.uuid4().hex
        self._shm_probe_path = f"/dev/shm/rtpu-probe-{self.hex[:16]}"
        try:
            with open(self._shm_probe_path, "w") as f:
                f.write(self._shm_probe_nonce)
        except OSError:  # no usable /dev/shm: direct plane impossible anyway
            self._shm_probe_path = ""
        # object_id hex -> error flag (mirror of GCS metadata for local objs)
        self.error_objects: Set[str] = set()
        # object_id hex -> (owner, contained): sealed-object metadata kept so
        # a peer's pull gets it piggybacked on the first chunk reply instead
        # of paying a post-transfer object_info/GCS round trip (bounded FIFO)
        from collections import OrderedDict as _OD

        self._object_meta: "_OD[str, Tuple[str, Optional[List[str]]]]" = _OD()
        # raw-frame transfer plane: pull manager + chunked-ingest writer
        # cache + per-transfer stats (reference: ObjectManager pull/push)
        self.transfer = TransferManager(self)
        self.rpc.register_raw("receive_chunk_raw", self.transfer.open_ingest)
        self.gcs: Optional[RpcClient] = None
        self._workers: Dict[str, _WorkerHandle] = {}
        # idle task-pool workers, keyed by runtime-env hash ("" = plain):
        # envs never share worker processes (reference: pool env isolation)
        self._idle_workers: Dict[str, List[_WorkerHandle]] = {}
        # env-hash -> event set whenever a worker of that env becomes IDLE;
        # _lease_worker blocks on this instead of a fixed-interval poll
        self._worker_free_events: Dict[str, asyncio.Event] = {}
        # FIFO of local-queue waiters; each resource release wakes exactly ONE
        # (a broadcast event here stampedes the loop: hundreds of queued
        # dispatches all waking per task completion)
        from collections import deque as _deque

        self._local_wait_q: "_deque[asyncio.Future]" = _deque()
        self._local_waiters = 0  # LIVE waiters (deque may hold stale futures)
        self._memory_task: Optional[asyncio.Task] = None
        self._log_monitor_task: Optional[asyncio.Task] = None
        # task_id -> OOM kill message: lets the dispatch path distinguish an
        # intentional memory-monitor kill from a plain worker crash
        self._oom_kills: Dict[str, str] = {}
        # worker_id -> last-seen absolute Arrow decode counters from run_task
        # replies (columnar exchange); node_info sums them so the shuffle
        # coordinator can diff zero-copy vs copied bytes per exchange
        self._worker_decode: Dict[str, Dict[str, int]] = {}
        # GCS write batching: submit-time pins and seal-time registrations
        # coalesce into one RPC per tick each, taking two GCS round trips off
        # every task's critical path (reference: batched location/ref flushes
        # in the ownership protocol)
        self._pin_queue: List[Tuple[Dict[str, Any], asyncio.Future]] = []
        self._pin_event = asyncio.Event()
        self._pin_flusher: Optional[asyncio.Task] = None
        self._reg_queue: List[Dict[str, Any]] = []
        self._reg_event = asyncio.Event()
        self._reg_flusher: Optional[asyncio.Task] = None
        # task-pin releases coalesce the same way (one unpin_tasks RPC per
        # tick instead of one remove_object_refs round trip per finished
        # task — the last per-task GCS RPC on the agent's hot path)
        self._unpin_queue: List[Dict[str, Any]] = []
        self._unpin_event = asyncio.Event()
        self._unpin_flusher: Optional[asyncio.Task] = None
        self._peer_clients: Dict[str, RpcClient] = {}
        # dedicated bulk-transfer connections per peer: multi-MB chunk
        # payloads must not head-of-line-block control RPCs sharing a socket
        self._transfer_clients: Dict[str, RpcClient] = {}
        self._peer_addr_cache: Dict[str, str] = {}
        self._hb_task: Optional[asyncio.Task] = None
        self._hb_client: Optional[RpcClient] = None  # dedicated heartbeat conn
        # delta-sync state: version of the current view, whether the full
        # payload must ride the next tick, and the last view sent
        self._hb_version = 0
        self._hb_full_pending = True
        self._hb_last_view: Optional[tuple] = None
        self._supervise_task: Optional[asyncio.Task] = None
        # GCS crash-restart recovery (core/recovery/resync.py): last epoch
        # observed on a heartbeat ack; a bump means a new GCS incarnation and
        # triggers a full re-registration of node/objects/actors/pins
        self._last_gcs_epoch: Optional[int] = None
        self._resync_task: Optional[asyncio.Task] = None
        self._resync_rerun = False
        self._resyncs = 0
        # task_holder -> pin kwargs of tasks still in flight on this node;
        # the resync re-asserts these leases so a restarted GCS can't reap
        # in-progress returns that were pinned after its last snapshot
        self._active_pins: Dict[str, Dict[str, Any]] = {}
        self._pull_locks: Dict[str, asyncio.Lock] = {}
        self._recon_locks: Dict[str, asyncio.Lock] = {}
        self._recon_attempts: Dict[str, int] = {}
        from collections import OrderedDict

        # task_id -> accept time: dedupes retried submit_task RPCs
        self._accepted_tasks: "OrderedDict[str, float]" = OrderedDict()
        # coalescing queue for GCS placement requests (one RPC per tick)
        self._sched_queue: List[Tuple[Dict[str, Any], asyncio.Future]] = []
        self._sched_drainer: Optional[asyncio.Task] = None
        # task_id -> lifecycle state (observability; state API reads this)
        self._task_states: Dict[str, str] = {}
        self._profile_events: List[Dict[str, Any]] = []
        # task_id -> [(wall_ts, state), ...] transition log (timeline source;
        # reference capability: core_worker/profile_event.h -> GcsTaskManager
        # -> `ray timeline` chrome trace)
        self._task_events: Dict[str, List[Tuple[float, str]]] = {}
        # job_id -> {proc, log, entrypoint, started} (job supervisor)
        self._jobs: Dict[str, Dict[str, Any]] = {}
        # task_id -> when it first became cluster-infeasible (grace window
        # lets the autoscaler add capacity before the task errors)
        self._infeasible_since: Dict[str, float] = {}
        # in-flight local dispatches (queued-or-running): heartbeated to the
        # GCS so the autoscaler never scales away a node with assigned work
        self._active_dispatches = 0
        # task_id -> first time its dispatch target was unreachable
        self._unreachable_since: Dict[str, float] = {}
        self._max_workers = max(1, int(ncpus))
        self.dashboard = None  # DashboardHead on the head node
        self._shutting_down = False
        # committed placement-group bundle reservations living on THIS node:
        # (pg_id, bundle_index) -> {"total": resources, "avail": remaining}.
        # Reserved out of self.available at prepare time so heartbeats report
        # the reduced capacity and unrelated tasks can't consume a gang's
        # resources (reference: raylet prepared/committed bundle state).
        self._pg_bundles: Dict[Tuple[str, int], Dict[str, Dict[str, float]]] = {}

    # ------------------------------------------------------------- lifecycle
    async def start(self) -> Tuple[str, int]:
        host, port = await self.rpc.start()
        self.gcs = await RpcClient(self.gcs_address).connect()
        resp = await self.gcs.call(
            "register_node",
            node_id=self.hex,
            address=self.rpc.address,
            resources=self.total_resources,
            labels=self.labels,
            is_head=self.is_head,
        )
        if isinstance(resp, dict):
            self._last_gcs_epoch = resp.get("gcs_epoch")
        await self.gcs.subscribe("nodes", self._on_node_event)
        self._hb_task = spawn(self._heartbeat_loop())
        self._supervise_task = spawn(self._supervise_loop())
        if config.log_to_driver:
            self._log_monitor_task = spawn(self._log_monitor_loop())
        if config.memory_monitor_refresh_ms > 0:
            self._memory_task = spawn(self._memory_monitor_loop())
        self._pin_flusher = spawn(self._pin_flush_loop())
        self._reg_flusher = spawn(self._reg_flush_loop())
        self._unpin_flusher = spawn(self._unpin_flush_loop())
        self._watchdog_task = spawn(loop_lag_watchdog("agent"))
        if self.is_head and config.dashboard_port >= 0:
            from ray_tpu.dashboard.head import DashboardHead

            self.dashboard = DashboardHead(
                self, host=config.dashboard_host, port=config.dashboard_port
            )
            try:
                addr = await self.dashboard.start()
                await self.gcs.call("kv_put", key="dashboard:address",
                                    value=addr.encode())
            except Exception:  # noqa: BLE001 - observability must not block boot
                logger.exception("dashboard failed to start")
                if self.dashboard is not None:
                    try:  # kv_put may have failed AFTER the server came up
                        await self.dashboard.stop()
                    except Exception:  # noqa: BLE001
                        pass
                    self.dashboard = None
        logger.info("node agent %s listening on %s", self.hex[:8], self.rpc.address)
        return host, port

    async def stop(self) -> None:
        self._shutting_down = True
        if self.dashboard is not None:
            await self.dashboard.stop()
        for t in (self._hb_task, self._supervise_task, self._memory_task,
                  self._pin_flusher, self._reg_flusher, self._unpin_flusher,
                  self._log_monitor_task, self._resync_task,
                  getattr(self, "_watchdog_task", None)):
            if t:
                t.cancel()
        if self._hb_client is not None:
            try:
                await self._hb_client.close()
            except Exception:  # noqa: BLE001
                pass
        for w in self._workers.values():
            try:
                w.proc.terminate()
            except Exception:
                pass
        self.store.cleanup()
        await self.rpc.stop()

    def _on_node_event(self, event: Dict[str, Any]) -> None:
        if event.get("event") == "dead":
            node_id = event.get("node_id", "")
            self._peer_addr_cache.pop(node_id, None)
            for pool in (self._peer_clients, self._transfer_clients):
                client = pool.pop(node_id, None)
                if client is not None:
                    spawn(client.close())

    async def _log_monitor_loop(self) -> None:
        """Tail this node's worker logs and push NEW lines to the GCS
        "worker_logs" pubsub channel, where connected drivers print them
        (reference: _private/log_monitor.py:103 — per-node log monitor
        publishing to the driver's stdout). Only growth after tail start
        ships; batches are capped so one chatty worker can't flood a tick.
        NOTE: fan-out is cluster-wide — every connected driver mirrors
        every worker's output; per-job filtering (the reference scopes
        lines by owning job) needs a worker->job registry and is a
        roadmap item. Opt out per driver with init(log_to_driver=False)
        or cluster-wide with config log_to_driver=false."""
        import glob as _glob

        window = 64 * 1024
        max_lines = 200
        # content existing at monitor START predates the tail: skip it.
        # Priming here (not lazily inside the tick) keeps the semantics
        # stable even if the first ticks fail on a GCS hiccup — files
        # appearing later always tail from 0.
        offsets: Dict[str, int] = {}
        for path in _glob.glob(os.path.join(self.session_dir, "worker-*.log")):
            try:
                offsets[path] = os.path.getsize(path)
            except OSError:
                pass
        while True:
            try:
                paths = set(_glob.glob(os.path.join(self.session_dir,
                                                    "worker-*.log")))
                for gone in set(offsets) - paths:
                    del offsets[gone]  # dead worker's file removed
                for path in sorted(paths):
                    try:
                        size = os.path.getsize(path)
                    except OSError:
                        continue
                    prev = offsets.get(path)
                    if prev is None:
                        prev = offsets[path] = 0  # new file: tail from start
                    if size <= prev:
                        continue
                    with open(path, "rb") as f:
                        f.seek(prev)
                        chunk = f.read(min(size - prev, window))
                    cut = chunk.rfind(b"\n")
                    if cut < 0:
                        if len(chunk) < window:
                            continue  # incomplete tail: wait for the newline
                        # one line bigger than the window: ship truncated and
                        # move on — never wedge this file's tail forever
                        raw = [chunk]
                        suffix = " ...[line truncated]"
                        new_off = prev + len(chunk)
                    else:
                        # split on the SAME delimiter the offset math uses
                        # (splitlines() also breaks on \r/\x85 and would
                        # desynchronize count vs byte position)
                        raw = chunk[:cut].split(b"\n")
                        suffix = ""
                        if len(raw) > max_lines:
                            raw = raw[:max_lines]
                            new_off = prev + sum(len(l) + 1 for l in raw)
                        else:
                            new_off = prev + cut + 1
                    lines = [l.decode("utf-8", "replace") + suffix for l in raw]
                    worker = os.path.basename(path)[len("worker-"):-len(".log")]
                    # publish BEFORE advancing: a failed publish re-sends the
                    # batch next tick instead of dropping it; seq (= the
                    # pre-batch offset) lets the GCS drop the duplicate when
                    # only the REPLY was lost, so drivers see each line once
                    await self.gcs.call(
                        "publish_worker_logs", node_id=self.hex[:8],
                        worker_id=worker, lines=lines, seq=prev, timeout=5.0,
                    )
                    offsets[path] = new_off
            except (RpcConnectionError, RpcError, TimeoutError, OSError):
                pass  # GCS hiccup: batch re-sends next tick
            except Exception:  # noqa: BLE001 - the tailer must survive
                logger.exception("log monitor tick failed")
            await asyncio.sleep(config.log_monitor_interval_s)

    async def _heartbeat_loop(self) -> None:
        period = config.health_check_period_ms / 1000.0
        # Dedicated connection: heartbeats must not queue behind bursty
        # control traffic (batched pins/registers/long-polls share the main
        # client's socket and send lock) — a busy node is not a dead node.
        while True:
            await asyncio.sleep(period)
            # the heartbeat tick doubles as the MAIN client's repairman: no
            # other path reconnects it after a breakage (long-poll handlers
            # would otherwise error-loop forever on a closed client)
            if self.gcs is not None and self.gcs._closed:  # noqa: SLF001
                try:
                    await self._reconnect_gcs()
                except Exception:  # noqa: BLE001
                    logger.warning("GCS main-client reconnect failed")
            try:
                if self._hb_client is None or self._hb_client._closed:  # noqa: SLF001
                    self._hb_client = await RpcClient(self.gcs_address).connect(timeout=2.0)
                # versioned delta sync (reference: ray_syncer.h): the full
                # resource/load view rides only when it CHANGED since the
                # last ack'd send; steady-state ticks are ~40-byte pings
                view = (dict(self.available),
                        {"dispatching": self._active_dispatches})
                if view != self._hb_last_view:
                    self._hb_version += 1
                    self._hb_last_view = view
                    self._hb_full_pending = True
                kwargs: Dict[str, Any] = {"node_id": self.hex,
                                          "version": self._hb_version}
                if self._hb_full_pending:
                    kwargs["available"] = view[0]
                    kwargs["load"] = view[1]
                ok = await self._hb_client.call(
                    "heartbeat",
                    timeout=period * config.health_check_failure_threshold,
                    **kwargs,
                )
                if ok is False:
                    # restarted GCS with no (or a pre-us) snapshot: it lost
                    # this node entirely — full re-registration, not just
                    # register_node (our objects/actors/pins are gone too)
                    if gcs_recovery_enabled():
                        from ray_tpu.core.recovery import trigger_resync

                        trigger_resync(self, "heartbeat rejected: GCS lost "
                                             "this node")
                    else:
                        await self.gcs.call(
                            "register_node",
                            node_id=self.hex,
                            address=self.rpc.address,
                            resources=self.total_resources,
                            labels=self.labels,
                            is_head=self.is_head,
                        )
                    self._hb_full_pending = True  # fresh GCS: resend view
                elif isinstance(ok, dict) and ok.get("resync"):
                    self._hb_full_pending = True  # GCS lost our version
                else:
                    self._hb_full_pending = False
                if isinstance(ok, dict):
                    epoch = ok.get("epoch")
                    if (epoch is not None and gcs_recovery_enabled()
                            and self._last_gcs_epoch is not None
                            and epoch != self._last_gcs_epoch):
                        from ray_tpu.core.recovery import trigger_resync

                        self._last_gcs_epoch = epoch
                        trigger_resync(
                            self, f"GCS epoch bumped to {epoch}")
                    elif epoch is not None:
                        self._last_gcs_epoch = epoch
            except (RpcConnectionError, TimeoutError):
                logger.warning("heartbeat to GCS failed")
                self._hb_full_pending = True
                await self._reconnect_gcs()

    async def _reconnect_gcs(self) -> None:
        """GCS restarted (or the connection broke): rebuild the client and
        re-subscribe — with persistence the new GCS resumes from its snapshot
        and this agent re-appears via the next heartbeat/register
        (reference: raylet GCS reconnect, node_manager.cc:1181)."""
        if self.gcs is not None and not self.gcs._closed:  # noqa: SLF001
            return
        try:
            fresh = await RpcClient(self.gcs_address).connect(timeout=2.0)
            await fresh.subscribe("nodes", self._on_node_event)
            old, self.gcs = self.gcs, fresh
            if old is not None:
                await old.close()
            logger.info("reconnected to GCS at %s", self.gcs_address)
        except (RpcConnectionError, OSError):
            pass  # still down; next heartbeat retries

    async def _supervise_loop(self) -> None:
        while True:
            await asyncio.sleep(0.2)
            for w in list(self._workers.values()):
                if w.state != "DEAD" and w.proc.poll() is not None:
                    await self._on_worker_death(w)

    async def _memory_monitor_loop(self) -> None:
        """OOM protection (reference: memory_monitor.h:52 + retriable-FIFO
        kill policy). Above the usage threshold, kill the newest retriable
        running task's worker; its caller sees a typed OutOfMemoryError (or a
        retry, if attempts remain). One victim per tick — killing frees
        memory asynchronously, so re-check before killing again."""
        from ray_tpu.core.node.memory_monitor import (
            MemoryMonitor, choose_victim, format_oom_message, process_rss_bytes,
        )

        monitor = MemoryMonitor(
            threshold_fraction=config.memory_usage_threshold,
            min_free_bytes=config.min_memory_free_bytes,
        )
        period = config.memory_monitor_refresh_ms / 1000.0
        while True:
            await asyncio.sleep(period)
            try:
                report = monitor.check()
            except OSError:
                continue  # /proc hiccup: skip the tick
            if report is None:
                continue
            candidates = []
            for w in self._workers.values():
                spec = w.running_task
                if spec is None or w.state == "DEAD" or w.proc.poll() is not None:
                    continue
                candidates.append({
                    "worker": w,
                    "spec": spec,
                    # same default as the dispatch retry loop (a spec without
                    # the key gets 0 retries there, so it is NOT retriable)
                    "retriable": int(spec.get("max_retries", 0)) > 0,
                    "started_at": w.task_started_at,
                })
            victim = choose_victim(candidates)
            if victim is None:
                logger.warning(
                    "memory pressure (%.1f%% used) but no running task to kill",
                    report["used_fraction"] * 100)
                continue
            w = victim["worker"]
            spec = victim["spec"]
            rss = process_rss_bytes(w.proc.pid)
            msg = format_oom_message(report, spec.get("name", "<task>"), rss)
            logger.warning("OOM kill: worker %s running %s (rss=%d)",
                           w.worker_id[:8], spec.get("name"), rss)
            tid = spec.get("task_id", "")
            if tid:
                self._oom_kills[tid] = msg
                while len(self._oom_kills) > 1000:
                    self._oom_kills.pop(next(iter(self._oom_kills)))
            try:
                w.proc.kill()  # cleanup rides _supervise_loop's death path
            except Exception:  # noqa: BLE001
                pass

    async def _on_worker_death(self, w: _WorkerHandle) -> None:
        prev_state = w.state
        w.state = "DEAD"
        self._workers.pop(w.worker_id, None)
        pool = self._idle_workers.get(w.env_hash)
        if pool and w in pool:
            pool.remove(w)
        logger.warning("worker %s died (state=%s)", w.worker_id[:8], prev_state)
        if w.tpu_chips is not None:
            self._return_chips(w.tpu_chips)
            pool = self._tpu_idle.get(w.tpu_chips)
            if pool and w in pool:
                pool.remove(w)
            w.tpu_chips = None
        if w.client_holder:
            try:
                await self.gcs.call("drop_holder", holder=w.client_holder)
            except Exception:  # noqa: BLE001
                pass
        token = w._actor_token
        if token is not None:
            self._release_token(token)
            w._actor_token = None
        if w.actor_id is not None:
            try:
                await self.gcs.call(
                    "report_actor_death", actor_id=w.actor_id,
                    reason=f"worker process exited with {w.proc.returncode}",
                )
            except Exception:  # noqa: BLE001
                pass

    # ----------------------------------------------------------- worker pool
    async def _spawn_worker(self, tpu_chips: Optional[Tuple[int, ...]] = None,
                            renv: Optional[Dict[str, Any]] = None,
                            env_hash: str = "",
                            staged: Optional[tuple] = None) -> _WorkerHandle:
        import uuid

        staged_cwd, py_paths = staged if staged else (None, [])

        worker_id = uuid.uuid4().hex
        env = dict(os.environ)
        env["RAY_TPU_WORKER_ID"] = worker_id
        env["RAY_TPU_AGENT_ADDR"] = self.rpc.address
        env["RAY_TPU_GCS_ADDR"] = self.gcs_address
        env["RAY_TPU_NODE_ID"] = self.hex
        if renv and renv.get("env_vars"):
            env.update(renv["env_vars"])
        path_prefix = ([staged_cwd] if staged_cwd else []) + list(py_paths)
        if path_prefix:
            # staged working_dir: cwd + importable; py_modules: importable
            # only (reference working_dir / py_modules plugin semantics)
            env["PYTHONPATH"] = os.pathsep.join(
                path_prefix + [env.get("PYTHONPATH", "")]).rstrip(os.pathsep)
        if tpu_chips is not None:
            # dedicated TPU worker: sees exactly its chip subset
            # (accelerators.py visible_chip_env, reference tpu.py:155-195)
            from ray_tpu.core import accelerators

            if not os.environ.get(accelerators.FAKE_CHIPS_ENV):
                # real chips: let jax find the TPU backend (fake-chip test
                # clusters keep the CPU backend)
                env.pop("JAX_PLATFORMS", None)
            for k in (accelerators.TPU_VISIBLE_CHIPS_ENV,
                      accelerators.TPU_CHIPS_PER_HOST_BOUNDS_ENV,
                      accelerators.TPU_HOST_BOUNDS_ENV):
                env.pop(k, None)
            env.update(accelerators.visible_chip_env(list(tpu_chips), self._total_chips))
        else:
            # CPU workers must NOT grab the TPU chip: force the cpu backend
            # (a setdefault is not enough — the inherited env may carry the
            # TPU platform, and the TPU plugin's sitecustomize can force its
            # platform past JAX_PLATFORMS when its trigger env is present)
            if renv is None or "JAX_PLATFORMS" not in (renv.get("env_vars") or {}):
                env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
        logfile = open(os.path.join(self.session_dir, f"worker-{worker_id[:8]}.log"), "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.node.worker_main"],
            env=env, stdout=logfile, stderr=subprocess.STDOUT,
            cwd=staged_cwd or os.getcwd(),
        )
        handle = _WorkerHandle(proc, worker_id)
        handle.tpu_chips = tpu_chips
        handle.env_hash = env_hash
        handle.staged_cwd = staged_cwd
        self._workers[worker_id] = handle
        return handle

    def _runtime_env_of(self, spec: Dict[str, Any]):
        """(renv, env_hash) for a task/actor spec. The driver already
        normalized/validated and replaced working_dir with its content
        hash."""
        from ray_tpu.core.runtime_env import env_hash as _h

        renv = {k: v for k, v in (spec.get("runtime_env") or {}).items()
                if not k.startswith("__")}
        return (renv or None), _h(renv)

    # ------------------------------------------------------- TPU chip leasing
    def _valid_chip_count(self, n: int) -> bool:
        """Partial-host chip subsets have known-good libtpu bounds only for
        1, 2 and 4 chips (accelerators.visible_chip_env); whole-host always
        works (framework defaults)."""
        return n == self._total_chips or n in (1, 2, 4)

    # Invariant: every chip id is in EXACTLY ONE place — self._free_chips, or
    # the .tpu_chips of one live worker handle. Workers own their chips from
    # spawn to death (_on_worker_death returns them); nothing else does.
    def _take_chips(self, n: int) -> Optional[Tuple[int, ...]]:
        """Assign n concrete chip ids from the free pool, reclaiming (killing)
        idle dedicated workers when the pool runs short — availability
        accounting already guarantees n <= total unleased."""
        if len(self._free_chips) < n:
            for key, idles in list(self._tpu_idle.items()):
                while idles and len(self._free_chips) < n:
                    w = idles.pop()
                    if w.state != "IDLE":
                        continue  # leased/racing: not reclaimable, just unlist
                    self._kill_worker(w)
                    if w.tpu_chips is not None:
                        self._return_chips(w.tpu_chips)
                        w.tpu_chips = None
                if not idles:
                    self._tpu_idle.pop(key, None)
                if len(self._free_chips) >= n:
                    break
        if len(self._free_chips) < n:
            return None
        chips = tuple(sorted(self._free_chips[:n]))
        self._free_chips = self._free_chips[n:]
        return chips

    def _return_chips(self, chips: Tuple[int, ...]) -> None:
        self._free_chips.extend(chips)

    def _kill_worker(self, w: _WorkerHandle) -> None:
        """Kill + deregister so _supervise_loop/_on_worker_death never sees it
        (the caller handles chip return exactly once)."""
        w.state = "DEAD"
        self._workers.pop(w.worker_id, None)
        try:
            w.proc.kill()
        except Exception:  # noqa: BLE001
            pass

    async def _lease_tpu_worker(self, n: int, env_hash: str = "",
                                renv: Optional[Dict[str, Any]] = None) -> _WorkerHandle:
        """Lease a dedicated worker for n chips: exact-size warm reuse first
        (libtpu init is seconds on real chips; runtime env must match too),
        else spawn on freshly assigned chip ids. Owns the whole chip
        lifecycle on failure."""
        for key, idles in self._tpu_idle.items():
            if len(key) != n:
                continue
            for w in list(idles):
                if (w.proc.poll() is None and w.state == "IDLE"
                        and w.env_hash == env_hash):
                    idles.remove(w)
                    w.state = "LEASED"
                    return w
        chips = self._take_chips(n)
        if chips is None:
            raise TimeoutError("TPU chips unavailable")
        staged = await self._stage_runtime_env(renv) if renv else None
        w = await self._spawn_worker(tpu_chips=chips, renv=renv,
                                     env_hash=env_hash, staged=staged)
        deadline = time.monotonic() + config.worker_start_timeout_s
        try:
            while not w.ready.is_set():
                if w.proc.poll() is not None:
                    raise TimeoutError(f"TPU worker exited with {w.proc.returncode}")
                if time.monotonic() > deadline:
                    raise TimeoutError("timed out waiting for TPU worker")
                try:  # woken by rpc_worker_ready; chunked only to re-check liveness
                    await asyncio.wait_for(w.ready.wait(), timeout=0.2)
                except asyncio.TimeoutError:
                    pass
        except TimeoutError:
            self._kill_worker(w)
            self._return_chips(chips)
            w.tpu_chips = None
            raise
        w.state = "LEASED"
        pool = self._tpu_idle.get(w.tpu_chips)
        if pool and w in pool:  # worker_ready parked it; we own it now
            pool.remove(w)
        return w

    def _release_tpu_worker(self, w: _WorkerHandle) -> None:
        if w.proc.poll() is None and w.tpu_chips is not None:
            w.state = "IDLE"
            pool = self._tpu_idle.setdefault(w.tpu_chips, [])
            if w not in pool:
                pool.append(w)

    async def rpc_worker_ready(self, worker_id: str, address: str,
                               client_holder: str = "") -> bool:
        w = self._workers.get(worker_id)
        if w is None:
            return False
        if w.ready.is_set() and w.address == address:
            # idempotent re-announce (retried RPC): the worker may already be
            # LEASED — resetting state/re-listing it would double-lease it
            return True
        w.client_holder = client_holder or None
        w.address = address
        w.client = await RpcClient(address).connect()
        w.state = "IDLE"
        w.ready.set()
        if w.tpu_chips is None:
            self._idle_workers.setdefault(w.env_hash, []).append(w)
            self._notify_worker_free(w.env_hash)
        else:
            # dedicated TPU worker: park in the chip-keyed pool so a worker
            # whose original lease timed out is reusable/reclaimable instead
            # of orphaned with its chips. A waiting _lease_tpu_worker grabs
            # it right after (state -> LEASED) and reuse skips non-IDLE.
            pool = self._tpu_idle.setdefault(w.tpu_chips, [])
            if w not in pool:
                pool.append(w)
        return True

    async def _lease_worker(self, timeout: Optional[float] = None,
                            env_hash: str = "",
                            renv: Optional[Dict[str, Any]] = None) -> _WorkerHandle:
        deadline = time.monotonic() + (timeout or config.worker_start_timeout_s)
        staged = await self._stage_runtime_env(renv) if renv else None
        free_ev = self._worker_free_events.setdefault(env_hash, asyncio.Event())
        while True:
            # clear-before-check: a worker freed after the check sets the
            # event and the wait below returns immediately (no missed wakeup)
            free_ev.clear()
            idles = self._idle_workers.get(env_hash, [])
            while idles:
                w = idles.pop()
                if w.state == "IDLE" and w.proc.poll() is None:
                    w.state = "LEASED"
                    return w
            # Cap counts only task-pool workers: actors hold their workers for
            # life and are bounded by node RESOURCES, not the pool (matching
            # the reference, where dedicated actor workers don't consume the
            # task worker pool). At the cap, idle workers of OTHER runtime
            # envs are evicted — they can never serve this env, and without
            # eviction the Nth distinct env would starve forever.
            pool = [w for w in self._workers.values() if w.state != "ACTOR"]
            starting = [w for w in pool if w.state == "STARTING"]
            if len(pool) < self._max_workers or not starting:
                if len(pool) >= self._max_workers * 2:
                    self._evict_idle_other_env(env_hash)
                    pool = [w for w in self._workers.values() if w.state != "ACTOR"]
                if len(pool) < self._max_workers * 2:
                    await self._spawn_worker(renv=renv, env_hash=env_hash,
                                             staged=staged)
            # event-driven wait for the next freed worker; the 0.25 s cap is
            # only a safety net for spawn failures (a release wakes us at once)
            try:
                await asyncio.wait_for(free_ev.wait(), timeout=0.25)
            except asyncio.TimeoutError:
                pass
            if time.monotonic() > deadline:
                raise TimeoutError("timed out waiting for a worker")

    def _evict_idle_other_env(self, env_hash: str) -> bool:
        for h, idles in list(self._idle_workers.items()):
            if h == env_hash:
                continue
            while idles:
                w = idles.pop()
                if w.state == "IDLE" and w.proc.poll() is None:
                    self._kill_worker(w)
                    if w.client_holder:
                        spawn(
                            self.gcs.call("drop_holder", holder=w.client_holder)
                        )
                    return True
            self._idle_workers.pop(h, None)
        return False

    async def _stage_runtime_env(self, renv: Dict[str, Any]) -> tuple:
        """Stage working_dir + py_modules packages from GCS KV. Returns
        (cwd_or_None, extra_pythonpath_dirs)."""
        from ray_tpu.core.runtime_env import kv_key, stage_package

        async def fetch(h: str) -> str:
            # staged-already fast path: _lease_worker stages on EVERY lease,
            # so skipping the KV download for warm hashes keeps multi-MB
            # packages off the per-task hot path
            dest = os.path.join(self.session_dir, "runtime_envs", h)
            if os.path.isdir(dest):
                return dest
            payload = await self.gcs.call("kv_get", key=kv_key(h))
            if payload is None:
                raise KeyError(f"runtime_env package {h} not found in GCS KV")
            return stage_package(payload, h, self.session_dir)

        h = renv.get("working_dir_hash")
        mods = renv.get("py_modules_hashes") or []
        # one gather: cold staging latency is max(fetches), not
        # workdir + max(modules)
        staged = list(await asyncio.gather(
            *(fetch(x) for x in ([h] if h else []) + list(mods))))
        cwd = staged.pop(0) if h else None
        return cwd, staged

    def _notify_worker_free(self, env_hash: str) -> None:
        ev = self._worker_free_events.get(env_hash)
        if ev is not None:
            ev.set()

    def _release_worker(self, w: _WorkerHandle) -> None:
        if w.state == "LEASED" and w.proc.poll() is None:
            w.state = "IDLE"
            self._idle_workers.setdefault(w.env_hash, []).append(w)
            self._notify_worker_free(w.env_hash)

    # ------------------------------------------------------------ object api
    async def rpc_create_object(self, object_id: str, size: int) -> Dict[str, Any]:
        """Idempotent reserve. ``existing``: None (fresh), "reserved" (a
        retried create whose first response was dropped — caller should
        attach and write), or "sealed" (object complete — caller must NOT
        rewrite live-readable memory)."""
        oid = ObjectID.from_hex(object_id)
        try:
            offset = self.store.reserve(oid, size)
            return {"ok": True, "existing": None, "offset": offset}
        except FileExistsError:
            info = self.store.info(oid)
            sealed = bool(info and info[1])
            return {
                "ok": True,
                "existing": "sealed" if sealed else "reserved",
                "size": info[0] if info else 0,
                "offset": self.store.offset(oid),
            }

    async def rpc_seal_object(self, object_id: str, size: int, owner: str = "",
                              is_error: bool = False,
                              contained: Optional[List[str]] = None,
                              payload: Optional[bytes] = None) -> bool:
        oid = ObjectID.from_hex(object_id)
        self.store.seal(oid)
        if is_error:
            self.error_objects.add(object_id)
        self._remember_meta(object_id, owner, contained)
        # registration is BATCHED (one GCS RPC covers every seal that arrives
        # while the previous flush is in flight) but the ack WAITS for the
        # flush: "sealed" always implies "GCS-registered" (state API and
        # remote waiters observe the object the moment the seal ack lands)
        reg = {
            "object_id": object_id, "size": size, "node_id": self.hex,
            "owner": owner, "contained": contained or None,
        }
        from ray_tpu.core.config import inline_max_bytes
        if payload is not None and len(payload) <= inline_max_bytes():
            # small result: the payload rides the registration so the GCS can
            # push it in-band to the submitter's sealed-event channel
            reg["payload"] = payload
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._reg_queue.append((reg, fut))
        self._reg_event.set()
        await fut
        return True

    async def _reg_flush_loop(self) -> None:
        # no coalescing sleep: batching happens naturally — seals arriving
        # during the in-flight GCS RPC pile into the next batch
        while True:
            await self._reg_event.wait()
            self._reg_event.clear()
            batch, self._reg_queue = self._reg_queue, []
            if not batch:
                continue
            parked_until: Optional[float] = None
            while True:
                try:
                    await self.gcs.call("register_objects",
                                        regs=[r for r, _ in batch])
                    for _, fut in batch:
                        if not fut.done():
                            fut.set_result(True)
                    break
                except (RpcConnectionError, TimeoutError) as e:
                    # GCS outage: PARK the batch and re-send once the
                    # restarted GCS answers — "sealed implies registered"
                    # must hold across a crash-restart, so pending seal acks
                    # wait instead of failing their tasks. register_objects
                    # is idempotent on the GCS side, so a duplicate re-send
                    # after an ambiguous timeout is harmless.
                    if not gcs_recovery_enabled():
                        self._fail_reg_batch(batch, e)
                        await asyncio.sleep(0.2)
                        break
                    now = time.monotonic()
                    if parked_until is None:
                        parked_until = now + config.recovery_park_timeout_s
                        logger.warning("register_objects parked across GCS "
                                       "outage (%d seals pending)", len(batch))
                    if now >= parked_until:
                        self._fail_reg_batch(batch, e)
                        break
                    await asyncio.sleep(0.2)
                except Exception as e:  # noqa: BLE001 - remote error: fail seals
                    logger.exception("register_objects flush failed")
                    self._fail_reg_batch(batch, e)
                    await asyncio.sleep(0.2)
                    break

    @staticmethod
    def _fail_reg_batch(batch: List[Tuple[Dict[str, Any], asyncio.Future]],
                        e: Exception) -> None:
        for _, fut in batch:
            if not fut.done():
                fut.set_exception(e)
                fut.exception()  # sealer may have gone: mark seen

    async def _unpin_flush_loop(self) -> None:
        while True:
            await self._unpin_event.wait()
            self._unpin_event.clear()
            batch, self._unpin_queue = self._unpin_queue, []
            if not batch:
                continue
            try:
                await self.gcs.call("unpin_tasks", unpins=batch)
            except Exception:  # noqa: BLE001 - advisory; node-scoped pins are
                # reaped with this node if they leak
                logger.exception("unpin flush failed")
                await asyncio.sleep(0.2)

    async def _pin_flush_loop(self) -> None:
        while True:
            await self._pin_event.wait()
            self._pin_event.clear()
            batch, self._pin_queue = self._pin_queue, []
            if not batch:
                continue
            try:
                await self.gcs.call("pin_tasks", pins=[p for p, _ in batch])
                for _, fut in batch:
                    if not fut.done():
                        fut.set_result(True)
            except Exception as e:  # noqa: BLE001
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
                        fut.exception()  # submitter may have gone: mark seen

    async def rpc_put_object(self, object_id: str, payload: bytes,
                             owner: str = "", is_error: bool = False,
                             contained: Optional[List[str]] = None) -> Dict[str, Any]:
        """Single-round-trip put for small objects: reserve + write + seal +
        GCS-register in ONE RPC. The payload rides the local socket instead
        of a client-side shm write, collapsing the create/seal handshake
        (reference: inlined small returns, max_direct_call_object_size)."""
        return await self._put_local(object_id, payload, owner=owner,
                                     is_error=is_error, contained=contained)

    async def _put_local(self, object_id: str, payload: bytes,
                         owner: str = "", is_error: bool = False,
                         contained: Optional[List[str]] = None) -> Dict[str, Any]:
        oid = ObjectID.from_hex(object_id)
        if self._reserve_idempotent(oid, len(payload)) == "sealed":
            return {"ok": True, "existing": "sealed"}  # idempotent retry
        offset = self.store.offset(oid)

        def _write_segment() -> None:
            # shm create/ftruncate/mmap/copy are synchronous syscalls: run off
            # the event loop so a put flood can't starve heartbeats/RPCs
            try:
                writer = ShmWriter(oid, len(payload), self.hex, offset=offset)
            except FileExistsError:
                # stale segment from a crashed writer: attach and overwrite
                from ray_tpu.core.shm_store import ShmSegment, segment_name

                shm = ShmSegment(segment_name(oid, self.hex), create=False)
                shm.buf[: len(payload)] = payload
                shm.close()
            else:
                writer.buffer[:] = payload
                writer.seal()

        if len(payload) > 256 * 1024:
            # big copy: off the loop (a put flood of large objects would
            # starve heartbeats); tiny writes are cheaper inline than the
            # executor handoff
            await asyncio.get_event_loop().run_in_executor(None, _write_segment)
        else:
            _write_segment()
        from ray_tpu.core.config import inline_max_bytes
        small = bytes(payload) if len(payload) <= inline_max_bytes() else None
        await self.rpc_seal_object(object_id, len(payload), owner=owner,
                                   is_error=is_error, contained=contained,
                                   payload=small)
        return {"ok": True, "existing": None}

    async def rpc_abort_object(self, object_id: str) -> bool:
        self.store.abort(ObjectID.from_hex(object_id))
        return True

    # ops endpoint: invoked ad hoc via `ray_tpu` tooling, not by in-tree code
    async def rpc_store_debug(self, limit: int = 200) -> List[Dict[str, Any]]:  # rtpulint: disable=rpc-drift
        return self.store.debug_entries(limit)

    async def rpc_object_sizes(self, object_ids: List[str]) -> List[Optional[int]]:
        """Stored sizes (local index first, GCS directory for remote refs);
        None = unknown. Backpressure hint for the Data executor."""
        out: List[Optional[int]] = []
        remote_idx: List[int] = []
        for object_id in object_ids:
            info = self.store.info(ObjectID.from_hex(object_id))
            if info is not None:
                out.append(info[0])
            else:
                out.append(None)
                remote_idx.append(len(out) - 1)
        for i in remote_idx:
            rec = await self.gcs.call("lookup_object", object_id=object_ids[i])
            if rec is not None:
                out[i] = rec["size"]
        return out

    async def rpc_object_info(self, object_id: str) -> Optional[Dict[str, Any]]:
        oid = ObjectID.from_hex(object_id)
        info = self.store.info(oid)
        if info is None:
            return None
        size, sealed = info
        return {"size": size, "sealed": sealed,
                "is_error": object_id in self.error_objects,
                "offset": self.store.offset(oid)}

    async def rpc_read_chunk(self, object_id: str, offset: int, length: int) -> bytes:
        oid = ObjectID.from_hex(object_id)
        size = self.store.ensure_local(oid)
        if size is None:
            raise KeyError(f"object {object_id[:16]} not on node {self.hex[:8]}")
        reader = ShmReader(oid, size, self.hex, offset=self.store.offset(oid))
        try:
            data = bytes(reader.buffer[offset : offset + length])
            if not reader.revalidate():
                raise KeyError(f"object {object_id[:16]} evicted mid-read")
            return data
        finally:
            reader.close()

    def _remember_meta(self, object_id: str, owner: str = "",
                       contained: Optional[List[str]] = None) -> None:
        """Keep sealed-object metadata so peer pulls get is_error/owner/
        contained piggybacked on their first chunk reply (bounded FIFO —
        an evicted entry costs the puller nothing: owner/contained already
        live at the GCS from the primary seal)."""
        if not owner and not contained:
            return
        self._object_meta[object_id] = (owner,
                                        list(contained) if contained else None)
        while len(self._object_meta) > 20000:
            self._object_meta.popitem(last=False)

    async def rpc_read_chunk_raw(self, object_id: str, offset: int,
                                 length: int, want_meta: bool = False) -> RawResult:
        """Serve one chunk on the raw transfer plane: the reply payload is
        the arena mapping itself (no bytes() copy, no msgpack encode). The
        object is PINNED until the frame is written so LRU eviction cannot
        recycle the slot mid-send; ``want_meta`` piggybacks is_error/owner/
        contained on the reply so a pull costs exactly its data frames."""
        oid = ObjectID.from_hex(object_id)
        size = self.store.ensure_local(oid)
        if size is None:
            raise KeyError(f"object {object_id[:16]} not on node {self.hex[:8]}")
        reader = ShmReader(oid, size, self.hex, offset=self.store.offset(oid))
        self.store.pin(oid)
        released = [False]

        def release() -> None:
            if not released[0]:
                released[0] = True
                self.store.unpin(oid)
                reader.close()

        try:
            ln = max(0, min(length, size - offset))
            view = reader.buffer[offset : offset + ln]
            if not reader.revalidate():
                raise KeyError(f"object {object_id[:16]} evicted mid-read")
        except BaseException:
            release()
            raise
        meta: Dict[str, Any] = {"size": size}
        if want_meta:
            owner, contained = self._object_meta.get(object_id, ("", None))
            meta.update(has_meta=True,
                        is_error=object_id in self.error_objects,
                        owner=owner, contained=contained)
        ts = self.transfer.stats
        ts["chunks_out"] += 1
        ts["bytes_out"] += ln
        usage = self.store.usage()
        if usage["used"] >= config.object_spilling_threshold * usage["capacity"]:
            # store under pressure: a pin held across the socket write would
            # block spill/eviction of exactly the objects that need to move
            # (observed jamming a 10x-over-budget Data pipeline). Serve a
            # copied chunk and release immediately — zero-copy stays the
            # healthy-store fast path.
            try:
                data = bytes(view)
                if not reader.revalidate():
                    raise KeyError(
                        f"object {object_id[:16]} evicted mid-read")
            finally:
                release()
            return RawResult(meta, data)
        return RawResult(meta, view, release)

    async def rpc_transfer_stats(self) -> Dict[str, Any]:
        """Per-transfer data-plane stats (pull/push bytes, bytes/s, stripe
        sources, stalls, retries, failovers) for the dashboard + ray_perf."""
        return self.transfer.snapshot()

    async def rpc_ensure_local(self, object_id: str,
                               timeout_s: Optional[float] = None,
                               rec_hint: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Make the object readable on this node, pulling if remote.
        Returns {size, is_error}. (named timeout_s: `timeout` is the RPC
        client's own deadline kwarg). ``rec_hint``: a directory record a
        BATCHED lookup already resolved — the first iteration skips the
        per-object GCS long-poll (partition-set pulls cost one lookup RPC
        for the whole set, not one per block); a stale hint falls through
        to the long-poll on the next iteration."""
        oid = ObjectID.from_hex(object_id)
        deadline = time.monotonic() + (timeout_s if timeout_s is not None else 1e18)
        lock = self._pull_locks.setdefault(object_id, asyncio.Lock())
        async with lock:
            size = self.store.ensure_local(oid)
            if size is not None and self.store.contains(oid):
                return {"size": size, "is_error": object_id in self.error_objects,
                        "offset": self.store.offset(oid)}
            # remote: resolve location via GCS long-poll (event-driven — the
            # GCS wakes us on register/lost instead of us re-polling lookup)
            rec = rec_hint
            while True:
                if rec is None:
                    chunk = min(2.0, max(0.05, deadline - time.monotonic()))
                    try:
                        # per-object pull lock: serializing concurrent pulls
                        # of ONE object behind this RPC is the point
                        # rtpulint: disable=race
                        rec = await self.gcs.call(
                            "wait_object_located", object_id=object_id,
                            timeout_s=chunk, timeout=chunk + 5.0,
                        )
                    except (TimeoutError, RpcError):  # chaos-dropped frame: re-poll
                        rec = None
                    except (RpcConnectionError, OSError):
                        # GCS down/restarting: the heartbeat loop reconnects the
                        # shared client; back off instead of failing the wait
                        await asyncio.sleep(0.2)
                        rec = None
                if rec and rec["locations"]:
                    if self.hex in rec["locations"] and self.store.contains(oid):
                        return {"size": rec["size"],
                                "is_error": object_id in self.error_objects,
                                "offset": self.store.offset(oid)}
                    remotes = [n for n in rec["locations"] if n != self.hex]
                    if remotes:
                        meta = await self._pull(oid, rec["size"], remotes,
                                                owner_hint=rec.get("owner", ""))
                        if meta is not None:
                            if meta.get("is_error") or \
                                    rec.get("owner", "").endswith(":error"):
                                self.error_objects.add(object_id)
                            return {
                                "size": rec["size"],
                                "is_error": object_id in self.error_objects,
                                "offset": self.store.offset(oid),
                            }
                        # pull failed (e.g. the only location just crashed and
                        # the GCS hasn't reaped it yet): the long-poll returns
                        # instantly while locations look live, so a failed
                        # pull must back off or this loop spins at full speed
                        await asyncio.sleep(0.05)
                elif rec and rec.get("lost"):
                    # every copy died with its node: waiting is pointless —
                    # re-execute the producing task from lineage (reference:
                    # object_recovery_manager.h:41 + task resubmission,
                    # task_manager.h:468). Raises if no lineage or the
                    # reconstruction budget is exhausted.
                    await self._reconstruct(object_id)
                    rec = None
                    continue  # lookup again: the re-run registered locations
                if time.monotonic() > deadline:
                    raise TimeoutError(f"object {object_id[:16]} not available")
                rec = None  # hint consumed/stale: long-poll next iteration

    async def rpc_ensure_local_batch(
        self, object_ids: List[str], timeout_s: Optional[float] = None
    ) -> List[Dict[str, Any]]:
        """Batched ensure_local (reference: plasma batched Get + parallel
        PullManager pulls). Ids not yet anywhere wait on ONE shared GCS
        long-poll for the whole batch — a 1,000-ref get() costs one control
        RPC per tick, not 1,000 concurrent pollers. Per-object failures come
        back in-band as {"error", "error_type"} so one missing object doesn't
        poison the whole batch."""
        deadline = time.monotonic() + (timeout_s if timeout_s is not None else 1e18)
        out: Dict[str, Dict[str, Any]] = {}

        async def _finish(object_id: str, rec_hint=None) -> None:
            try:
                out[object_id] = await self.rpc_ensure_local(
                    object_id, timeout_s=max(0.05, deadline - time.monotonic()),
                    rec_hint=rec_hint,
                )
            except BaseException as res:  # noqa: BLE001
                out[object_id] = {
                    "error": str(res) or type(res).__name__,
                    "error_type": type(res).__name__,
                    "object_id": object_id,
                }

        # fast path: whatever is already local or already located resolves
        # through rpc_ensure_local immediately (pulls run concurrently)
        pending: List[str] = []
        for object_id in object_ids:
            if self.store.contains(ObjectID.from_hex(object_id)):
                await _finish(object_id)
            else:
                pending.append(object_id)
        while pending:
            chunk = min(2.0, max(0.05, deadline - time.monotonic()))
            try:
                located = await self.gcs.call(
                    "wait_objects_located", object_ids=pending,
                    num_returns=len(pending), timeout_s=chunk,
                    include_lost=True,  # loss must trigger reconstruction NOW
                    timeout=chunk + 5.0,
                )
            except (TimeoutError, RpcError):
                located = []
            except (RpcConnectionError, OSError):
                await asyncio.sleep(0.2)
                located = []
            if located:
                # ONE batched holder lookup for the whole located set (a
                # shuffle reduce's partition set resolves in a single RPC);
                # each record rides into rpc_ensure_local as its first-
                # iteration hint, skipping the per-object long-poll
                try:
                    recs = await self.gcs.call("lookup_objects",
                                               object_ids=located,
                                               timeout=10.0)
                except (TimeoutError, RpcError, RpcConnectionError, OSError):
                    recs = [None] * len(located)
                await asyncio.gather(*[
                    _finish(o, rec_hint=r) for o, r in zip(located, recs)
                ])
                located_set = set(located)
                pending = [o for o in pending if o not in located_set]
            if pending and time.monotonic() >= deadline:
                for object_id in pending:
                    # the per-object path reports lost/reconstruction errors;
                    # anything still unlocated at the deadline times out there
                    await _finish(object_id)
                pending = []
        return [out[o] for o in object_ids]

    async def _reconstruct(self, object_id: str) -> None:
        """Re-execute the task that produced a lost object, from GCS lineage.
        Serialized per producing task (sibling return ids share one re-run);
        raises ObjectLostError (no lineage — e.g. put() data or actor-task
        returns) or ObjectReconstructionFailedError (budget exhausted)."""
        from ray_tpu import exceptions as exc

        spec = await self.gcs.call("get_lineage", object_id=object_id)
        if spec is None:
            raise exc.ObjectLostError(
                object_id,
                "all copies were lost with their nodes and the object has no "
                "lineage (ray.put data and actor-task returns are not "
                "reconstructable)",
            )
        task_key = spec.get("task_id", object_id)
        attempts = self._recon_attempts.get(task_key, 0)
        if attempts >= config.max_object_reconstructions:
            raise exc.ObjectReconstructionFailedError(
                f"object {object_id[:16]} lost again after "
                f"{attempts} reconstruction attempts"
            )
        lock = self._recon_locks.setdefault(task_key, asyncio.Lock())
        async with lock:
            # another waiter may have reconstructed while we queued; the
            # per-task recon lock exists to serialize exactly these RPCs
            # rtpulint: disable=race
            rec = await self.gcs.call("lookup_object", object_id=object_id)
            if rec and rec["locations"]:
                return
            self._recon_attempts[task_key] = self._recon_attempts.get(task_key, 0) + 1
            logger.info(
                "reconstructing %s (attempt %d): re-running task %s",
                object_id[:16], self._recon_attempts[task_key], spec.get("name"),
            )
            if (spec.get("strategy") or {}).get("kind") == "node_affinity":
                # the pinned node is typically the one that died; the original
                # placement preference is moot for a re-run
                spec = {**spec, "strategy": {"kind": "default"}}
            # pin deps+returns for the re-run (removed by _submit_with_retries);
            # dep objects that are themselves lost reconstruct recursively via
            # the dispatch path's ensure_local.
            pinned = (spec.get("deps") or []) + (spec.get("returns") or [])
            try:
                # rtpulint: disable=race -- same per-task recon lock as above
                await self.gcs.call(
                    "add_object_refs", object_ids=pinned,
                    holder=self._task_holder(spec),
                )
            except Exception:  # noqa: BLE001
                pass
            await self._submit_with_retries(spec)

    # ------------------------------------------------------- object broadcast
    async def _upload_object_to(self, client: "RpcClient", oid: ObjectID,
                                object_id: str, size: int) -> bool:
        """Stream the object to one peer. Returns True if the peer NEWLY
        materialized it, False if it already held a sealed copy (detected on
        the first chunk — no wasted re-upload). A size-0 object still sends
        one empty chunk so the receiver can reserve+seal.

        Raw plane: chunk payloads are arena memoryviews written straight to
        the socket (object pinned for the duration — no bytes() copy, no
        msgpack encode) with ``transfer_window_chunks`` sends in flight;
        RTPU_RAW_TRANSFER=0 restores the serial in-band path."""
        if not raw_transfer_enabled():
            return await self._upload_object_to_legacy(client, oid,
                                                       object_id, size)
        reader = ShmReader(oid, size, self.hex, offset=self.store.offset(oid))
        self.store.pin(oid)
        try:
            if not reader.revalidate():
                raise KeyError(f"object {object_id[:16]} evicted mid-push")
            owner, contained = self._object_meta.get(object_id, ("", None))
            is_err = object_id in self.error_objects
            chunk = config.fetch_chunk_bytes

            async def send(off: int, n: int) -> Dict[str, Any]:
                from ray_tpu.core.node.transfer import attempt_timeout

                last_err: Optional[Exception] = None
                for attempt in range(4):
                    try:
                        # re-sends are idempotent: the receiver's ingest
                        # table dedupes by offset (chaos may drop frames);
                        # short first deadline, doubling per retry
                        return await client.call_raw_send(
                            "receive_chunk_raw",
                            reader.buffer[off : off + n],
                            timeout=attempt_timeout(attempt),
                            object_id=object_id, total_size=size, offset=off,
                            is_error=is_err, owner=owner, contained=contained,
                        )
                    except TimeoutError as e:
                        last_err = e
                raise last_err  # type: ignore[misc]

            resp = await send(0, min(chunk, size))
            if isinstance(resp, dict) and resp.get("existing") == "sealed":
                return False
            sem = asyncio.Semaphore(max(1, int(config.transfer_window_chunks)))

            async def one(off: int) -> None:
                async with sem:
                    await send(off, min(chunk, size - off))

            await asyncio.gather(*(one(off)
                                   for off in range(chunk, size, chunk)))
            self.transfer.stats["bytes_out"] += size
            return True
        finally:
            self.store.unpin(oid)
            reader.close()

    async def _upload_object_to_legacy(self, client: "RpcClient",
                                       oid: ObjectID, object_id: str,
                                       size: int) -> bool:
        """Serial in-band msgpack chunk upload (pre-raw-plane baseline)."""
        reader = ShmReader(oid, size, self.hex, offset=self.store.offset(oid))
        try:
            sent = 0
            chunk = config.fetch_chunk_bytes
            while True:
                n = min(chunk, size - sent)
                data = bytes(reader.buffer[sent : sent + n])
                if not reader.revalidate():
                    raise KeyError(f"object {object_id[:16]} evicted mid-push")
                resp = await client.call(
                    "receive_chunk", object_id=object_id, total_size=size,
                    offset=sent, data=data,
                    is_error=object_id in self.error_objects,
                    timeout=60.0,
                )
                if isinstance(resp, dict) and resp.get("existing") == "sealed":
                    return sent > 0  # already had it iff detected up front
                sent += n
                if sent >= size:
                    return True
        finally:
            reader.close()

    async def rpc_push_object(self, object_id: str,
                              targets: List[str]) -> Dict[str, Any]:
        """Binomial-tree broadcast (reference: object_manager/push_manager.h
        — proactive pushes; here the N-node broadcast costs each node at
        most 2 uploads and completes in ~log2(N) rounds instead of N serial
        pulls from one source). This node uploads the object to the head of
        each half of `targets`; each head recurses on the rest of its half.
        Unreachable/failed heads are skipped (the next node in the half
        takes over) and reported in ``failed`` — one dead node never sinks
        its whole subtree. ``pushed`` counts nodes that NEWLY got a copy."""
        oid = ObjectID.from_hex(object_id)
        size = self.store.ensure_local(oid)
        if size is None or not self.store.contains(oid):
            raise KeyError(f"object {object_id[:16]} not local to {self.hex[:8]}")
        targets = [t for t in targets if t != self.hex]
        if not targets:
            return {"ok": True, "pushed": 0, "failed": {}}
        mid = (len(targets) + 1) // 2
        halves = [h for h in (targets[:mid], targets[mid:]) if h]

        async def push_half(half: List[str]):
            failed: Dict[str, str] = {}
            for i, head in enumerate(half):
                client = await self._peer(head)
                if client is None:
                    failed[head] = "no route"
                    continue
                try:
                    # bulk bytes ride the dedicated transfer connection so
                    # they don't head-of-line-block control RPCs to the peer
                    xfer = await self._transfer_peer(head) or client
                    newly = await self._upload_object_to(xfer, oid,
                                                         object_id, size)
                except (RpcError, RpcConnectionError, TimeoutError,
                        KeyError, OSError) as e:
                    failed[head] = str(e) or type(e).__name__
                    continue
                rest = half[i + 1:]
                try:
                    sub = await client.call("push_object",
                                            object_id=object_id,
                                            targets=rest, timeout=600.0)
                except (RpcError, RpcConnectionError, TimeoutError) as e:
                    # the head has its copy but couldn't fan out: count it,
                    # report the rest as failed
                    failed.update({t: f"via {head[:8]}: {e}" for t in rest})
                    return int(newly), failed
                failed.update(sub.get("failed", {}))
                return int(newly) + int(sub.get("pushed", 0)), failed
            return 0, failed

        results = await asyncio.gather(*(push_half(h) for h in halves))
        failed: Dict[str, str] = {}
        for _, f in results:
            failed.update(f)
        return {"ok": True, "pushed": sum(p for p, _ in results),
                "failed": failed}

    def _reserve_idempotent(self, oid: ObjectID, size: int) -> str:
        """Reserve-or-recover shared by every ingest path. Returns "fresh",
        "reserved" (same-size reservation exists), or "sealed"."""
        try:
            self.store.reserve(oid, size)
            return "fresh"
        except FileExistsError:
            info = self.store.info(oid)
            if info and info[1]:
                return "sealed"
            if info is None or info[0] != size:
                # stale half-written reservation of a DIFFERENT size (or an
                # entry aborted between reserve and info): recreate
                self.store.abort(oid)
                self.store.reserve(oid, size)
                return "fresh"
            return "reserved"

    async def rpc_receive_chunk(self, object_id: str, total_size: int,
                                offset: int, data: bytes,
                                is_error: bool = False, owner: str = "",
                                contained: Optional[List[str]] = None) -> Dict[str, Any]:
        """In-band (msgpack) chunk ingest — compat path and the
        RTPU_RAW_TRANSFER=0 A/B baseline. Shares the per-object cached
        ShmWriter ingest table with the raw plane instead of constructing a
        fresh writer (attach + validate) for every chunk; seals + registers
        with the GCS once every byte has landed."""
        sink, finish = await self.transfer.open_ingest(
            payload_len=len(data), object_id=object_id,
            total_size=total_size, offset=offset, is_error=is_error,
            owner=owner, contained=contained)
        if sink is not None and data:
            sink[: len(data)] = data
        return await finish(len(data))

    async def _pull(self, oid: ObjectID, size: int, locations: List[str],
                    owner_hint: str = "") -> Optional[Dict[str, Any]]:
        """Materialize a remote object locally. Raw plane: striped windowed
        pull with mid-object failover/resume (TransferManager); returns the
        piggybacked metadata dict on success, None on failure.
        RTPU_RAW_TRANSFER=0 restores the serial single-source msgpack path."""
        if raw_transfer_enabled():
            return await self.transfer.pull(oid, size, locations,
                                            owner_hint=owner_hint)
        ok = await self._pull_legacy(oid, size, locations)
        return {} if ok else None

    async def _pull_legacy(self, oid: ObjectID, size: int,
                           locations: List[str]) -> bool:
        """Serial chunked pull from one peer agent (pre-raw-plane baseline;
        reference: PullManager/PushManager 64MB chunks)."""
        object_id = oid.hex()
        for node_id in locations:
            try:
                client = await self._peer(node_id)
                if client is None:
                    continue
                arena_off = self.store.reserve(oid, size)
                writer = ShmWriter(oid, size, self.hex, offset=arena_off)
                seal_failed = False
                try:
                    offset = 0
                    chunk = config.fetch_chunk_bytes
                    while offset < size:
                        data = await client.call(
                            "read_chunk", object_id=object_id, offset=offset,
                            length=min(chunk, size - offset),
                        )
                        writer.buffer[offset : offset + len(data)] = data
                        offset += len(data)
                finally:
                    try:
                        writer.seal()
                    except FileNotFoundError:
                        # reservation aborted while pulling: don't let the
                        # seal error mask the chunk error / skip cleanup
                        seal_failed = True
                if seal_failed:
                    raise KeyError(
                        f"reservation for {object_id[:16]} aborted mid-pull")
                self.store.seal(oid)
                # peer knows error-ness
                info = await client.call("object_info", object_id=object_id)
                if info and info.get("is_error"):
                    self.error_objects.add(object_id)
                await self.gcs.call(
                    "register_object", object_id=object_id, size=size, node_id=self.hex
                )
                return True
            except (RpcConnectionError, RpcError, TimeoutError, KeyError) as e:
                logger.warning("pull of %s from %s failed: %s", object_id[:16], node_id[:8], e)
                try:
                    self.store.abort(oid)
                except Exception:  # noqa: BLE001
                    pass
                continue
        return False

    async def _peer(self, node_id: str) -> Optional[RpcClient]:
        client = self._peer_clients.get(node_id)
        if client is not None and not client._closed:
            return client
        addr = self._peer_addr_cache.get(node_id)
        if addr is None:
            for info in await self.gcs.call("get_nodes"):
                self._peer_addr_cache[info["NodeID"]] = info["NodeManagerAddress"]
            addr = self._peer_addr_cache.get(node_id)
        if addr is None:
            return None
        try:
            client = await RpcClient(addr).connect(timeout=2.0)
        except RpcConnectionError:
            return None
        self._peer_clients[node_id] = client
        return client

    async def _transfer_peer(self, node_id: str) -> Optional[RpcClient]:
        """Dedicated bulk-transfer connection to a peer (chunk payloads must
        not queue control RPCs behind multi-MB reads on a shared socket)."""
        client = self._transfer_clients.get(node_id)
        if client is not None and not client._closed:  # noqa: SLF001
            return client
        if await self._peer(node_id) is None:  # resolves + caches the address
            return None
        addr = self._peer_addr_cache.get(node_id)
        if addr is None:
            return None
        try:
            client = await RpcClient(addr).connect(timeout=2.0)
        except RpcConnectionError:
            return None
        self._transfer_clients[node_id] = client
        return client

    async def rpc_wait_objects(
        self, object_ids: List[str], num_returns: int, timeout_s: Optional[float]
    ) -> List[str]:
        """Wait until >= num_returns of the ids are available SOMEWHERE in the
        cluster (GCS-registered) or locally; returns the ready subset."""
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        ready: Set[str] = set(
            o for o in object_ids if self.store.contains(ObjectID.from_hex(o))
        )
        while True:
            if len(ready) >= num_returns or len(ready) == len(object_ids):
                break
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                break
            # event-driven: one GCS long-poll covers every still-pending id
            # (sealed objects always register at the GCS, so GCS-located is
            # the cluster-wide readiness signal)
            pending = [o for o in object_ids if o not in ready]
            chunk = 2.0 if remaining is None else min(2.0, max(0.05, remaining))
            try:
                located = await self.gcs.call(
                    "wait_objects_located", object_ids=pending,
                    num_returns=num_returns - len(ready),
                    timeout_s=chunk, timeout=chunk + 5.0,
                )
            except (TimeoutError, RpcError):  # chaos-dropped frame: re-poll
                located = []
            except (RpcConnectionError, OSError):  # GCS down: back off, retry
                await asyncio.sleep(0.2)
                located = []
            ready.update(located)
            if not located and remaining is not None and remaining <= chunk:
                break
        return [o for o in object_ids if o in ready]

    async def rpc_free_objects(self, object_ids: List[str]) -> bool:
        for object_id in object_ids:
            # prompt local delete, then the GCS fans out to every other
            # location (idempotent — a retried RPC re-frees nothing)
            self.store.delete(ObjectID.from_hex(object_id))
            self.error_objects.discard(object_id)
            self._object_meta.pop(object_id, None)
            await self.gcs.call("free_object_everywhere", object_id=object_id)
        return True

    async def rpc_delete_local_object(self, object_id: str) -> bool:
        self.store.delete(ObjectID.from_hex(object_id))
        self.error_objects.discard(object_id)
        self._object_meta.pop(object_id, None)
        return True

    # ------------------------------------------------------------ scheduling
    async def rpc_submit_task(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        """Entry from drivers/workers on this node. Returns {accepted: bool}.
        Completion is observed through the object plane.

        Before accepting, the task's deps + returns are PINNED at the GCS
        under a task holder (so distributed GC can't free an argument while
        the task is queued/running — the pin outlives the submitter's own
        refs), the submitter's holder is registered on the returns, and the
        spec is retained as lineage for reconstruction. Pinning completes
        before this RPC returns, which closes the submit-then-drop race:
        the caller's arg refs are still live during this call."""
        fut = self._accept_task(spec)
        if fut is None:
            return {"accepted": True}  # duplicate submit (retried RPC): dedupe
        try:
            # the ack still waits for the pin (it closes the submit-then-drop
            # race) but the pin rides a BATCHED GCS RPC shared with every
            # other submit in the same tick
            await fut
        except Exception:  # noqa: BLE001 - pinning is best-effort bookkeeping
            logger.exception("ref pinning failed")
        spawn(self._submit_with_retries(spec))
        return {"accepted": True}

    def _accept_task(self, spec: Dict[str, Any]) -> Optional[asyncio.Future]:
        """Dedupe + queue the GCS ref pin for one submitted spec. Returns the
        pin future, or None for a duplicate (already accepted) task."""
        tid = spec.get("task_id", "")
        if tid in self._accepted_tasks:
            return None
        self._accepted_tasks[tid] = time.monotonic()
        while len(self._accepted_tasks) > 20000:
            self._accepted_tasks.popitem(last=False)
        returns: List[str] = spec.get("returns") or []
        deps: List[str] = spec.get("deps") or []
        pin = {
            "task_holder": self._task_holder(spec),
            "deps": deps,
            "returns": returns,
            "submitter": spec.get("holder") or "",
            "spec": spec if (
                returns and self._lineage_size(spec) <= config.max_lineage_bytes
            ) else None,
        }
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pin_queue.append((pin, fut))
        self._pin_event.set()
        # tracked while the task is in flight so a GCS-restart resync can
        # re-assert the lease (pins taken after the last snapshot are gone
        # from the restored state)
        self._active_pins[pin["task_holder"]] = pin
        return fut

    async def rpc_submit_task_batch(self, specs: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Coalesced driver-side submission: one RPC accepts a whole batch of
        task specs (the driver flushes its buffer by size or a ~1 ms window).
        Per-task dedupe makes the batch idempotent, so the method is
        retry-safe; the ack waits for every batch member's ref pin exactly
        like the single-spec path."""
        entries = [(spec, self._accept_task(spec)) for spec in specs]
        pins = [f for _, f in entries if f is not None]
        if pins:
            results = await asyncio.gather(*pins, return_exceptions=True)
            for r in results:
                if isinstance(r, Exception):
                    logger.error("ref pinning failed in batch: %s", r)
        for spec, fut in entries:
            if fut is not None:
                spawn(self._submit_with_retries(spec))
        return {"accepted": sum(1 for _, f in entries if f is not None)}

    def _task_holder(self, spec: Dict[str, Any]) -> str:
        # node-scoped so the GCS can drop this pin if the whole node dies
        # before _submit_with_retries gets to remove it
        return f"task:{spec.get('task_id', '')}@{self.hex}"

    @staticmethod
    def _lineage_size(spec: Dict[str, Any]) -> int:
        return len(spec.get("args_payload") or b"")

    async def _submit_with_retries(self, spec: Dict[str, Any]) -> None:
        try:
            await self._submit_with_retries_inner(spec)
        except Exception as e:  # noqa: BLE001 - fire-and-forget: NEVER lose returns
            logger.exception("task submission crashed")
            try:
                await self._store_error(spec, f"internal scheduling error: {e}")
            except Exception:  # noqa: BLE001
                logger.exception("failed to store error objects")
        finally:
            self._unreachable_since.pop(spec.get("task_id", ""), None)
            self._infeasible_since.pop(spec.get("task_id", ""), None)
            # release the task pin: returns stay alive through the
            # submitter's holder; deps fall back to their own holders.
            # Rides the batched unpin flush (one GCS RPC per tick).
            pinned = (spec.get("deps") or []) + (spec.get("returns") or [])
            self._active_pins.pop(self._task_holder(spec), None)
            if pinned:
                self._unpin_queue.append({
                    "holder": self._task_holder(spec), "object_ids": pinned,
                })
                self._unpin_event.set()

    def _can_grant_locally(self, spec: Dict[str, Any]) -> bool:
        """Local-first fast path (reference two-level design:
        cluster_resource_scheduler.cc:150 + local_task_manager.h:58): grant
        on THIS node without a control-plane round trip when the strategy has
        no global placement intent and resources fit right now. Everything
        else — SPREAD, labels, affinity to other nodes, unfit — goes through
        the (batched) GCS path with spillback."""
        if config.external_scheduler_address:
            # an external placement policy has authority over EVERY placement
            # (the fork's contract); the local fast path would bypass it
            return False
        strat = spec.get("strategy") or {}
        kind = strat.get("kind", "default")
        if kind == "node_affinity":
            if strat.get("node_id") != self.hex:
                return False
        elif kind != "placement_group" and (kind != "default" or strat.get("labels")):
            return False
        # the SAME code path the real acquire uses, in dry-run mode, so the
        # fast-path check can never drift from acquire semantics
        return self._acquire_for_spec(spec, dry_run=True) is not None

    async def _schedule_via_gcs(self, spec: Dict[str, Any]) -> Optional[str]:
        """Batched placement: requests arriving within one tick coalesce into
        a single GCS `schedule` RPC (the fork's measured failure mode was a
        control-plane round trip per lease; SURVEY §6)."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._sched_queue.append((
            {"resources": spec.get("resources") or {},
             "strategy": spec.get("strategy") or {},
             "req_id": spec.get("task_id", "")},
            fut,
        ))
        if self._sched_drainer is None or self._sched_drainer.done():
            self._sched_drainer = spawn(self._drain_sched_queue())
        return await fut

    async def _drain_sched_queue(self) -> None:
        try:
            while self._sched_queue:
                await asyncio.sleep(config.scheduler_batch_ms / 1000.0)
                batch, self._sched_queue = self._sched_queue, []
                if not batch:
                    continue
                try:
                    placements = await self.gcs.call(
                        "schedule", requests=[r for r, _ in batch]
                    )
                except RpcError:
                    # a handler-level error (e.g. one request's invalid PG
                    # bundle index) must not fail the whole batch: isolate it
                    # by re-scheduling each request individually
                    await self._schedule_batch_individually(batch)
                    continue
                except Exception as e:  # noqa: BLE001
                    for _, fut in batch:
                        if not fut.done():
                            fut.set_exception(e)
                    continue
                if not isinstance(placements, list) or len(placements) != len(batch):
                    # malformed scheduler reply (e.g. buggy external policy):
                    # fail loudly instead of stranding the tail futures forever
                    err = RpcError(
                        "SchedulerProtocolError",
                        f"scheduler returned {len(placements) if isinstance(placements, list) else type(placements).__name__} "
                        f"placements for {len(batch)} requests",
                    )
                    for _, fut in batch:
                        if not fut.done():
                            fut.set_exception(err)
                    continue
                for (_, fut), target in zip(batch, placements):
                    if not fut.done():
                        fut.set_result(target)
        finally:
            # no await between the while-exit and this check, so an enqueue
            # cannot slip in unseen (single-threaded loop): if one raced in
            # during the last batch's processing, hand off to a fresh drainer
            # rather than strand its future (lost-wakeup)
            if self._sched_queue:
                self._sched_drainer = spawn(self._drain_sched_queue())

    async def _schedule_batch_individually(
        self, batch: List[Tuple[Dict[str, Any], asyncio.Future]]
    ) -> None:
        for req, fut in batch:
            if fut.done():
                continue
            try:
                placements = await self.gcs.call("schedule", requests=[req])
                fut.set_result(placements[0] if placements else None)
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

    async def _submit_with_retries_inner(self, spec: Dict[str, Any]) -> None:
        max_retries = int(spec.get("max_retries", 0))
        tid = spec.get("task_id", "")
        attempt = 0
        last_error = "unknown"
        last_error_type = "WorkerCrashedError"
        skip_local = False  # set after a local busy-grant: spill back via GCS
        busy_rounds = 0     # consecutive busy spillbacks (adaptive backoff)
        while attempt <= max_retries:
            target = None
            self._set_task_state(tid, "scheduling")
            if not skip_local and self._can_grant_locally(spec):
                target = self.hex
            else:
                try:
                    target = await self._schedule_via_gcs(spec)
                except RpcError as e:
                    # handler-level failure (e.g. invalid placement-group
                    # index) is fatal: materialize the error for get()
                    self._set_task_state(tid, "failed")
                    await self._store_error(spec, f"scheduling failed: {e}")
                    return
                except (RpcConnectionError, TimeoutError) as e:
                    last_error = f"scheduler unavailable: {e}"
            skip_local = False
            self._set_task_state(tid, f"placed:{(target or 'none')[:8]}")
            if target is None:
                # unplaceable now: backoff-retry without consuming an attempt.
                # Even CLUSTER-infeasible shapes wait out a grace window —
                # the unmet-demand ledger this retry keeps feeding is exactly
                # what the autoscaler scales up from (reference: infeasible
                # tasks pend while the autoscaler reacts; they don't error)
                feasible = await self._check_feasible(spec)
                if not feasible:
                    start = self._infeasible_since.setdefault(tid, time.monotonic())
                    if time.monotonic() - start > config.infeasible_task_grace_s:
                        self._infeasible_since.pop(tid, None)
                        await self._store_error(
                            spec,
                            f"Task {spec.get('name')} is infeasible: requires "
                            f"{spec.get('resources')}, no alive node can satisfy "
                            f"it, and none appeared within "
                            f"{config.infeasible_task_grace_s}s",
                        )
                        return
                    self._set_task_state(tid, "pending:infeasible")
                    await asyncio.sleep(0.5)
                    continue
                self._infeasible_since.pop(tid, None)
                await asyncio.sleep(0.05)
                continue
            self._infeasible_since.pop(tid, None)
            dispatch_started = False
            try:
                if target == self.hex:
                    dispatch_started = True
                    result = await self._dispatch_local(spec)
                else:
                    peer = await self._peer(target)
                    if peer is None:
                        raise RpcConnectionError(f"no route to node {target[:8]}")
                    dispatch_started = True
                    result = await peer.call("dispatch_task", spec=spec, timeout=None)
                if result.get("ok"):
                    self._set_task_state(tid, "finished")
                    return
                if not result.get("retryable", True):
                    self._set_task_state(tid, "failed")
                    return  # error object already stored by executor
                last_error = result.get("error", "dispatch failed")
                last_error_type = ("OutOfMemoryError" if result.get("oom")
                                   else "WorkerCrashedError")
                if spec.get("streaming") and result.get("reason") != "busy":
                    # the generator may have begun producing: a re-run would
                    # duplicate side effects and splice items from a second
                    # execution into a partially-consumed stream — fail it
                    # (consumer sees an error item at the next index)
                    attempt = max_retries + 1
                    continue
                if result.get("reason") == "busy":
                    # spillback: the task is merely QUEUED (resources/worker
                    # busy on the chosen node) — not a failure; re-place
                    # without consuming a retry attempt (reference: lease
                    # spillback never burns task retries). If the busy grant
                    # was the local fast path, consult the GCS next round.
                    # Backoff grows with consecutive busy rounds so a deep
                    # backlog doesn't hammer the scheduler at 50 Hz per task.
                    skip_local = target == self.hex
                    busy_rounds += 1
                    await asyncio.sleep(min(0.02 * busy_rounds, 0.25))
                    continue
                busy_rounds = 0
            except (RpcConnectionError, RpcError, TimeoutError) as e:
                last_error = str(e)
                if spec.get("streaming") and dispatch_started:
                    # connection lost mid-execution of a generator: never
                    # re-run a possibly-partially-consumed stream
                    attempt = max_retries + 1
                    continue
                if isinstance(e, RpcConnectionError) and not dispatch_started:
                    # target unreachable BEFORE the task could start: a pure
                    # PLACEMENT problem (node died or was scaled down; health
                    # checks lag by seconds) — re-place without consuming task
                    # retries, within a grace window. Connection loss MID-call
                    # must consume an attempt (at-most-once for retries=0).
                    start = self._unreachable_since.setdefault(tid, time.monotonic())
                    if time.monotonic() - start < config.dispatch_unreachable_grace_s:
                        self._set_task_state(tid, "replacing:unreachable-node")
                        await asyncio.sleep(0.2)
                        continue
            self._unreachable_since.pop(tid, None)
            self._set_task_state(tid, f"retrying:{last_error[:40]}")
            attempt += 1
            await asyncio.sleep(min(0.05 * (2 ** attempt), 1.0))
        self._set_task_state(tid, "failed")
        await self._store_error(
            spec, f"Task {spec.get('name')} failed after {max_retries} retries: {last_error}",
            error_type=last_error_type,
        )

    async def _check_feasible(self, spec: Dict[str, Any]) -> bool:
        resources = spec.get("resources") or {}
        for info in await self.gcs.call("get_nodes"):
            if info["Alive"] and all(
                info["Resources"].get(k, 0.0) + 1e-9 >= v for k, v in resources.items()
            ):
                return True
        return False

    async def rpc_dispatch_task(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        return await self._dispatch_local(spec)

    async def _dispatch_local(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        self._active_dispatches += 1
        try:
            return await self._dispatch_local_inner(spec)
        finally:
            self._active_dispatches -= 1

    async def _dispatch_local_inner(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        tid = spec.get("task_id", "")
        # 1. dependencies local — ONE batched ensure (concurrent pulls, one
        # shared GCS long-poll + one batched holder lookup for the whole
        # dep set). A shuffle reduce task's N map-partition args land
        # through the transfer plane in parallel instead of N serial
        # lookup->pull round trips.
        deps: List[str] = spec.get("deps") or []
        from ray_tpu.exceptions import ObjectStoreFullError

        if deps:
            results = await self.rpc_ensure_local_batch(
                deps, timeout_s=config.worker_lease_timeout_s * 10)
            failed = [r for r in results if "error" in r]
            try:
                # failures re-resolve through the per-object path so hard
                # errors (lost without lineage, reconstruction budget spent)
                # surface with their original exception type
                for r in failed:
                    await self.rpc_ensure_local(r["object_id"], timeout_s=5.0)
            except (TimeoutError, ObjectStoreFullError) as e:
                # store-full/timeout while pulling deps = transient local
                # pressure, not a task failure: requeue and let GC/spill
                # free space
                return {"ok": False, "retryable": True, "reason": "busy",
                        "error": f"deps unavailable: {e}"}
        self._set_task_state(tid, "deps-ready")
        # Pin deps in the LOCAL store for the rest of dispatch: the worker
        # reads its args straight out of the shm arena — and under the
        # columnar exchange keeps column views over the slot for the whole
        # task body — so LRU spill/eviction must not recycle a dep's slot
        # while the task can still touch it. (The GCS holder pins taken at
        # rpc_submit_task guard distributed GC; they say nothing about
        # local LRU.) pin() on a not-yet-resident entry is a no-op, so
        # re-ensure and re-pin until the pin actually holds: once an entry
        # is resident AND pinned it can neither be evicted nor spilled.
        pinned_deps: List[ObjectID] = []
        try:
            for d in dict.fromkeys(deps):
                oid = ObjectID.from_hex(d)
                self.store.pin(oid)
                pinned_deps.append(oid)
                while not self.store.contains(oid):
                    # entry vanished before the pin took (evicted while a
                    # later batch member was still pulling)
                    self.store.unpin(oid)
                    pinned_deps.remove(oid)
                    try:
                        await self.rpc_ensure_local(d, timeout_s=5.0)
                    except (TimeoutError, ObjectStoreFullError) as e:
                        return {"ok": False, "retryable": True,
                                "reason": "busy",
                                "error": f"deps unavailable: {e}"}
                    self.store.pin(oid)
                    pinned_deps.append(oid)
            return await self._dispatch_execute(spec, tid)
        finally:
            for oid in pinned_deps:
                self.store.unpin(oid)

    async def _dispatch_execute(self, spec: Dict[str, Any],
                                tid: str) -> Dict[str, Any]:
        """Steps 2+3 of local dispatch (resources, worker lease, run, seal);
        runs with the task's deps pinned in the local store by the caller."""
        from ray_tpu.exceptions import ObjectStoreFullError

        # 2. resources (PG tasks draw from their committed bundle). Busy is
        # first absorbed by a short LOCAL wait — tasks queue at the node like
        # the reference raylet's local task queue — and only then reported
        # back for (GCS) spillback, which avoids a control-plane round trip
        # per 10ms of contention.
        # NO-STEAL fast path: a fresh dispatch may only grab resources when
        # nobody is parked in the FIFO — otherwise a sustained arrival stream
        # starves parked tasks indefinitely (each release stolen by a
        # newcomer; observed losing a task for 20+ min in the 50k stress)
        token = self._acquire_for_spec(spec) if self._local_waiters == 0 else None
        if token is None:
            deadline = time.monotonic() + config.local_queue_wait_s
            while token is None and time.monotonic() < deadline:
                # event-driven FIFO: _release_token wakes exactly one waiter;
                # the timeout is a safety net for resource-shape mismatches
                # (e.g. head waiter needs TPU, a CPU was released)
                fut: asyncio.Future = asyncio.get_event_loop().create_future()
                self._local_wait_q.append(fut)
                self._local_waiters += 1
                try:
                    # wakeups come from the FIFO (releases chain through
                    # mismatched waiters); the 0.5 s cap bounds head-of-line
                    # stalls for resource-SHAPE mismatches — e.g. a CPU task
                    # parked behind a TPU waiter while CPUs sit free and no
                    # release ever fires to chain the wakeup
                    await asyncio.wait_for(
                        fut,
                        timeout=max(0.01, min(0.5, deadline - time.monotonic())),
                    )
                except asyncio.TimeoutError:
                    fut.cancel()  # abandoned: a release must skip, not consume
                finally:
                    self._local_waiters -= 1
                token = self._acquire_for_spec(spec)
                if token is None and fut.done() and not fut.cancelled():
                    # consumed a wakeup without acquiring (wrong resource
                    # shape): pass it on so the release isn't wasted
                    while self._local_wait_q:
                        # the wait queue exists to straddle the await: append
                        # before parking, hand off after waking is the protocol
                        # rtpulint: disable=race
                        nxt = self._local_wait_q.popleft()
                        if not nxt.done():
                            nxt.set_result(True)
                            break
        if token is None:
            return {"ok": False, "retryable": True, "reason": "busy", "error": "resources busy"}
        self._set_task_state(tid, "resources-acquired")
        # 3. worker lease + push. Tasks holding TPU resources run on a
        # DEDICATED worker that sees exactly its assigned chip subset
        # (TPU_VISIBLE_CHIPS); CPU tasks use the shared pool.
        tpu_need = int((spec.get("resources") or {}).get("TPU", 0))
        if tpu_need > 0 and not self._valid_chip_count(tpu_need):
            self._release_token(token)
            await self._store_error(
                spec,
                f"TPU count {tpu_need} is not a valid chip subset on a "
                f"{self._total_chips}-chip host (valid: 1, 2, 4, or all chips)",
            )
            return {"ok": False, "retryable": False, "error": "invalid TPU count"}
        renv, env_hash = self._runtime_env_of(spec)
        try:
            if tpu_need > 0:
                w = await self._lease_tpu_worker(tpu_need, env_hash=env_hash, renv=renv)
            else:
                w = await self._lease_worker(env_hash=env_hash, renv=renv)
        except TimeoutError as e:
            self._release_token(token)
            return {"ok": False, "retryable": True, "reason": "busy", "error": str(e)}
        except Exception as e:  # noqa: BLE001 - staging/env errors are fatal
            self._release_token(token)
            await self._store_error(spec, f"runtime_env setup failed: {e}")
            return {"ok": False, "retryable": False, "error": str(e)}
        w.lease_token = token
        w.running_task = spec
        w.task_started_at = time.monotonic()
        self._set_task_state(tid, "running")
        try:
            result = await w.client.call("run_task", spec=spec, timeout=None)
            snap = (result or {}).pop("decode_stats", None)
            if snap:
                self._worker_decode[w.worker_id] = snap
            self._set_task_state(tid, "executed")
        except (RpcConnectionError, RpcError) as e:
            if isinstance(e, RpcError):
                # handler-level failure: error object was stored by the worker
                return {"ok": False, "retryable": False, "error": str(e)}
            oom_msg = self._oom_kills.pop(spec.get("task_id", ""), None)
            if oom_msg is not None:
                # the memory monitor killed this worker deliberately: typed
                # failure (or retry) instead of a generic crash
                return {"ok": False, "retryable": True, "error": oom_msg,
                        "oom": True}
            return {"ok": False, "retryable": True, "error": f"worker connection lost: {e}"}
        finally:
            # release the worker + resource slot the moment execution ends:
            # sealing the returns below is AGENT-side work and must not
            # extend slot occupancy (it awaits a batched GCS registration —
            # ~tens of ms that used to serialize into every slot's turnover)
            w.running_task = None
            if not w.blocked:
                self._release_token(token)
            else:
                w.blocked = False  # resources already released at block time
            w.lease_token = None
            if w.tpu_chips is not None:
                self._release_tpu_worker(w)
            else:
                self._release_worker(w)
        # small returns ride inline in the reply: write+seal them here
        # (one fewer worker->agent round trip per task)
        inline = (result or {}).pop("inline_returns", None) or []
        try:
            for item in inline:
                await self._put_local(**item)
        except ObjectStoreFullError as e:
            # the task ran but its returns don't fit RIGHT NOW: requeue
            # (at-least-once; already-sealed returns dedupe on re-store)
            # instead of surfacing an internal error
            return {"ok": False, "retryable": True, "reason": "busy",
                    "error": f"store full for returns: {e}"}
        if (result or {}).get("state") == "retry_store_full":
            # worker-side big-return store failed the same way: requeue
            return {"ok": False, "retryable": True, "reason": "busy",
                    "error": "store full for returns (worker)"}
        return {"ok": True, **(result or {})}

    def _try_acquire(self, resources: Dict[str, float], dry_run: bool = False) -> bool:
        for k, v in resources.items():
            if self.available.get(k, 0.0) + 1e-9 < v:
                return False
        if not dry_run:
            for k, v in resources.items():
                self.available[k] = self.available.get(k, 0.0) - v
        return True

    def _release_resources(self, resources: Dict[str, float]) -> None:
        for k, v in resources.items():
            self.available[k] = self.available.get(k, 0.0) + v

    # -------------------------------------------------- placement-group bundles
    async def rpc_reserve_bundle(
        self, pg_id: str, bundle_index: int, resources: Dict[str, float]
    ) -> bool:
        key = (pg_id, bundle_index)
        if key in self._pg_bundles:
            return True  # idempotent re-commit
        if not self._try_acquire(resources):
            return False
        self._pg_bundles[key] = {"total": dict(resources), "avail": dict(resources)}
        return True

    async def rpc_return_bundle(self, pg_id: str, bundle_index: int = -1) -> bool:
        """Release bundle reservation(s) back to node availability.
        bundle_index < 0 releases every bundle of the pg on this node.
        In-flight tasks still drawing from a returned bundle release into a
        no-op (the full bundle already went back) — PG removal while tasks
        run is destructive, matching the reference."""
        for key in list(self._pg_bundles):
            if key[0] == pg_id and (bundle_index < 0 or key[1] == bundle_index):
                rec = self._pg_bundles.pop(key)
                self._release_resources(rec["total"])
        return True

    def _acquire_for_spec(self, spec: Dict[str, Any], dry_run: bool = False
                          ) -> Optional[Tuple[str, Any, Dict[str, float]]]:
        """Acquire execution resources for a task/actor spec. PG-scheduled
        work draws from its committed bundle; everything else from the node
        pool. Returns an opaque token for _release_token, or None if busy.
        ``dry_run`` answers "would this acquire succeed" without mutating —
        the local-first fast path uses it so grant checks can't drift from
        acquire semantics."""
        resources = spec.get("resources") or {}
        strat = spec.get("strategy") or {}
        if strat.get("kind") == "placement_group":
            pg_id = strat.get("pg", "")
            want = strat.get("bundle", -1)
            keys = [k for k in self._pg_bundles
                    if k[0] == pg_id and (want < 0 or k[1] == want)]
            for key in sorted(keys, key=lambda k: k[1]):
                avail = self._pg_bundles[key]["avail"]
                if all(avail.get(r, 0.0) + 1e-9 >= v for r, v in resources.items()):
                    if not dry_run:
                        for r, v in resources.items():
                            avail[r] = avail.get(r, 0.0) - v
                    return ("bundle", key, resources)
            return None
        if self._try_acquire(resources, dry_run=dry_run):
            return ("node", None, resources)
        return None

    def _release_token(self, token: Tuple[str, Any, Dict[str, float]]) -> None:
        kind, key, resources = token
        if kind == "bundle":
            rec = self._pg_bundles.get(key)
            if rec is not None:
                for r, v in resources.items():
                    rec["avail"][r] = rec["avail"].get(r, 0.0) + v
        else:
            self._release_resources(resources)
        while self._local_wait_q:  # wake ONE live waiter
            fut = self._local_wait_q.popleft()
            if not fut.done():
                fut.set_result(True)
                break

    def _reacquire_token(self, token: Tuple[str, Any, Dict[str, float]]) -> None:
        """Forcible re-acquire after a blocked worker resumes: brief
        oversubscription beats deadlock."""
        kind, key, resources = token
        if kind == "bundle":
            rec = self._pg_bundles.get(key)
            if rec is not None:
                for r, v in resources.items():
                    rec["avail"][r] = rec["avail"].get(r, 0.0) - v
        else:
            for k, v in resources.items():
                self.available[k] = self.available.get(k, 0.0) - v

    async def _store_error(self, spec: Dict[str, Any], message: str,
                           error_type: str = "TaskError") -> None:
        """Materialize a failure as error objects for every return id."""
        from ray_tpu import exceptions as exc
        from ray_tpu.core import serialization

        cls = getattr(exc, error_type, exc.TaskError)
        if cls is exc.TaskError:
            err = exc.TaskError(spec.get("name", "?"), message)
        else:
            err = cls(message)
        payload, _ = serialization.pack(err)
        if spec.get("streaming") and spec.get("task_id"):
            # a streaming consumer blocks on the stream directory, not the
            # fixed returns: surface the failure as an error ITEM at the
            # first unproduced index + end-of-stream. Never at index 0
            # blindly — a worker crash after items 0..k were produced (and
            # possibly consumed) must not truncate the stream into a
            # successful-looking end (the error would be invisible).
            tid = spec["task_id"]
            try:
                st = await self.gcs.call("stream_state", task_id=tid)
                if st.get("finished"):
                    return  # stream already ended (e.g. producer reported)
                nxt = int(st.get("produced", 0))
                from ray_tpu.core.streaming import stream_item_id

                err_hex = stream_item_id(tid, nxt).hex()
                await self._write_error_object(err_hex, payload)
                await self.gcs.call(
                    "register_object", object_id=err_hex, size=len(payload),
                    node_id=self.hex, owner=":error",
                )
                await self.gcs.call("stream_put", task_id=tid, index=nxt,
                                    object_id=err_hex)
                await self.gcs.call("stream_end", task_id=tid, total=nxt + 1)
            except Exception:  # noqa: BLE001
                logger.exception("failed to report stream error")
            return
        from ray_tpu.core.config import inline_max_bytes
        small = bytes(payload) if len(payload) <= inline_max_bytes() else None
        for object_id in spec.get("returns", []):
            try:
                await self._write_error_object(object_id, payload)
                await self.gcs.call(
                    "register_object", object_id=object_id, size=len(payload),
                    node_id=self.hex, owner=":error", payload=small,
                )
            except FileExistsError:
                pass  # a retry already stored a result

    async def _write_error_object(self, object_id: str, payload: bytes) -> None:
        from ray_tpu.exceptions import ObjectStoreFullError

        oid = ObjectID.from_hex(object_id)
        deadline = time.monotonic() + 30.0
        while True:
            try:
                offset = self.store.reserve(oid, len(payload))
                break
            except ObjectStoreFullError:
                # error objects are what UNBLOCK waiters — losing one turns a
                # failure into an infinite hang. Wait out transient pressure
                # (GC/spill frees space within the ref-grace window).
                if time.monotonic() > deadline:
                    raise
                await asyncio.sleep(0.1)
        writer = ShmWriter(oid, len(payload), self.hex, offset=offset)
        writer.buffer[:] = payload
        writer.seal()
        self.store.seal(oid)
        self.error_objects.add(object_id)

    # ---------------------------------------------------------------- actors
    async def rpc_start_actor(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        token = self._acquire_for_spec(spec)
        if token is None:
            return {"ok": False, "retryable": True, "reason": "busy", "error": "resources busy"}
        tpu_need = int((spec.get("resources") or {}).get("TPU", 0))
        if tpu_need > 0 and not self._valid_chip_count(tpu_need):
            self._release_token(token)
            await self._store_error(
                spec,
                f"TPU count {tpu_need} is not a valid chip subset on a "
                f"{self._total_chips}-chip host (valid: 1, 2, 4, or all chips)",
            )
            return {"ok": False, "retryable": False, "error": "invalid TPU count"}
        renv, env_hash = self._runtime_env_of(spec)
        try:
            if tpu_need > 0:
                w = await self._lease_tpu_worker(tpu_need, env_hash=env_hash, renv=renv)
            else:
                w = await self._lease_worker(env_hash=env_hash, renv=renv)
        except TimeoutError as e:
            self._release_token(token)
            return {"ok": False, "retryable": True, "error": str(e)}
        except Exception as e:  # noqa: BLE001 - staging/env errors are fatal
            self._release_token(token)
            await self._store_error(spec, f"runtime_env setup failed: {e}")
            return {"ok": False, "retryable": False, "error": str(e)}
        w.state = "ACTOR"
        w.actor_id = spec["actor_id"]
        w._actor_token = token
        try:
            result = await w.client.call("start_actor", spec=spec, timeout=None)
        except (RpcConnectionError, RpcError) as e:
            self._release_token(token)
            w._actor_token = None
            await self._on_worker_death(w)
            return {"ok": False, "retryable": True, "error": str(e)}
        if not result.get("ok"):
            # constructor raised: creation error object stored by worker
            self._release_token(token)
            w._actor_token = None
            w.actor_id = None
            if w.tpu_chips is not None:
                # dedicated worker returns to the chip-keyed pool (NEVER the
                # CPU pool: it would run CPU tasks with a TPU env and strand
                # its chips forever)
                self._release_tpu_worker(w)
            else:
                w.state = "IDLE"
                self._idle_workers.setdefault(w.env_hash, []).append(w)
                self._notify_worker_free(w.env_hash)
            return {"ok": False, "retryable": False, "error": result.get("error", "")}
        await self.gcs.call(
            "actor_started", actor_id=spec["actor_id"], node_id=self.hex, address=w.address
        )
        return {"ok": True, "address": w.address}

    async def rpc_store_error(self, returns: List[str], name: str, message: str,
                              error_type: str = "TaskError") -> bool:
        await self._store_error({"returns": returns, "name": name}, message, error_type)
        return True

    async def rpc_kill_actor_worker(self, actor_id: str) -> bool:
        for w in list(self._workers.values()):
            if w.actor_id == actor_id:
                w.actor_id = None  # supervisor must not report this as a crash
                try:
                    w.proc.kill()
                except Exception:  # noqa: BLE001
                    pass
                token = w._actor_token
                if token is not None:
                    self._release_token(token)
                    w._actor_token = None
                return True
        return False

    # ------------------------------------------------------------------ info
    def _set_task_state(self, tid: str, state: str) -> None:
        self._task_states[tid] = state
        self._task_events.setdefault(tid, []).append((time.time(), state))
        while len(self._task_states) > 20000:  # bounded, like _accepted_tasks
            self._task_states.pop(next(iter(self._task_states)))
        while len(self._task_events) > 20000:
            self._task_events.pop(next(iter(self._task_events)))

    async def rpc_task_states(self) -> Dict[str, str]:
        return dict(self._task_states)

    async def rpc_report_profile_events(self, worker_id: str,
                                        events: List[Dict[str, Any]]) -> bool:
        """User profile spans from a worker (reference: profile_event.h ->
        GcsTaskManager); bounded ring, served to the dashboard timeline."""
        if len(events) > 1000:
            logger.warning("profile report from %s truncated: %d of %d spans "
                           "kept", worker_id[:8], 1000, len(events))
        for e in events[:1000]:
            e["worker_id"] = worker_id
            self._profile_events.append(e)
        del self._profile_events[:-20000]
        return True

    async def rpc_profile_events(self) -> List[Dict[str, Any]]:
        return list(self._profile_events)

    async def rpc_task_events(self) -> Dict[str, List[Tuple[float, str]]]:
        """Per-task (wall_ts, state) transition logs for the timeline."""
        return {t: list(ev) for t, ev in self._task_events.items()}

    async def rpc_metrics_text(self) -> str:
        """This node's metrics in Prometheus exposition format, labeled with
        the node id (reference: _private/metrics_agent.py:483 per-node
        collector -> Prometheus scrape)."""
        from ray_tpu.utils import metrics

        self._scrape_gauges()
        return metrics.registry.prometheus_text(
            extra_labels={"node": self.hex[:16]}
        )

    def _scrape_gauges(self) -> None:
        from ray_tpu.utils import metrics

        usage = self.store.usage()
        _gauge("ray_tpu_object_store_used_bytes",
               "Shared-memory object store bytes in use").set(usage.get("used", 0))
        _gauge("ray_tpu_object_store_capacity_bytes",
               "Shared-memory object store capacity").set(usage.get("capacity", 0))
        _gauge("ray_tpu_object_store_spilled_bytes",
               "Bytes spilled to disk").set(usage.get("spilled", 0))
        _gauge("ray_tpu_node_workers", "Worker processes on this node").set(
            len(self._workers))
        _gauge("ray_tpu_node_active_dispatches",
               "Tasks queued or running on this node").set(self._active_dispatches)
        ts = self.transfer.stats
        _gauge("ray_tpu_transfer_pull_bytes_total",
               "Object bytes pulled from peers").set(ts["pull_bytes"])
        _gauge("ray_tpu_transfer_ingest_bytes_total",
               "Object bytes received via chunked ingest").set(ts["ingest_bytes"])
        _gauge("ray_tpu_transfer_bytes_out_total",
               "Object bytes served/pushed to peers").set(ts["bytes_out"])
        _gauge("ray_tpu_transfer_pull_failovers_total",
               "Pulls that failed over to another source mid-object").set(
            ts["pull_failovers"])
        _gauge("ray_tpu_transfer_stalls_total",
               "Chunk requests delayed by the in-flight-bytes budget").set(
            ts["stalls"])
        _gauge("ray_tpu_transfer_last_pull_mbps",
               "Throughput of the most recent completed pull").set(
            ts["last_pull"].get("mbps", 0.0))
        for res in ("CPU", "TPU"):
            if res in self.total_resources:
                _gauge("ray_tpu_resource_available", "Available resource units",
                       ).set(self.available.get(res, 0.0), tags={"resource": res})
                _gauge("ray_tpu_resource_total", "Total resource units",
                       ).set(self.total_resources.get(res, 0.0), tags={"resource": res})

    # ------------------------------------------------------------------- jobs
    # Driver-script job submission (reference capability:
    # dashboard/modules/job/sdk.py:35 submit_job:125 — here the head agent
    # doubles as the job supervisor; job metadata mirrors into GCS KV so any
    # client can query status/logs cluster-wide).
    async def rpc_submit_job(
        self,
        entrypoint: str,
        env: Optional[Dict[str, str]] = None,
        working_dir: Optional[str] = None,
        job_id: Optional[str] = None,
    ) -> str:
        import shlex
        import uuid as _uuid

        if not entrypoint.strip():
            raise ValueError("empty job entrypoint")
        job_id = job_id or f"job-{_uuid.uuid4().hex[:10]}"
        log_path = os.path.join(self.session_dir, f"{job_id}.log")
        jenv = dict(os.environ)
        jenv.update(env or {})
        jenv["RAY_TPU_ADDRESS"] = self.gcs_address
        jenv.setdefault("JAX_PLATFORMS", "cpu")
        with open(log_path, "ab") as logfile:  # child keeps its own dup
            proc = subprocess.Popen(
                shlex.split(entrypoint), env=jenv, stdout=logfile,
                stderr=subprocess.STDOUT, cwd=working_dir or os.getcwd(),
                start_new_session=True,
            )
        self._jobs[job_id] = {"proc": proc, "log": log_path,
                              "entrypoint": entrypoint, "started": time.time()}
        await self._publish_job(job_id, "RUNNING")
        spawn(self._watch_job(job_id))
        return job_id

    async def _watch_job(self, job_id: str) -> None:
        rec = self._jobs[job_id]
        proc: subprocess.Popen = rec["proc"]
        while proc.poll() is None:
            await asyncio.sleep(0.2)
        rec["returncode"] = proc.returncode
        if rec.get("stop_requested"):
            status = "STOPPED"
        else:
            status = "SUCCEEDED" if proc.returncode == 0 else "FAILED"
        await self._publish_job(job_id, status, retries=30)

    async def _publish_job(self, job_id: str, status: str, retries: int = 3) -> None:
        import json

        rec = self._jobs.get(job_id, {})
        meta = {
            "job_id": job_id,
            "status": status,
            "node_id": self.hex,
            "entrypoint": rec.get("entrypoint", ""),
            "returncode": rec.get("returncode"),
            "started": rec.get("started"),
        }
        for attempt in range(max(retries, 1)):
            try:
                await self.gcs.call("kv_put", key=f"job:{job_id}",
                                    value=json.dumps(meta).encode())
                return
            except Exception:  # noqa: BLE001
                if attempt == max(retries, 1) - 1:
                    logger.exception("failed to publish job status")
                else:
                    await asyncio.sleep(1.0)

    async def rpc_job_logs(self, job_id: str, tail_bytes: int = 65536,
                           offset: Optional[int] = None) -> Any:
        """tail mode (offset=None): last tail_bytes as raw bytes.
        stream mode (offset=N): {"data": bytes-from-N, "offset": new-end} so
        followers track an absolute position instead of a sliding tail."""
        rec = self._jobs.get(job_id)
        if rec is None:
            raise KeyError(f"unknown job {job_id}")
        if offset is None:
            return self._read_log_tail(rec["log"], tail_bytes)
        try:
            with open(rec["log"], "rb") as f:
                f.seek(offset)
                data = f.read(tail_bytes)
                return {"data": data, "offset": offset + len(data)}
        except OSError:
            return {"data": b"", "offset": offset}

    async def rpc_stop_job(self, job_id: str) -> bool:
        rec = self._jobs.get(job_id)
        if rec is None:
            return False
        rec["stop_requested"] = True
        proc: subprocess.Popen = rec["proc"]
        if proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), 15)
            except Exception:  # noqa: BLE001
                proc.terminate()
        return True

    @staticmethod
    def _read_log_tail(path: str, tail_bytes: int) -> bytes:
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                return f.read()
        except OSError:
            return b""

    async def rpc_get_log(self, name: str, tail_bytes: int = 65536) -> bytes:
        """Read a log file from this node's session dir by BASENAME only
        (no path traversal)."""
        base = os.path.basename(name)
        return self._read_log_tail(os.path.join(self.session_dir, base), tail_bytes)

    async def rpc_list_logs(self) -> List[str]:
        try:
            return sorted(f for f in os.listdir(self.session_dir) if f.endswith(".log"))
        except OSError:
            return []

    async def rpc_dump_stacks(self) -> str:
        """All thread stacks of THIS process (`ray_tpu stack` backend;
        reference capability: `ray stack` py-spy dump)."""
        from ray_tpu.utils.debug import format_all_stacks

        return format_all_stacks()

    async def rpc_dump_worker_stacks(self) -> Dict[str, str]:
        """Relay dump_stacks to every live worker on this node — where hung
        USER code actually lives (the `ray stack` use-case)."""
        out: Dict[str, str] = {}

        async def one(worker_id: str, w) -> None:
            if w.client is None or w.proc.poll() is not None:
                return
            try:
                out[worker_id] = await w.client.call("dump_stacks", timeout=10.0)
            except Exception as e:  # noqa: BLE001 - a stuck worker still times out
                out[worker_id] = f"<dump failed: {type(e).__name__}: {e}>"

        await asyncio.gather(*[one(wid, w) for wid, w in self._workers.items()])
        return out

    async def rpc_node_info(self) -> Dict[str, Any]:
        import socket

        return {
            "node_id": self.hex,
            "hostname": socket.gethostname(),
            "address": self.rpc.address,
            "resources": self.total_resources,
            "available": self.available,
            "labels": self.labels,
            "workers": len(self._workers),
            "idle_workers": sum(len(v) for v in self._idle_workers.values()),
            "store": self.store.usage(),
            # summed last-seen worker decode counters (dead workers keep
            # their final value so the node total stays monotonic)
            "decode": {
                k: sum(v.get(k, 0) for v in self._worker_decode.values())
                for k in ("zero_copy_bytes", "copied_bytes")
            },
            # shm-locality probe: a nonce file in THIS machine's /dev/shm.
            # A driver that can read the nonce shares the agent's shm and may
            # use the direct data plane; hostname comparison alone misses
            # cloned VMs with identical hostnames (ADVICE r4)
            "shm_probe": {"path": self._shm_probe_path,
                          "nonce": self._shm_probe_nonce},
        }

    async def rpc_worker_blocked(self, worker_id: str) -> bool:
        """A leased worker is blocking in get(): release its CPU lease so
        dependent tasks can run (reference: raylet releases CPUs for workers
        blocked in ray.get — prevents nested-task deadlock)."""
        w = self._workers.get(worker_id)
        if w is not None and w.state == "LEASED" and w.lease_token and not w.blocked:
            w.blocked = True
            self._release_token(w.lease_token)
        return True

    async def rpc_worker_unblocked(self, worker_id: str) -> bool:
        w = self._workers.get(worker_id)
        if w is not None and w.blocked and w.lease_token:
            w.blocked = False
            # reacquire without waiting: brief oversubscription beats deadlock
            self._reacquire_token(w.lease_token)
        return True

    async def rpc_ping(self) -> str:
        return "pong"


async def serve_forever(args) -> None:
    agent = NodeAgent(
        gcs_address=args.gcs,
        host=args.host,
        port=args.port,
        num_cpus=args.num_cpus,
        num_tpus=args.num_tpus,
        resources={k: float(v) for k, v in
                   (kv.split("=", 1) for kv in (args.resource or []))},
        labels=dict(kv.split("=", 1) for kv in (args.label or [])),
        is_head=args.head,
        session_dir=args.session_dir,
        object_store_memory=args.object_store_memory or None,
    )
    h, p = await agent.start()
    if args.ready_file:
        with open(args.ready_file, "w") as f:
            f.write(f"{h}:{p}")
    await asyncio.Event().wait()


def main() -> None:
    import argparse

    parser = argparse.ArgumentParser(description="ray_tpu node agent")
    parser.add_argument("--gcs", required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--num-cpus", type=int, default=None)
    parser.add_argument("--num-tpus", type=int, default=0)
    parser.add_argument("--label", action="append", default=[])
    parser.add_argument("--resource", action="append", default=[])
    parser.add_argument("--head", action="store_true")
    parser.add_argument("--session-dir", default=None)
    parser.add_argument("--object-store-memory", type=int, default=0)
    parser.add_argument("--ready-file", default=None)
    args = parser.parse_args()
    asyncio.run(serve_forever(args))


if __name__ == "__main__":
    main()
