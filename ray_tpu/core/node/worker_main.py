"""Worker process: executes tasks and hosts actors.

Reference capability: python/ray/_private/workers/default_worker.py +
the CoreWorker execution path (task_receiver.h, _raylet.pyx
task_execution_handler) — a process that registers with its node agent,
serves direct task/actor-call RPCs (callers push work straight to the
worker, the agent is off the per-call data path exactly like the
reference's lease-then-PushTask design), executes user code on threads,
and writes results into the node's shared-memory object plane.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import functools
import hashlib
import os
import queue
import threading
import traceback
from typing import Any, Dict, List, Optional

import cloudpickle

from ray_tpu import exceptions as exc
from ray_tpu.core import serialization
from ray_tpu.core.config import config
from ray_tpu.core.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.rpc import RpcClient, RpcServer, SyncRpcClient, spawn
from ray_tpu.core.shm_store import ShmWriter
from ray_tpu.utils.logging import get_logger, setup_component_logging

logger = get_logger("worker")


class WorkerProcess:
    def __init__(self) -> None:
        self.worker_id = os.environ["RAY_TPU_WORKER_ID"]
        self.agent_addr = os.environ["RAY_TPU_AGENT_ADDR"]
        self.gcs_addr = os.environ["RAY_TPU_GCS_ADDR"]
        self.node_hex = os.environ["RAY_TPU_NODE_ID"]
        # chaos-exempt: task/actor-call execution is not idempotent (the
        # chaos tier targets the control plane — GCS + agents)
        self.rpc = RpcServer("127.0.0.1", 0, chaos=False)
        self.rpc.register_object(self)
        self.agent: Optional[RpcClient] = None
        self._fn_cache: Dict[str, Any] = {}
        self._exec_pool = concurrent.futures.ThreadPoolExecutor(max_workers=8)
        # actor state
        self.actor_id: Optional[str] = None
        self.actor_instance: Any = None
        self.actor_dead_error: Optional[BaseException] = None
        self._actor_mailbox: "queue.Queue" = queue.Queue()
        self._actor_thread: Optional[threading.Thread] = None
        self._actor_max_concurrency = 1
        self._actor_pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # pipelined actor-call state (reference: ActorSchedulingQueue seq_no
        # ordering + completed-task dedup):
        # caller id -> {"next": expected seq, "ev": event set on each advance}
        self._actor_seq: Dict[str, Dict[str, Any]] = {}
        # task_id -> reply: completed-call cache so a re-pushed call (caller
        # deadline expiry / connection retry) replays instead of re-executing
        from collections import OrderedDict

        self._actor_done: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        # task_id -> future: a duplicate push of a STILL-RUNNING call
        # piggybacks on the original execution instead of starting a second
        self._actor_inflight: Dict[str, asyncio.Future] = {}

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        await self.rpc.start()
        self.agent = await RpcClient(self.agent_addr).connect()
        # worker-side runtime so user code can call the public API in-task
        from ray_tpu.core.cluster_runtime import ClusterRuntime
        from ray_tpu.core.worker import Worker, set_global_worker

        runtime = ClusterRuntime(
            gcs_address=self.gcs_addr, agent_address=self.agent_addr,
            node_id=NodeID.from_hex(self.node_hex), is_driver=False,
        )
        worker = Worker(
            runtime, JobID.from_int(1),
            worker_id=WorkerID.from_hex(self.worker_id.ljust(32, "0")[:32]),
            node_id=NodeID.from_hex(self.node_hex), is_driver=False,
        )
        # zero-refcount in a worker withdraws its cluster holder (borrowed
        # refs); the GCS frees an object once every process's holder is gone
        worker.ref_counter.set_on_zero(runtime.release)
        set_global_worker(worker)
        self._worker_ctx = worker
        self._runtime = runtime
        await self.agent.call(
            "worker_ready", worker_id=self.worker_id, address=self.rpc.address,
            client_holder=runtime.client_id,
        )
        # tracing bridge: trace spans (created only for specs that carry a
        # __trace_ctx__ from a tracing-enabled driver) fold into the
        # profiling pipeline and land on the cluster timeline
        from ray_tpu import profiling
        from ray_tpu.util import tracing

        def _bridge(spans) -> None:
            for s in spans:
                profiling.record_external_span(
                    s["name"], s["start_s"], s.get("end_s", s["start_s"]),
                    extra={"trace_id": s["trace_id"], "span_id": s["span_id"],
                           "parent_id": s.get("parent_id")},
                )

        tracing.set_exporter(_bridge)  # record ONLY driver-traced tasks
        spawn(self._agent_watchdog())
        logger.info("worker %s ready at %s", self.worker_id[:8], self.rpc.address)

    async def _agent_watchdog(self) -> None:
        """Die with the node agent (reference: workers exit when their raylet
        goes away) — otherwise SIGKILLed agents orphan worker processes that
        accumulate and saturate the host."""
        while True:
            await asyncio.sleep(2.0)
            if self.agent is not None and self.agent._closed:  # noqa: SLF001
                logger.warning("agent connection lost; worker exiting")
                os._exit(0)

    # ----------------------------------------------------------- helpers
    def _load_function(self, function_id: str) -> Any:
        fn = self._fn_cache.get(function_id)
        if fn is None:
            if function_id.startswith("xlang:"):
                # cross-language descriptor "xlang:<module>:<qualname>"
                # (reference capability: java/xlang function descriptors —
                # non-Python frontends submit by importable name instead of
                # a pickled closure)
                import importlib

                _, module_name, qualname = function_id.split(":", 2)
                obj = importlib.import_module(module_name)
                for part in qualname.split("."):
                    obj = getattr(obj, part)
                fn = obj
            else:
                from ray_tpu.core.worker import global_worker

                payload = global_worker().runtime.kv_get(f"fn:{function_id}")
                if payload is None:
                    raise KeyError(f"function {function_id} not found in GCS KV")
                fn = cloudpickle.loads(payload)
            self._fn_cache[function_id] = fn
        return fn

    def _resolve_args(self, payload: bytes) -> tuple:
        """Unpack (args, kwargs); resolve TOP-LEVEL ObjectRefs to values
        (nested refs stay refs — reference semantics). Dep objects are
        pinned by the agent for the whole task execution, so these gets run
        inside a ``pinned_reads`` window: arena-backed payloads decode over
        the live shm mapping (columnar-exchange blocks alias the arena)
        instead of paying a per-arg heap copy."""
        args, kwargs = serialization.unpack(memoryview(payload), zero_copy=False)
        from ray_tpu import api

        def resolve(v):
            return api.get(v) if isinstance(v, ObjectRef) else v

        with serialization.pinned_reads():
            return (tuple(resolve(a) for a in args),
                    {k: resolve(v) for k, v in kwargs.items()})

    def _store_value(self, object_id: str, value: Any, is_error: bool = False,
                     collector: Optional[List[Dict[str, Any]]] = None,
                     xlang: bool = False,
                     inline_limit: Optional[int] = None) -> None:
        if xlang:
            payload, refs = serialization.xlang_pack(value), []
        else:
            payload, refs = serialization.pack(value)
        oid = ObjectID.from_hex(object_id)
        # inline_limit set = actor-call completion path: the payload rides the
        # reply to the CALLER and never touches this node's arena, so nested
        # ObjectRefs must fall through to the agent path (their contained-ref
        # pins only exist for GCS-registered containers)
        collect_ok = (len(payload) <= config.max_direct_call_object_size
                      if inline_limit is None
                      else (len(payload) <= inline_limit and not refs))
        if collector is not None and collect_ok:
            # small return rides INLINE in the run_task reply: the agent
            # writes+seals it locally, removing a full worker->agent round
            # trip per task (reference: max_direct_call_object_size inlining)
            collector.append({
                "object_id": object_id, "payload": bytes(payload),
                "owner": ":error" if is_error else "", "is_error": is_error,
                "contained": [r.id.hex() for r in refs] or None,
            })
            return
        if len(payload) <= config.max_direct_call_object_size:
            # small return: one agent round trip (reserve+write+seal+register)
            resp = asyncio.run_coroutine_threadsafe(
                self.agent.call(
                    "put_object", object_id=object_id, payload=bytes(payload),
                    owner=":error" if is_error else "", is_error=is_error,
                    contained=[r.id.hex() for r in refs] or None,
                ),
                self._loop,
            ).result()
            if isinstance(resp, dict) and resp.get("existing") == "sealed":
                # a previous execution already stored this result; never
                # rewrite memory that readers may be consuming
                raise FileExistsError(object_id)
            return
        fut = asyncio.run_coroutine_threadsafe(
            self.agent.call("create_object", object_id=object_id, size=len(payload)),
            self._loop,
        )
        resp = fut.result()
        if isinstance(resp, dict) and resp.get("existing") == "sealed":
            # a previous execution of this task already stored the result;
            # never rewrite memory that readers may be consuming
            raise FileExistsError(object_id)
        if (isinstance(resp, dict) and resp.get("existing") == "reserved"
                and resp.get("size") != len(payload)):
            # stale half-written reservation from a crashed execution with a
            # DIFFERENT payload size: recreate at the right size
            asyncio.run_coroutine_threadsafe(
                self.agent.call("abort_object", object_id=object_id), self._loop
            ).result()
            resp = asyncio.run_coroutine_threadsafe(
                self.agent.call("create_object", object_id=object_id, size=len(payload)),
                self._loop,
            ).result()
        offset = resp.get("offset") if isinstance(resp, dict) else None
        writer = ShmWriter(oid, len(payload), self.node_hex, offset=offset)
        writer.buffer[:] = payload
        writer.seal()
        asyncio.run_coroutine_threadsafe(
            self.agent.call(
                "seal_object", object_id=object_id, size=len(payload),
                owner=":error" if is_error else "", is_error=is_error,
                contained=[r.id.hex() for r in refs] or None,
            ),
            self._loop,
        ).result()

    def _store_returns(self, spec: Dict[str, Any], result: Any,
                       collector: Optional[List[Dict[str, Any]]] = None,
                       inline_limit: Optional[int] = None) -> None:
        returns: List[str] = spec["returns"]
        xlang = bool(spec.get("xlang"))
        if len(returns) == 1:
            try:
                self._store_value(returns[0], result, collector=collector,
                                  xlang=xlang, inline_limit=inline_limit)
            except FileExistsError:
                pass  # duplicate execution (at-least-once): result already stored
            return
        if not isinstance(result, (tuple, list)) or len(result) != len(returns):
            err = exc.TaskError(
                spec.get("name", "?"),
                f"declared num_returns={len(returns)} but returned "
                f"{type(result).__name__}",
            )
            for r in returns:
                try:
                    self._store_value(r, err, is_error=True, collector=collector,
                                      inline_limit=inline_limit)
                except FileExistsError:
                    pass
            return
        for r, v in zip(returns, result):
            try:
                self._store_value(r, v, collector=collector, xlang=xlang,
                                  inline_limit=inline_limit)
            except FileExistsError:
                pass  # duplicate execution (at-least-once): already stored

    def _store_error_returns(self, spec: Dict[str, Any], e: BaseException,
                             collector: Optional[List[Dict[str, Any]]] = None,
                             inline_limit: Optional[int] = None) -> None:
        err: Any = exc.TaskError.from_exception(
            e, spec.get("name", "?"), pid=os.getpid(), node_id=self.node_hex
        )
        xlang = bool(spec.get("xlang"))
        if xlang:
            # cross-language error envelope: msgpack-able, recognized by
            # cluster_runtime._read_local AND the C++ client's is_error path
            err = {"__rtpu_error__": type(e).__name__, "message": str(err)}
        for r in spec["returns"]:
            try:
                self._store_value(r, err, is_error=True, collector=collector,
                                  xlang=xlang, inline_limit=inline_limit)
            except FileExistsError:
                pass
        if spec.get("streaming") and spec.get("returns"):
            # surface the pre-iteration failure to the streaming consumer as
            # item 0 (the fixed first return slot) followed by end-of-stream
            try:
                self._stream_report(spec, 0, spec["returns"][0])
                self._runtime.gcs.call("stream_end", task_id=spec["task_id"], total=1)
            except Exception:  # noqa: BLE001
                logger.exception("failed to report stream error")

    # ------------------------------------------------- streaming generators
    def _stream_report(self, spec: Dict[str, Any], index: int, oid_hex: str) -> Dict[str, Any]:
        return self._runtime.gcs.call(
            "stream_put", task_id=spec["task_id"], index=index, object_id=oid_hex,
        )

    def _sync_iter_async_gen(self, agen):
        """Iterate an async generator from an executor thread by driving each
        __anext__ on the worker's event loop."""
        while True:
            try:
                yield asyncio.run_coroutine_threadsafe(
                    agen.__anext__(), self._loop
                ).result()
            except StopAsyncIteration:
                return

    def _drive_streaming(self, spec: Dict[str, Any], gen: Any) -> Dict[str, Any]:
        """Producer side of num_returns='streaming' on a cluster worker: seal
        each yielded item via the normal object path, report it to the GCS
        stream directory, honor consumer backpressure via stream_wait.
        Mid-stream exceptions become an error item + end-of-stream.
        (reference: _raylet.pyx:1206,1263 per-item report paths)"""
        import inspect

        from ray_tpu.core.streaming import stream_item_id

        task_hex = spec["task_id"]
        backpressure = int(spec.get("backpressure") or 0)
        if inspect.isasyncgen(gen):
            gen = self._sync_iter_async_gen(gen)
        elif not inspect.isgenerator(gen):
            self._store_error_returns(spec, TypeError(
                f"num_returns='streaming' requires a generator function; "
                f"{spec.get('name', '?')} returned {type(gen).__name__}"
            ))
            return {"state": "error"}
        idx = 0
        try:
            for item in gen:
                oid_hex = stream_item_id(task_hex, idx).hex()
                try:
                    self._store_value(oid_hex, item)
                except FileExistsError:
                    pass  # duplicate execution: item already stored
                resp = self._stream_report(spec, idx, oid_hex)
                idx += 1
                if resp.get("closed"):
                    gen.close()
                    break
                if backpressure > 0 and idx - resp.get("consumed", 0) >= backpressure:
                    while True:
                        try:
                            r = self._runtime.gcs.call(
                                "stream_wait", task_id=task_hex, index=idx,
                                max_ahead=backpressure, timeout=10.0, timeout_s=5.0,
                            )
                        except TimeoutError:
                            continue
                        if r.get("timeout"):
                            continue
                        break
                    if r.get("closed"):
                        gen.close()
                        break
        except BaseException as e:  # noqa: BLE001 - delivered as an error item
            err = exc.TaskError.from_exception(
                e, spec.get("name", "?"), pid=os.getpid(), node_id=self.node_hex
            )
            oid_hex = stream_item_id(task_hex, idx).hex()
            try:
                self._store_value(oid_hex, err, is_error=True)
            except FileExistsError:
                pass
            self._stream_report(spec, idx, oid_hex)
            self._runtime.gcs.call("stream_end", task_id=task_hex, total=idx + 1)
            return {"state": "error"}
        self._runtime.gcs.call("stream_end", task_id=task_hex, total=idx)
        return {"state": "ok"}

    # ------------------------------------------------------------- task rpc
    async def rpc_run_task(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        out = await self._loop.run_in_executor(self._exec_pool, self._execute_task, spec)
        # absolute process-wide Arrow decode counters ride back on every
        # reply; the agent keeps the last-seen value per worker (absolute,
        # not deltas: concurrent tasks in this pool share the counter, so
        # per-task windows would double-count overlapping decodes)
        out["decode_stats"] = serialization.arrow_decode_snapshot()
        return out


    def _flush_profile_spans(self) -> None:
        """Ship this thread's recorded profile spans to the agent (one RPC,
        only when ray_tpu.profile() was used in the task)."""
        from ray_tpu import profiling
        from ray_tpu.util import tracing

        tracing.flush()  # bridge exporter folds trace spans into profiling
        spans = profiling.drain()
        if not spans:
            return
        try:
            # fire-and-forget: the reply is unused and exceptions are
            # swallowed, so never stall the task-completion path on it
            asyncio.run_coroutine_threadsafe(
                self.agent.call("report_profile_events",
                                worker_id=self.worker_id, events=spans),
                self._loop,
            )
        except Exception:  # noqa: BLE001 - observability is best-effort
            pass

    def _execute_task(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.core.worker import global_worker

        w = global_worker()
        task_id = TaskID(bytes.fromhex(spec["task_id"]))
        attempts = 0
        max_attempts = 1 + (spec.get("max_retries", 0) if spec.get("retry_exceptions") else 0)
        from ray_tpu.util import tracing

        while True:
            w.set_task_context(task_id, None, spec.get("name", ""), attempt=attempts)
            try:
                with tracing.task_execution_span(spec):
                    fn = self._load_function(spec["function_id"])
                    args, kwargs = self._resolve_args(spec["args_payload"])
                    result = fn(*args, **kwargs)
                if spec.get("streaming"):
                    return self._drive_streaming(spec, result)
                inline: List[Dict[str, Any]] = []
                try:
                    self._store_returns(spec, result, collector=inline)
                except Exception as store_err:  # noqa: BLE001
                    if "ObjectStoreFullError" in repr(store_err):
                        # the task ran but its returns don't fit the local
                        # store right now: ask the agent to requeue (GC/spill
                        # frees space; already-sealed returns dedupe)
                        return {"state": "retry_store_full",
                                "inline_returns": inline}
                    raise
                return {"state": "ok", "inline_returns": inline}
            except BaseException as e:  # noqa: BLE001
                attempts += 1
                if attempts < max_attempts:
                    continue
                inline = []
                self._store_error_returns(spec, e, collector=inline)
                return {"state": "error", "inline_returns": inline}
            finally:
                w.set_task_context(None)
                self._flush_profile_spans()
                # borrows registered during execution must reach the GCS
                # while the task pin still protects them
                try:
                    self._runtime.flush_refs()
                except Exception:  # noqa: BLE001
                    pass

    # ------------------------------------------------------------ actor rpc
    async def rpc_start_actor(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        if self.actor_id is not None and self.actor_id != spec["actor_id"]:
            return {"ok": False, "retryable": True,
                    "error": f"worker already hosts actor {self.actor_id[:8]}"}
        self.actor_id = spec["actor_id"]
        self._actor_max_concurrency = max(1, int(spec.get("max_concurrency", 1)))
        result = await self._loop.run_in_executor(None, self._construct_actor, spec)
        return result

    def _construct_actor(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.core.worker import global_worker

        w = global_worker()
        task_id = TaskID(bytes.fromhex(spec["task_id"]))
        w.set_task_context(task_id, ActorID.from_hex(spec["actor_id"]), spec.get("name", ""))
        try:
            cls = self._load_function(spec["function_id"])
            args, kwargs = self._resolve_args(spec["args_payload"])
            self.actor_instance = cls(*args, **kwargs)
            try:
                self._store_value(spec["returns"][0], None)
            except Exception:  # noqa: BLE001 - restart: marker already stored
                pass
            if self._actor_max_concurrency > 1:
                self._actor_pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=self._actor_max_concurrency
                )
            return {"ok": True}
        except BaseException as e:  # noqa: BLE001
            self.actor_dead_error = e
            self._store_error_returns(spec, e)
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        finally:
            w.set_task_context(None)
            self._flush_profile_spans()

    async def rpc_run_actor_task(self, spec: Dict[str, Any],
                                 seq: Optional[int] = None,
                                 caller: str = "") -> Dict[str, Any]:
        if self.actor_instance is None:
            raise exc.ActorDiedError(self.actor_id or "", "actor not constructed")
        if spec.get("actor_id") != self.actor_id:
            # stale routing: this worker hosts a different actor
            raise ConnectionError(
                f"worker hosts actor {str(self.actor_id)[:8]}, not {spec.get('actor_id', '')[:8]}"
            )
        tid = spec.get("task_id", "")
        done = self._actor_done.get(tid)
        if done is not None:
            return done  # re-pushed completed call (caller retry): replay
        running = self._actor_inflight.get(tid)
        if running is not None:
            # duplicate push of a STILL-RUNNING call (caller deadline expired
            # and re-attached): wait on the original execution — never run a
            # non-idempotent method twice
            return await asyncio.shield(running)
        fut: asyncio.Future = self._loop.create_future()
        self._actor_inflight[tid] = fut
        try:
            if seq is not None and self._actor_pool is None:
                # windowed pipelining: frames normally arrive in seq order on
                # the persistent connection, but retries/reconnects reorder —
                # gate EXECUTOR SUBMISSION by seq; the single-thread executor
                # then runs jobs in submission order, so the turn advances at
                # submission time and consecutive calls pipeline through the
                # executor without a loop round trip between them
                await self._await_turn(caller, seq)
                try:
                    exec_fut = self._loop.run_in_executor(
                        self._ordered_executor(), self._execute_actor_task, spec)
                finally:
                    self._advance_turn(caller, seq)
                reply = await exec_fut
            else:
                pool = self._actor_pool or self._ordered_executor()
                reply = await self._loop.run_in_executor(
                    pool, self._execute_actor_task, spec)
            self._actor_done[tid] = reply
            while len(self._actor_done) > 512:
                self._actor_done.popitem(last=False)
            if not fut.done():
                fut.set_result(reply)
            return reply
        except BaseException as e:
            if not fut.done():
                fut.set_exception(e)
                fut.exception()  # piggybackers may be gone: mark retrieved
            raise
        finally:
            self._actor_inflight.pop(tid, None)

    async def _await_turn(self, caller: str, seq: int) -> None:
        """Block until `seq` is the next expected call from `caller`, or the
        reorder window expires (a lost/abandoned predecessor must not wedge
        the actor). First contact from a caller accepts its current seq
        (actor restarts join a caller's sequence mid-stream)."""
        st = self._actor_seq.get(caller)
        if st is None:
            st = self._actor_seq[caller] = {
                "next": seq, "ev": asyncio.Event(),
            }
            while len(self._actor_seq) > 256:  # bounded per-caller state
                oldest = next(iter(self._actor_seq))
                if oldest == caller:
                    break
                del self._actor_seq[oldest]
        deadline = self._loop.time() + config.actor_reorder_wait_s
        last_next = st["next"]
        while seq > st["next"]:
            if st["next"] != last_next:
                # predecessors ARE arriving: measure the stall, not the total
                # queue wait — a deep window must not trip the skip-forward
                last_next = st["next"]
                deadline = self._loop.time() + config.actor_reorder_wait_s
            remaining = deadline - self._loop.time()
            if remaining <= 0:
                # predecessor lost (failed call whose error objects the
                # caller already stored): skip forward, don't wedge
                st["next"] = seq
                break
            ev = st["ev"]
            try:
                await asyncio.wait_for(asyncio.shield(ev.wait()), remaining)
            except (asyncio.TimeoutError, TimeoutError):
                pass

    def _advance_turn(self, caller: str, seq: int) -> None:
        st = self._actor_seq.get(caller)
        if st is None:
            return
        if seq + 1 > st["next"]:
            st["next"] = seq + 1
        ev, st["ev"] = st["ev"], asyncio.Event()
        ev.set()  # wake every parked successor; each re-checks its turn

    _ordered: Optional[concurrent.futures.ThreadPoolExecutor] = None

    def _ordered_executor(self) -> concurrent.futures.ThreadPoolExecutor:
        if self._ordered is None:
            self._ordered = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        return self._ordered

    def _execute_actor_task(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.core.worker import global_worker

        w = global_worker()
        task_id = TaskID(bytes.fromhex(spec["task_id"]))
        w.set_task_context(
            task_id, ActorID.from_hex(spec["actor_id"]), spec.get("name", "")
        )
        try:
            if spec["method"] == "__rtpu_channel_loop__":
                # compiled-DAG stage loop (ray_tpu/dag/compiled.py): a
                # framework-injected long-running method that takes over
                # this actor until its channels close
                from ray_tpu.dag.compiled import channel_loop

                method = functools.partial(channel_loop, self.actor_instance)
            else:
                method = getattr(self.actor_instance, spec["method"])
            args, kwargs = self._resolve_args(spec["args_payload"])
            result = method(*args, **kwargs)
            if asyncio.iscoroutine(result):
                result = asyncio.run_coroutine_threadsafe(result, self._loop).result()
            if spec.get("streaming"):
                return self._drive_streaming(spec, result)
            # pipelined callers ask for small results IN the completion reply
            # (spec["inline_max"]): those payloads skip the arena write and
            # the caller's read RPC entirely
            inline_max = int(spec.get("inline_max") or 0)
            inline: Optional[List[Dict[str, Any]]] = [] if inline_max else None
            self._store_returns(spec, result, collector=inline,
                                inline_limit=inline_max or None)
            reply = {"state": "ok"}
            if inline:
                reply["inline_returns"] = inline
            return reply
        except BaseException as e:  # noqa: BLE001
            inline_max = int(spec.get("inline_max") or 0)
            inline = [] if inline_max else None
            self._store_error_returns(spec, e, collector=inline,
                                      inline_limit=inline_max or None)
            if isinstance(e, (SystemExit, KeyboardInterrupt)):
                os._exit(1)
            reply = {"state": "error"}
            if inline:
                reply["inline_returns"] = inline
            return reply
        finally:
            w.set_task_context(None)
            self._flush_profile_spans()
            try:
                self._runtime.flush_refs()
            except Exception:  # noqa: BLE001
                pass

    # ops endpoint: remote kill switch for `ray_tpu` tooling, no in-tree caller
    async def rpc_terminate(self) -> bool:  # rtpulint: disable=rpc-drift
        asyncio.get_event_loop().call_later(0.05, os._exit, 0)
        return True

    async def rpc_dump_stacks(self) -> str:
        """All thread stacks of THIS process (`ray_tpu stack` backend;
        reference capability: `ray stack` py-spy dump)."""
        from ray_tpu.utils.debug import format_all_stacks

        return format_all_stacks()

    async def rpc_ping(self) -> str:
        return "pong"


def main() -> None:
    setup_component_logging("worker", os.environ.get("RAY_TPU_SESSION_DIR"), also_stderr=True)

    async def run() -> None:
        wp = WorkerProcess()
        await wp.start()
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
