"""Host-memory monitor and OOM worker-killing policy.

Protects a node from a runaway task eating host RAM: the agent polls kernel
memory state and, above a usage threshold, kills the worker whose task is
cheapest to sacrifice — retriable tasks first, newest first — surfacing a
typed ``OutOfMemoryError`` to the caller instead of letting the kernel OOM
killer take down the whole node agent.

Equivalent capability to the reference's MemoryMonitor
(reference: src/ray/common/memory_monitor.h:52 — cgroup/proc polling with a
usage-fraction threshold) and its retriable-FIFO kill policy
(reference: src/ray/raylet/worker_killing_policy_retriable_fifo.h — "retriable
last-started first" victim ordering). Redesigned for the asyncio agent: the
monitor is a coroutine on the agent's loop and the kill is a plain SIGKILL on
the leased worker process; cleanup rides the existing worker-death path.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def read_host_memory() -> Tuple[int, int]:
    """(total_bytes, available_bytes) from /proc/meminfo.

    MemAvailable is the kernel's estimate of allocatable memory without
    swapping — the same signal the reference reads (memory_monitor.cc
    GetLinuxMemoryBytes)."""
    total = available = 0
    with open("/proc/meminfo", "rb") as f:
        for line in f:
            if line.startswith(b"MemTotal:"):
                total = int(line.split()[1]) * 1024
            elif line.startswith(b"MemAvailable:"):
                available = int(line.split()[1]) * 1024
            if total and available:
                break
    return total, available


def process_rss_bytes(pid: int) -> int:
    """Resident set size of one process (0 if it is gone)."""
    try:
        with open(f"/proc/{pid}/statm", "rb") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        return 0


def choose_victim(candidates: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Retriable-FIFO policy: prefer killing a task that can be retried, and
    among equals the one started most recently (it has lost the least work).
    Each candidate: {"retriable": bool, "started_at": float, ...}."""
    if not candidates:
        return None
    return sorted(
        candidates,
        key=lambda c: (not c.get("retriable", False), -c.get("started_at", 0.0)),
    )[0]


class MemoryMonitor:
    """Threshold detector with injectable readers (tests fake the kernel).

    ``on_pressure(usage_fraction, total, available)`` fires each poll tick
    while memory is above threshold; the owner decides whom to kill.
    """

    def __init__(
        self,
        threshold_fraction: float,
        min_free_bytes: int = -1,
        read_memory: Callable[[], Tuple[int, int]] = read_host_memory,
    ):
        self.threshold_fraction = threshold_fraction
        self.min_free_bytes = min_free_bytes
        self._read_memory = read_memory

    def check(self) -> Optional[Dict[str, Any]]:
        """Returns a pressure report when above threshold, else None."""
        total, available = self._read_memory()
        if total <= 0:
            return None
        used_fraction = 1.0 - available / total
        over_fraction = used_fraction > self.threshold_fraction
        over_floor = self.min_free_bytes >= 0 and available < self.min_free_bytes
        if not (over_fraction or over_floor):
            return None
        return {
            "total": total,
            "available": available,
            "used_fraction": used_fraction,
            "threshold": self.threshold_fraction,
            "ts": time.time(),
        }


def format_oom_message(report: Dict[str, Any], task_name: str, rss: int) -> str:
    gib = 1024.0**3
    return (
        f"Task {task_name} was killed by the node memory monitor: host memory "
        f"usage {report['used_fraction']:.1%} exceeded the threshold "
        f"{report['threshold']:.1%} "
        f"({(report['total'] - report['available']) / gib:.2f}/"
        f"{report['total'] / gib:.2f} GiB used); this worker's RSS was "
        f"{rss / gib:.2f} GiB. The task was chosen because it is the most "
        f"recently started retriable work on the node (retriable-FIFO "
        f"policy). Reduce per-task memory use, or lower parallelism, or "
        f"raise RAY_TPU_MEMORY_USAGE_THRESHOLD."
    )
