"""Object serialization: cloudpickle + pickle-5 out-of-band buffers.

Capability-equivalent to the reference's serialization stack
(reference: python/ray/_private/serialization.py + includes/serialization.pxi):

- values are pickled with cloudpickle (protocol 5); large contiguous buffers
  (numpy arrays, jax host arrays, arrow buffers, bytes) are extracted
  out-of-band so the object store can hold them contiguously and readers can
  reconstruct **zero-copy** views over shared memory;
- ``ObjectRef``s contained inside a value are captured during serialization
  (the borrowing hook) so the runtime can track nested references;
- a custom-serializer registry mirrors ``ray.util.register_serializer``.

Wire format of a stored object:
    [u32 n_buffers][u64 meta_len][u64 len_0]...[u64 len_{n-1}][meta_pickle][buf_0]...[buf_n]
with 64-byte alignment for each out-of-band buffer so numpy/jax views are
aligned for vectorized readers.
"""

from __future__ import annotations

import pickle
import struct
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle

ALIGN = 64

_custom_serializers: Dict[type, Tuple[Callable, Callable]] = {}
_custom_lock = threading.Lock()

# Thread-local capture of ObjectRefs encountered while pickling a value.
_capture = threading.local()

# Thread-local marker for reads whose source buffer is PINNED for the
# caller's lifetime (a worker resolving task args: the agent holds the deps
# pinned until the task completes). Inside this window an arena-backed read
# may decode directly over the live shm mapping — columns/arrays alias the
# arena instead of a heap copy. Outside it (driver gets, ad-hoc gets inside
# task bodies), nothing guarantees the slot isn't evicted+recycled later,
# so readers must copy (PR 3's read_chunk_raw copy-under-pressure rule).
_pinned_reads = threading.local()

# Process-local decode accounting for the columnar exchange: bytes of Arrow
# columns reconstructed as views over the IPC payload (zero-copy) vs bytes
# of columns whose layout forces a copy/decode on access (pyobj and other
# non-fixed-width fallbacks). Sampled by ShuffleCoordinator baseline/diff.
arrow_decode_stats: Dict[str, int] = {"zero_copy_bytes": 0, "copied_bytes": 0}


class pinned_reads:
    """``with serialization.pinned_reads():`` — marks the current thread as
    holding pins over every object it reads (nestable)."""

    def __enter__(self):
        _pinned_reads.depth = getattr(_pinned_reads, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _pinned_reads.depth = getattr(_pinned_reads, "depth", 1) - 1
        return False


def pinned_reads_active() -> bool:
    return getattr(_pinned_reads, "depth", 0) > 0


def arrow_decode_snapshot() -> Dict[str, int]:
    return dict(arrow_decode_stats)


def register_serializer(cls: type, *, serializer: Callable, deserializer: Callable) -> None:
    """Register a custom reducer for ``cls`` (like ray.util.register_serializer)."""
    with _custom_lock:
        _custom_serializers[cls] = (serializer, deserializer)


def deregister_serializer(cls: type) -> None:
    with _custom_lock:
        _custom_serializers.pop(cls, None)


class _Pickler(cloudpickle.Pickler):
    def reducer_override(self, obj: Any):
        # ObjectRef capture hook: record and serialize by id.
        from ray_tpu.core.object_ref import ObjectRef

        if isinstance(obj, ObjectRef):
            captured = getattr(_capture, "refs", None)
            if captured is not None:
                captured.append(obj)
            return (_reconstruct_ref, (obj.id.binary(), obj.owner_hint))
        if _is_jax_array(obj):
            # Device arrays travel as host numpy (out-of-band buffer) and are
            # re-placed on the default device at load; explicit device
            # placement is the caller's job (parallel/ channels move HBM-HBM).
            import numpy as np

            return (_reconstruct_jax, (np.asarray(obj), obj.dtype.name))
        if _is_arrow_table(obj):
            _sync_arrow_serializer()
        with _custom_lock:
            entry = _custom_serializers.get(type(obj))
        if entry is not None:
            ser, deser = entry
            return (_apply_deserializer, (deser, ser(obj)))
        return NotImplemented


def _apply_deserializer(deser: Callable, payload: Any) -> Any:
    return deser(payload)


def _reconstruct_ref(id_bytes: bytes, owner_hint: Optional[str]):
    from ray_tpu.core.ids import ObjectID
    from ray_tpu.core.object_ref import ObjectRef
    from ray_tpu.core.worker import maybe_register_borrowed_ref

    ref = ObjectRef(ObjectID(id_bytes), owner_hint=owner_hint, _borrowed=True)
    maybe_register_borrowed_ref(ref)
    return ref


def _reconstruct_jax(np_value: Any, dtype_name: str) -> Any:
    import jax.numpy as jnp

    return jnp.asarray(np_value, dtype=dtype_name)


def _is_jax_array(obj: Any) -> bool:
    mod = type(obj).__module__
    return mod.startswith("jax") and type(obj).__name__ in ("ArrayImpl", "Array")


# ---------------------------------------------------------------------------
# Columnar exchange: pa.Table <-> Arrow IPC stream bytes, out-of-band.
#
# A Table's default pickle materializes every column through in-band bytes
# (decode = full copy). Under RTPU_COLUMNAR_EXCHANGE the Table instead
# reduces to ONE Arrow IPC stream buffer wrapped in a PickleBuffer, which
# serialize()'s buffer_callback extracts out-of-band into the object
# payload (64-byte aligned). unpack(zero_copy=True) hands the deserializer
# a memoryview over the stored payload, and ``pa.ipc.open_stream`` over it
# is zero-copy for fixed-width layouts — the reconstructed columns are
# views of the payload (the shm arena itself on the pinned worker-arg
# path). Registered lazily through register_serializer on the first Table
# pickled, so importing this module never imports pyarrow.
# ---------------------------------------------------------------------------
def _is_arrow_table(obj: Any) -> bool:
    mod = type(obj).__module__
    return mod.split(".")[0] == "pyarrow" and type(obj).__name__ == "Table"


def _table_to_ipc(table: Any) -> "pickle.PickleBuffer":
    import pyarrow as pa

    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as writer:
        writer.write_table(table)
    return pickle.PickleBuffer(sink.getvalue())


def _ipc_to_table(buf: Any) -> Any:
    import pyarrow as pa

    # registers the ray_tpu.pyobj extension type before the schema is
    # parsed (an unknown extension would decay to its storage type)
    from ray_tpu.data.block import classify_table_bytes

    table = pa.ipc.open_stream(pa.py_buffer(buf)).read_all()
    fast, fallback = classify_table_bytes(table)
    arrow_decode_stats["zero_copy_bytes"] += fast
    arrow_decode_stats["copied_bytes"] += fallback
    return table


def _sync_arrow_serializer() -> None:
    """Keep the pa.Table registry entry in step with the columnar flag:
    register the IPC serializer when enabled, drop it when disabled (so the
    Table falls back to its default pickle for A/B). Never clobbers a
    user-registered Table serializer."""
    import pyarrow as pa

    from ray_tpu.core.config import columnar_exchange_enabled

    with _custom_lock:
        entry = _custom_serializers.get(pa.Table)
        ours = entry is not None and entry[0] is _table_to_ipc
        if columnar_exchange_enabled():
            if entry is None:
                _custom_serializers[pa.Table] = (_table_to_ipc, _ipc_to_table)
        elif ours:
            del _custom_serializers[pa.Table]


def serialize(value: Any) -> Tuple[bytes, List["pickle.PickleBuffer"], List[Any]]:
    """Serialize to (meta, oob_buffers, contained_refs).

    jax.Arrays are converted to host numpy before pickling (device buffers
    never travel through the host object store implicitly as anything else).
    """
    import io

    buffers: List[pickle.PickleBuffer] = []
    _capture.refs = []
    try:
        f = io.BytesIO()
        pickler = _Pickler(f, protocol=5, buffer_callback=buffers.append)
        pickler.dump(value)
        meta = f.getvalue()
        refs = list(_capture.refs)
    finally:
        _capture.refs = None
    return meta, buffers, refs


def pack(value: Any) -> Tuple[bytes, List[Any]]:
    """Serialize and frame into one contiguous payload. Returns (payload, refs)."""
    meta, buffers, refs = serialize(value)
    raws = [b.raw() for b in buffers]
    header = struct.pack("<IQ", len(raws), len(meta))
    lens = b"".join(struct.pack("<Q", len(r)) for r in raws)
    prefix_len = len(header) + len(lens) + len(meta)
    parts = [header, lens, meta]
    offset = prefix_len
    for r in raws:
        pad = (-offset) % ALIGN
        parts.append(b"\x00" * pad)
        offset += pad
        parts.append(r)
        offset += len(r)
    return b"".join(parts), refs


def packed_size(value: Any) -> int:
    payload, _ = pack(value)
    return len(payload)


# ---------------------------------------------------------------------------
# Cross-language (xlang) object format: C++/other-language clients cannot
# produce or parse pickle, so xlang tasks exchange values as
# [4-byte magic][msgpack body] (reference capability: java/xlang cross-
# language serialization — realized with msgpack, the wire format the rest
# of this runtime already speaks). Discriminator safety: a real packed
# object starts with u32 n_buffers, and "RTXL" would decode to ~1.3e9
# buffers, which no legitimate payload has.
# ---------------------------------------------------------------------------
XLANG_MAGIC = b"RTXL"


def xlang_pack(value: Any) -> bytes:
    """msgpack-encode a plain value (None/bool/int/float/str/bytes/list/
    dict). Raises TypeError for anything richer — xlang results must stay in
    the cross-language type universe."""
    import msgpack

    try:
        return XLANG_MAGIC + msgpack.packb(value, use_bin_type=True)
    except (TypeError, ValueError) as e:
        raise TypeError(
            f"xlang task result must be msgpack-serializable "
            f"(got {type(value).__name__}): {e}"
        ) from None


def is_xlang_payload(payload: memoryview | bytes) -> bool:
    return bytes(payload[:4]) == XLANG_MAGIC


def unpack(payload: memoryview | bytes, zero_copy: bool = True) -> Any:
    """Reconstruct a value from a framed payload.

    With ``zero_copy=True`` and a memoryview over shared memory, numpy arrays
    alias the store buffer (read-only), like plasma's zero-copy gets.
    """
    view = memoryview(payload)
    if is_xlang_payload(view):
        import msgpack

        return msgpack.unpackb(bytes(view[4:]), raw=False, strict_map_key=False)
    n_buffers, meta_len = struct.unpack_from("<IQ", view, 0)
    off = 12
    lengths = []
    for _ in range(n_buffers):
        (ln,) = struct.unpack_from("<Q", view, off)
        lengths.append(ln)
        off += 8
    meta = bytes(view[off : off + meta_len])
    pos = off + meta_len
    bufs = []
    for ln in lengths:
        pos += (-pos) % ALIGN
        b = view[pos : pos + ln]
        if not zero_copy:
            b = memoryview(bytes(b))
        bufs.append(b)
        pos += ln
    return pickle.loads(meta, buffers=bufs)


def dumps(value: Any) -> bytes:
    """Plain in-band pickle (for RPC payloads, small control messages)."""
    return cloudpickle.dumps(value, protocol=5)


def loads(data: bytes) -> Any:
    return pickle.loads(data)
