"""Actor descriptors and handles.

Reference capability: python/ray/actor.py (ActorClass._remote:869,
ActorMethod, ActorHandle) — option chaining, named/detached actors, handle
serialization, per-method num_returns overrides.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from ray_tpu.core.ids import ActorID, TaskID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.remote_function import (
    build_resources,
    build_task_args,
    make_function_descriptor,
    resolve_strategy,
)
from ray_tpu.core.task_spec import FunctionDescriptor, TaskSpec, TaskType
from ray_tpu.core.worker import require_worker

_VALID_ACTOR_OPTIONS = {
    "num_cpus", "num_tpus", "num_gpus", "resources", "memory",
    "max_restarts", "max_task_retries", "max_concurrency", "max_pending_calls",
    "name", "namespace", "lifetime", "scheduling_strategy", "runtime_env",
    "placement_group", "placement_group_bundle_index", "_metadata",
}


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1,
                 generator_backpressure: Optional[int] = None):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        self._generator_backpressure = generator_backpressure

    def options(self, **opts) -> "ActorMethod":
        return ActorMethod(
            self._handle, self._method_name,
            num_returns=opts.get("num_returns", self._num_returns),
            generator_backpressure=opts.get(
                "_generator_backpressure", self._generator_backpressure
            ),
        )

    def remote(self, *args, **kwargs) -> Union[ObjectRef, List[ObjectRef]]:
        return self._handle._submit(
            self._method_name, args, kwargs, self._num_returns,
            generator_backpressure=self._generator_backpressure,
        )

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly; use .remote()."
        )


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str, actor_options: Optional[Dict] = None):
        self._actor_id = actor_id
        self._class_name = class_name
        self._actor_options = dict(actor_options or {})

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def _submit(self, method_name: str, args: tuple, kwargs: dict, num_returns,
                generator_backpressure: Optional[int] = None):
        worker = require_worker()
        streaming = num_returns in ("streaming", "dynamic")
        task_id = TaskID.for_actor_task(self._actor_id)
        spec_args, spec_kwargs = build_task_args(args, kwargs)
        opts = self._actor_options
        backpressure = 0
        if streaming:
            if generator_backpressure is not None:
                backpressure = int(generator_backpressure)
            else:
                from ray_tpu.core.config import config

                backpressure = int(config.generator_backpressure_items)
        spec = TaskSpec(
            task_id=task_id,
            job_id=worker.job_id,
            task_type=TaskType.ACTOR_TASK,
            name=f"{self._class_name}.{method_name}",
            function=FunctionDescriptor(module="", qualname=method_name, function_id=""),
            args=spec_args,
            kwargs=spec_kwargs,
            num_returns=1 if streaming else num_returns,
            resources=build_resources({"num_cpus": 0}, default_num_cpus=0),
            strategy=resolve_strategy({}),
            owner_worker=worker.worker_id,
            actor_id=self._actor_id,
            actor_method_name=method_name,
            max_task_retries=opts.get("max_task_retries", 0),
            max_pending_calls=opts.get("max_pending_calls", -1),
            generator=streaming,
            generator_backpressure=backpressure,
        )
        refs = worker.runtime.submit_actor_task(self._actor_id, spec, args, kwargs)
        if streaming:
            from ray_tpu.core.streaming import ObjectRefGenerator

            return ObjectRefGenerator(task_id.binary().hex(), worker.runtime)
        if num_returns == 1:
            return refs[0]
        return refs

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self) -> str:
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:16]})"

    def __hash__(self) -> int:
        return hash(self._actor_id)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name, self._actor_options))


class ActorClass:
    def __init__(self, cls: type, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(options or {})
        unknown = set(self._options) - _VALID_ACTOR_OPTIONS
        if unknown:
            raise ValueError(f"Invalid actor options: {sorted(unknown)}")
        self._descriptor = make_function_descriptor(cls, is_class=True)
        self.__name__ = cls.__name__
        self.__doc__ = cls.__doc__

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self.__name__}' cannot be instantiated directly; "
            f"use {self.__name__}.remote()."
        )

    def options(self, **new_options) -> "ActorClass":
        return ActorClass(self._cls, {**self._options, **new_options})

    def bind(self, *args, **kwargs):
        """Lazy DAG construction (reference: dag/class_node.py — bind builds
        a ClassNode; method .bind()s on it chain ClassMethodNodes)."""
        from ray_tpu.dag import ClassNode

        return ClassNode(self, args, kwargs)

    def remote(self, *args, **kwargs) -> ActorHandle:
        worker = require_worker()
        opts = self._options
        actor_id = ActorID.of(worker.job_id)
        task_id = TaskID.for_actor_creation(actor_id)
        # Actors hold their explicit resources for their lifetime; the default
        # is zero CPUs while alive (reference semantics: 1 CPU to schedule,
        # 0 CPU held while running).
        resources = build_resources(opts, default_num_cpus=0)
        runtime_env = dict(opts.get("runtime_env") or {})
        if opts.get("name"):
            runtime_env["__actor_name__"] = opts["name"]
            runtime_env["__actor_namespace__"] = opts.get("namespace") or getattr(
                worker, "namespace", "default"
            )
        spec_args, spec_kwargs = build_task_args(args, kwargs)
        spec = TaskSpec(
            task_id=task_id,
            job_id=worker.job_id,
            task_type=TaskType.ACTOR_CREATION_TASK,
            name=f"{self.__name__}.__init__",
            function=self._descriptor,
            args=spec_args,
            kwargs=spec_kwargs,
            num_returns=1,
            resources=resources,
            strategy=resolve_strategy(opts),
            owner_worker=worker.worker_id,
            actor_id=actor_id,
            max_restarts=opts.get("max_restarts", 0),
            max_task_retries=opts.get("max_task_retries", 0),
            max_concurrency=opts.get("max_concurrency", 1),
            max_pending_calls=opts.get("max_pending_calls", -1),
            runtime_env=runtime_env,
        )
        worker.runtime.create_actor(spec, self._cls, args, kwargs)
        return ActorHandle(actor_id, self.__name__, actor_options=opts)


def method(**options):
    """@ray_tpu.method(num_returns=...) decorator for actor methods."""

    def decorator(fn):
        fn.__ray_tpu_method_options__ = options
        return fn

    return decorator
