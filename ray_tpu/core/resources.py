"""Resource sets and scheduling strategies.

Reference capability: src/ray/common/scheduling/ (ResourceRequest,
ResourceSet) + python/ray/util/scheduling_strategies.py. TPU additions:
``TPU`` chips are a first-class resource alongside CPU/memory, and nodes carry
ICI-topology labels (slice name, host index in slice, topology string) used by
the placement-group policies for same-ICI-domain gang scheduling.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

CPU = "CPU"
TPU = "TPU"
MEMORY = "memory"
OBJECT_STORE_MEMORY = "object_store_memory"

# Node labels (mirroring the reference's ray.io/* accelerator labels,
# python/ray/_private/accelerators/tpu.py:155-220).
LABEL_SLICE_NAME = "ray_tpu.io/slice-name"
LABEL_SLICE_HOST_INDEX = "ray_tpu.io/slice-host-index"
LABEL_TPU_TOPOLOGY = "ray_tpu.io/tpu-topology"
LABEL_TPU_GENERATION = "ray_tpu.io/tpu-generation"
LABEL_NODE_ID = "ray_tpu.io/node-id"


def tpu_slice_head_resource(generation: str) -> str:
    """Resource granted to host 0 of a slice; lets one actor gang-own a slice
    (reference: TPU-{type}-head resource, accelerators/tpu.py)."""
    return f"TPU-{generation}-head"


class ResourceSet(dict):
    """A {resource_name: quantity} multiset with arithmetic and feasibility."""

    def __init__(self, items: Optional[Dict[str, float]] = None):
        super().__init__()
        for k, v in (items or {}).items():
            if v:
                self[k] = float(v)

    def copy(self) -> "ResourceSet":
        return ResourceSet(self)

    def is_subset_of(self, other: "ResourceSet") -> bool:
        return all(other.get(k, 0.0) + 1e-9 >= v for k, v in self.items())

    def add(self, other: Dict[str, float]) -> None:
        for k, v in other.items():
            self[k] = self.get(k, 0.0) + v
            if abs(self[k]) < 1e-9:
                del self[k]

    def subtract(self, other: Dict[str, float]) -> None:
        for k, v in other.items():
            self[k] = self.get(k, 0.0) - v
            if abs(self[k]) < 1e-9:
                del self[k]

    def utilization(self, total: "ResourceSet") -> float:
        """Max over resources of used/total (used = total - self-as-available)."""
        util = 0.0
        for k, tot in total.items():
            if tot <= 0:
                continue
            avail = self.get(k, 0.0)
            util = max(util, (tot - avail) / tot)
        return util


@dataclass
class SchedulingStrategy:
    """Base: default hybrid pack-then-spread."""


@dataclass
class DefaultSchedulingStrategy(SchedulingStrategy):
    pass


@dataclass
class SpreadSchedulingStrategy(SchedulingStrategy):
    """Round-robin over feasible nodes (reference: spread_scheduling_policy.h)."""


@dataclass
class NodeAffinitySchedulingStrategy(SchedulingStrategy):
    node_id: str = ""
    soft: bool = False


@dataclass
class NodeLabelSchedulingStrategy(SchedulingStrategy):
    hard: Dict[str, str] = field(default_factory=dict)
    soft: Dict[str, str] = field(default_factory=dict)


@dataclass
class PlacementGroupSchedulingStrategy(SchedulingStrategy):
    placement_group: object = None
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class SliceSchedulingStrategy(SchedulingStrategy):
    """TPU-native: place onto hosts of one ICI slice (optionally a specific
    slice by name). The gang analogue of STRICT_PACK for TPU pods."""

    slice_name: str = ""
    require_head: bool = False
