"""Per-process worker state: runtime handle, reference counting, task context.

Capability-equivalent of the reference's CoreWorker + ReferenceCounter
(reference: src/ray/core_worker/core_worker.h:271, reference_count.h:64):
every process that touches the API — driver or worker — holds exactly one
``Worker`` with:

- the runtime backend (local in-process or cluster client),
- a reference counter tracking local refs, borrowed refs and
  pending-task argument refs; when an object's count reaches zero the
  runtime is told to release it (eviction eligibility / owner bookkeeping),
- a thread-local execution context (current task/actor id, put counter) so
  ``put()`` inside a task derives lineage-correct ObjectIDs.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Optional, TYPE_CHECKING

from ray_tpu.core.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID

if TYPE_CHECKING:
    from ray_tpu.core.object_ref import ObjectRef
    from ray_tpu.core.runtime import CoreRuntime


class ReferenceCounter:
    """Tracks why each object id is still alive in this process.

    Counts: local (ObjectRef instances alive in this interpreter), borrowed
    (refs deserialized out of other objects/args), submitted (pending tasks
    that take the object as an argument)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._local: Dict[ObjectID, int] = defaultdict(int)
        self._submitted: Dict[ObjectID, int] = defaultdict(int)
        self._borrowed: Dict[ObjectID, bool] = {}
        self._on_zero = None  # callback(ObjectID)

    def set_on_zero(self, cb) -> None:
        self._on_zero = cb

    def add_local(self, oid: ObjectID, borrowed: bool = False) -> None:
        with self._lock:
            self._local[oid] += 1
            if borrowed:
                self._borrowed[oid] = True

    def remove_local(self, oid: ObjectID) -> None:
        fire = False
        with self._lock:
            self._local[oid] -= 1
            if self._local[oid] <= 0:
                del self._local[oid]
                self._borrowed.pop(oid, None)
                if self._submitted.get(oid, 0) <= 0:
                    self._submitted.pop(oid, None)
                    fire = True
        if fire and self._on_zero is not None:
            self._on_zero(oid)

    def live_ids(self) -> List[str]:
        """Hex ids of every object this process still holds (local refs or
        pending submissions) — what a GCS-restart catch-up re-asserts."""
        with self._lock:
            return [oid.hex() for oid in
                    set(self._local) | set(self._submitted)]

    def add_submitted(self, oid: ObjectID) -> None:
        with self._lock:
            self._submitted[oid] += 1

    def remove_submitted(self, oid: ObjectID) -> None:
        fire = False
        with self._lock:
            self._submitted[oid] -= 1
            if self._submitted[oid] <= 0:
                del self._submitted[oid]
                if self._local.get(oid, 0) <= 0:
                    fire = True
        if fire and self._on_zero is not None:
            self._on_zero(oid)

    def count(self, oid: ObjectID) -> int:
        with self._lock:
            return self._local.get(oid, 0) + self._submitted.get(oid, 0)

    def alive_ids(self):
        with self._lock:
            return set(self._local) | set(self._submitted)


class _TaskCtx:
    __slots__ = ("task_id", "actor_id", "task_name", "put_index", "attempt")

    def __init__(
        self,
        task_id: Optional[TaskID] = None,
        actor_id: Optional[ActorID] = None,
        task_name: str = "",
        attempt: int = 0,
    ) -> None:
        self.task_id = task_id
        self.actor_id = actor_id
        self.task_name = task_name
        self.put_index = 0
        self.attempt = attempt


# contextvars (not threading.local): async actor calls interleave many logical
# tasks on one event-loop thread, and each asyncio task gets its own Context,
# so per-call execution context stays isolated in both thread and coroutine
# execution models.
import contextvars

_task_ctx: "contextvars.ContextVar[Optional[_TaskCtx]]" = contextvars.ContextVar(
    "ray_tpu_task_ctx", default=None
)


class Worker:
    def __init__(
        self,
        runtime: "CoreRuntime",
        job_id: JobID,
        worker_id: Optional[WorkerID] = None,
        node_id: Optional[NodeID] = None,
        is_driver: bool = True,
    ) -> None:
        self.runtime = runtime
        self.job_id = job_id
        self.worker_id = worker_id or WorkerID.from_random()
        self.node_id = node_id or NodeID.nil()
        self.is_driver = is_driver
        self.ref_counter = ReferenceCounter()
        self._driver_task_id = TaskID.for_driver(job_id)
        self._put_lock = threading.Lock()
        self._driver_put_index = 0

    # --- execution context -------------------------------------------------
    def set_task_context(
        self,
        task_id: Optional[TaskID],
        actor_id: Optional[ActorID] = None,
        task_name: str = "",
        attempt: int = 0,
    ) -> None:
        if task_id is None:
            _task_ctx.set(None)
        else:
            _task_ctx.set(_TaskCtx(task_id, actor_id, task_name, attempt))

    @property
    def current_task_id(self) -> TaskID:
        ctx = _task_ctx.get()
        return ctx.task_id if ctx is not None else self._driver_task_id

    @property
    def current_actor_id(self) -> Optional[ActorID]:
        ctx = _task_ctx.get()
        return ctx.actor_id if ctx is not None else None

    @property
    def current_task_name(self) -> str:
        ctx = _task_ctx.get()
        return ctx.task_name if ctx is not None else ""

    def next_put_id(self) -> ObjectID:
        ctx = _task_ctx.get()
        if ctx is not None and ctx.task_id is not None:
            ctx.put_index += 1
            return ObjectID.for_put(ctx.task_id, ctx.put_index)
        with self._put_lock:
            self._driver_put_index += 1
            return ObjectID.for_put(self._driver_task_id, self._driver_put_index)

    # --- reference counting -------------------------------------------------
    def add_local_ref(self, oid: ObjectID, borrowed: bool = False) -> None:
        self.ref_counter.add_local(oid, borrowed=borrowed)

    def remove_local_ref(self, oid: ObjectID) -> None:
        self.ref_counter.remove_local(oid)


_global_worker: Optional[Worker] = None
_global_lock = threading.Lock()

# ---------------------------------------------------------------------------
# Deferred-release drain: ObjectRef.__del__ may fire mid-GC while this very
# thread holds the ref-counter/store lock, so it only appends the id to
# object_ref._PENDING_RELEASES (lock-free). This thread applies the releases
# OUTSIDE any caller's critical section (see object_ref.py module comment —
# this closed the r4 monolithic-suite deadlock).
# ---------------------------------------------------------------------------
_drain_started = False


def drain_deferred_releases(max_items: int = 100_000) -> int:
    """Apply queued __del__ releases now. Called by the background drain
    thread; also useful in tests that assert prompt frees."""
    from ray_tpu.core.object_ref import _PENDING_RELEASES

    w = _global_worker
    n = 0
    while n < max_items:
        try:
            oid = _PENDING_RELEASES.popleft()
        except IndexError:
            break
        n += 1
        if w is None:
            continue  # shutdown raced: nothing to release against
        try:
            w.ref_counter.remove_local(oid)
        except Exception:  # noqa: BLE001 - releases are best-effort
            pass
    return n


def _drain_loop() -> None:
    import time

    while True:
        time.sleep(0.05)
        try:
            drain_deferred_releases()
        except Exception:  # noqa: BLE001 - the drain must never die
            pass


def _ensure_drain_thread() -> None:
    global _drain_started
    with _global_lock:
        if _drain_started:
            return
        _drain_started = True
    threading.Thread(target=_drain_loop, daemon=True,
                     name="ref-release-drain").start()


def global_worker() -> Optional[Worker]:
    return _global_worker


def require_worker() -> Worker:
    w = _global_worker
    if w is None:
        raise RuntimeError("ray_tpu.init() has not been called in this process")
    return w


def set_global_worker(worker: Optional[Worker]) -> None:
    global _global_worker
    if worker is None:
        # shutdown: apply releases against the OUTGOING worker first, so a
        # shutdown-then-init sequence can't leak them onto the next runtime
        try:
            drain_deferred_releases()
        except Exception:  # noqa: BLE001
            pass
    with _global_lock:
        _global_worker = worker
    if worker is not None:
        _ensure_drain_thread()


def maybe_register_borrowed_ref(ref: "ObjectRef") -> None:
    """Called by the deserializer when an ObjectRef is reconstructed out of a
    containing object — the borrowing hook (reference:
    reference_count.h AddBorrowedObject)."""
    # ObjectRef.__init__ already added the local ref with borrowed=True when a
    # worker exists; nothing further for the in-process plane. The cluster
    # runtime additionally notifies the owner (see core/cluster_runtime.py).
    w = _global_worker
    if w is not None and hasattr(w.runtime, "on_borrowed_ref"):
        w.runtime.on_borrowed_ref(ref)
