"""CoreRuntime: the backend interface behind the public API.

Two implementations:

- ``LocalRuntime`` (core/local_runtime.py): in-process — threads for tasks,
  dedicated threads/event-loops for actors, a zero-copy in-process object
  table. Device arrays passed between tasks stay resident in HBM (the single-
  process, multi-device JAX model). This is also the test backend.
- ``ClusterRuntime`` (core/cluster_runtime.py): multi-process/multi-node —
  control service (GCS-equivalent), per-node agents with worker pools, a
  shared-memory object plane, lease-based task submission.

The public API (ray_tpu/api.py) only ever talks to this interface.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu.core.ids import ActorID, ObjectID, PlacementGroupID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.task_spec import TaskSpec


class CoreRuntime(abc.ABC):
    is_local: bool = True

    # --- objects -----------------------------------------------------------
    @abc.abstractmethod
    def put(self, value: Any) -> ObjectRef: ...

    @abc.abstractmethod
    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float]) -> List[Any]: ...

    @abc.abstractmethod
    def wait(
        self,
        refs: Sequence[ObjectRef],
        num_returns: int,
        timeout: Optional[float],
        fetch_local: bool,
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]: ...

    @abc.abstractmethod
    def free(self, refs: Sequence[ObjectRef]) -> None: ...

    def object_sizes(self, refs: Sequence[ObjectRef]) -> List[Optional[int]]:
        """Best-effort stored size per ref (None = unknown). Used by the Data
        executor's byte-budget backpressure; not part of the public API."""
        return [None] * len(refs)

    def release(self, oid: ObjectID) -> None:
        """Refcount reached zero in this process."""

    # --- tasks -------------------------------------------------------------
    @abc.abstractmethod
    def submit_task(self, spec: TaskSpec, func: Any, args: tuple, kwargs: dict) -> List[ObjectRef]: ...

    # --- streaming generators (num_returns="streaming") --------------------
    def stream_next(self, task_hex: str, index: int, timeout: Optional[float]) -> Tuple[str, Any]:
        """Block until stream item ``index`` exists or the stream ended.
        Returns ("item", oid_hex) or ("end", total). Asking for index i
        acknowledges consumption of items < i (backpressure watermark)."""
        raise NotImplementedError

    def stream_close(self, task_hex: str) -> None:
        """Consumer abandoned the stream: unblock/stop the producer."""
        raise NotImplementedError

    @abc.abstractmethod
    def cancel(self, ref: ObjectRef, force: bool, recursive: bool) -> None: ...

    # --- actors ------------------------------------------------------------
    @abc.abstractmethod
    def create_actor(self, spec: TaskSpec, cls: Any, args: tuple, kwargs: dict) -> ActorID: ...

    @abc.abstractmethod
    def submit_actor_task(
        self, actor_id: ActorID, spec: TaskSpec, args: tuple, kwargs: dict
    ) -> List[ObjectRef]: ...

    @abc.abstractmethod
    def kill_actor(self, actor_id: ActorID, no_restart: bool) -> None: ...

    def get_named_actor(self, name: str, namespace: Optional[str]) -> ActorID:
        raise ValueError(f"Failed to look up actor '{name}'")

    def list_named_actors(self, all_namespaces: bool = False, namespace: str = "default") -> List[str]:
        return []

    # --- placement groups ---------------------------------------------------
    @abc.abstractmethod
    def create_placement_group(
        self, bundles: List[Dict[str, float]], strategy: str, name: str
    ) -> PlacementGroupID: ...

    @abc.abstractmethod
    def remove_placement_group(self, pg_id: PlacementGroupID) -> None: ...

    @abc.abstractmethod
    def placement_group_ready(self, pg_id: PlacementGroupID, timeout: Optional[float]) -> bool: ...

    # --- cluster info ------------------------------------------------------
    @abc.abstractmethod
    def nodes(self) -> List[Dict[str, Any]]: ...

    @abc.abstractmethod
    def cluster_resources(self) -> Dict[str, float]: ...

    @abc.abstractmethod
    def available_resources(self) -> Dict[str, float]: ...

    @abc.abstractmethod
    def shutdown(self) -> None: ...

    # --- kv / misc ---------------------------------------------------------
    def kv_put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def kv_get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def kv_del(self, key: str) -> None:
        raise NotImplementedError

    def kv_keys(self, prefix: str = "") -> List[str]:
        raise NotImplementedError
