"""LocalRuntime: in-process task/actor/object runtime.

The single-process backend behind ``ray_tpu.init(address="local")`` (and the
default for tests). Semantics match the cluster runtime with these documented
deltas:

- objects are stored **zero-copy in-process**: device (jax) arrays passed
  between tasks/actors stay resident in HBM — the natural single-process
  multi-device JAX model (the cluster runtime serializes through the shared-
  memory plane instead);
- tasks run on threads; ``num_cpus``/``TPU``/custom resources are accounted
  against one virtual node so scheduling/backpressure behaves like a real
  node, but there is no process isolation;
- actors are dedicated threads (or an asyncio event loop for async actors)
  consuming an ordered mailbox — submission order is execution order when
  ``max_concurrency == 1``, exactly the reference's ActorSchedulingQueue
  guarantee (reference: src/ray/core_worker/transport/actor_task_submitter.h).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import inspect
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu import exceptions as exc
from ray_tpu.core.config import config
from ray_tpu.core.ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.resources import CPU, MEMORY, OBJECT_STORE_MEMORY, TPU, PlacementGroupSchedulingStrategy, ResourceSet
from ray_tpu.core.runtime import CoreRuntime
from ray_tpu.core.task_spec import TaskSpec, TaskType
from ray_tpu.core.worker import Worker, global_worker
from ray_tpu.utils.logging import get_logger
from ray_tpu.utils import metrics

logger = get_logger("local_runtime")


class _ObjectEntry:
    __slots__ = ("future", "free_on_seal")

    def __init__(self) -> None:
        self.future: concurrent.futures.Future = concurrent.futures.Future()
        self.free_on_seal = False


class InProcessStore:
    """Object table: id -> future(value | error).

    The lock is an RLock as defense in depth: ``entry()`` allocates while
    holding it, and although ObjectRef.__del__ no longer does locked work
    (core/object_ref.py deferred releases), any OTHER finalizer running off
    a GC triggered inside the critical section must not self-deadlock."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._entries: Dict[ObjectID, _ObjectEntry] = {}
        self._closed_error: Optional[BaseException] = None

    def entry(self, oid: ObjectID, create: bool = True) -> Optional[_ObjectEntry]:
        with self._lock:
            e = self._entries.get(oid)
            if e is None and create:
                e = _ObjectEntry()
                if self._closed_error is not None:
                    # post-shutdown: never hand out a future that nothing
                    # will ever seal (an executor thread blocked on it would
                    # wedge interpreter exit via the futures atexit join)
                    e.future.set_result(_StoredError(self._closed_error))
                self._entries[oid] = e
            return e

    def close(self, error: BaseException) -> None:
        """Fail every unsealed entry and poison future ones: shutdown must
        WAKE all blocked get()/dependency waits (liveness over silence)."""
        with self._lock:
            self._closed_error = error
            entries = list(self._entries.values())
        for e in entries:
            if not e.future.done():
                e.future.set_result(_StoredError(error))

    def seal(self, oid: ObjectID, value: Any = None, error: Optional[BaseException] = None) -> None:
        e = self.entry(oid)
        if e.future.done():
            return  # idempotent (retries may re-seal)
        if error is not None:
            # store errors as values: gets inspect and raise
            e.future.set_result(_StoredError(error))
        else:
            e.future.set_result(value)
        if e.free_on_seal:
            self.free(oid)

    def free(self, oid: ObjectID) -> None:
        with self._lock:
            e = self._entries.get(oid)
            if e is not None and e.future.done():
                del self._entries[oid]
            elif e is not None:
                e.free_on_seal = True

    def contains_sealed(self, oid: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(oid)
        return e is not None and e.future.done()

    def size(self) -> int:
        with self._lock:
            return len(self._entries)


@dataclass
class _StoredError:
    error: BaseException


@dataclass
class _PendingTask:
    spec: TaskSpec
    func: Callable
    args: tuple
    kwargs: dict
    unresolved_deps: int = 0
    cancelled: bool = False
    dispatched: bool = False
    lock: threading.Lock = field(default_factory=threading.Lock)


class _ResourcePool:
    """One virtual node's resources with FIFO-ish dispatch."""

    def __init__(self, total: ResourceSet) -> None:
        self.total = total
        self.available = total.copy()
        self.lock = threading.Lock()

    def try_acquire(self, req: ResourceSet) -> bool:
        with self.lock:
            if req.is_subset_of(self.available):
                self.available.subtract(req)
                return True
            return False

    def release(self, req: ResourceSet) -> None:
        with self.lock:
            self.available.add(req)

    def feasible(self, req: ResourceSet) -> bool:
        return req.is_subset_of(self.total)


class _GrowingThreadPool:
    """Thread pool that caches idle workers but always grows when none are
    idle — tasks may block on nested get(), so a fixed-size pool would
    deadlock. The local-mode analogue of the reference's WorkerPool
    (reference: src/ray/raylet/worker_pool.h:174)."""

    def __init__(self, soft_limit: int = 256, idle_timeout: float = 30.0) -> None:
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._lock = threading.Lock()
        self._idle = 0
        self._threads = 0
        self._idle_timeout = idle_timeout
        self._soft_limit = soft_limit

    def submit(self, fn, *args) -> None:
        # Enqueue BEFORE the idle check: a worker that times out re-checks the
        # queue under the same lock, so the item is either taken by an idle
        # worker or a new thread is spawned — never stranded.
        self._q.put((fn, args))
        with self._lock:
            spawn = self._idle == 0
            if spawn:
                self._threads += 1
        if spawn:
            threading.Thread(target=self._worker, daemon=True, name="ray-tpu-exec").start()

    def _worker(self) -> None:
        while True:
            with self._lock:
                self._idle += 1
            try:
                item = self._q.get(timeout=self._idle_timeout)
                with self._lock:
                    self._idle -= 1
            except queue.Empty:
                with self._lock:
                    try:
                        item = self._q.get_nowait()
                        self._idle -= 1
                    except queue.Empty:
                        self._idle -= 1
                        self._threads -= 1
                        return
            fn, args = item
            try:
                fn(*args)
            except Exception:  # noqa: BLE001 - executor must survive task bugs
                logger.exception("executor thread: unhandled error in %r", fn)


class _ActorCall:
    __slots__ = ("spec", "func_name", "args", "kwargs", "return_ids")

    def __init__(self, spec: TaskSpec, func_name: str, args: tuple, kwargs: dict):
        self.spec = spec
        self.func_name = func_name
        self.args = args
        self.kwargs = kwargs
        self.return_ids = spec.return_ids()


class _LocalActor:
    def __init__(self, runtime: "LocalRuntime", spec: TaskSpec, cls: type, args: tuple, kwargs: dict):
        self.runtime = runtime
        self.spec = spec
        self.actor_id = spec.actor_id
        self.cls = cls
        self.init_args = args
        self.init_kwargs = kwargs
        self.instance: Any = None
        self.state = "PENDING"  # PENDING | ALIVE | DEAD
        self.death_cause: Optional[BaseException] = None
        self.mailbox: "queue.Queue[Optional[_ActorCall]]" = queue.Queue()
        self.num_pending = 0
        self.is_async = any(
            asyncio.iscoroutinefunction(m) or inspect.isasyncgenfunction(m)
            for _, m in inspect.getmembers(cls, predicate=inspect.isfunction)
        )
        self.max_concurrency = max(1, spec.max_concurrency)
        self._threads: List[threading.Thread] = []
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._kill_event = threading.Event()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        t = threading.Thread(target=self._main, name=f"actor-{self.actor_id.hex()[:8]}", daemon=True)
        self._threads.append(t)
        t.start()

    def _construct(self) -> None:
        w = global_worker()
        w.set_task_context(self.spec.task_id, self.actor_id, self.cls.__name__ + ".__init__")
        try:
            self.instance = self.cls(*self.init_args, **self.init_kwargs)
            self.state = "ALIVE"
            self.runtime._store.seal(self.spec.return_ids()[0], value=None)
        except BaseException as e:  # noqa: BLE001
            err = exc.TaskError.from_exception(e, f"{self.cls.__name__}.__init__", pid=os.getpid())
            self.state = "DEAD"
            self.death_cause = err
            self.runtime._store.seal(self.spec.return_ids()[0], error=err)
            self.runtime._on_actor_dead(self)
        finally:
            w.set_task_context(None)
            _flush_profile_local()

    def _main(self) -> None:
        self._construct()
        if self.state == "DEAD":
            self._drain_dead()
            return
        if self.is_async:
            self._loop = asyncio.new_event_loop()
            threading.Thread(target=self._loop.run_forever, daemon=True,
                             name=f"actor-loop-{self.actor_id.hex()[:8]}").start()
        pool = (
            concurrent.futures.ThreadPoolExecutor(self.max_concurrency)
            if self.max_concurrency > 1 and not self.is_async
            else None
        )
        sem = threading.Semaphore(self.max_concurrency) if self.is_async else None
        while not self._kill_event.is_set():
            call = self.mailbox.get()
            if call is None:
                break
            mfn = getattr(self.cls, call.func_name, None)
            if self.is_async and (
                asyncio.iscoroutinefunction(mfn) or inspect.isasyncgenfunction(mfn)
            ):
                sem.acquire()
                fut = asyncio.run_coroutine_threadsafe(self._run_async(call), self._loop)
                fut.add_done_callback(lambda _f: sem.release())
            elif pool is not None:
                pool.submit(self._run_sync, call)
            else:
                self._run_sync(call)
        if pool is not None:
            pool.shutdown(wait=False)
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        self._drain_dead()

    def _run_sync(self, call: _ActorCall) -> None:
        self.runtime._execute_actor_call(self, call)

    async def _run_async(self, call: _ActorCall) -> None:
        await self.runtime._execute_actor_call_async(self, call)

    def kill(self) -> None:
        self.state = "DEAD"
        self.death_cause = self.death_cause or exc.ActorDiedError(
            self.actor_id.hex(), "killed via ray_tpu.kill"
        )
        self._kill_event.set()
        self.mailbox.put(None)

    def _drain_dead(self) -> None:
        while True:
            try:
                call = self.mailbox.get_nowait()
            except queue.Empty:
                return
            if call is None:
                continue
            err = self.death_cause or exc.ActorDiedError(self.actor_id.hex(), "actor is dead")
            for oid in call.return_ids:
                self.runtime._store.seal(oid, error=err)
            self.runtime._stream_mark_error(call.spec)
            w = global_worker()
            if w is not None:
                for dep in call.spec.dependencies():
                    w.ref_counter.remove_submitted(dep)


class _PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[ResourceSet], strategy: str, name: str):
        self.id = pg_id
        self.bundles = bundles
        self.bundle_available = [b.copy() for b in bundles]
        self.strategy = strategy
        self.name = name
        self.lock = threading.Lock()

    def try_acquire(self, bundle_index: int, req: ResourceSet) -> Optional[int]:
        """Acquire from a specific bundle, or any bundle when index==-1.
        Returns the bundle index used, or None."""
        if bundle_index >= len(self.bundles):
            raise ValueError(
                f"placement_group_bundle_index={bundle_index} out of range "
                f"(group has {len(self.bundles)} bundles)"
            )
        with self.lock:
            candidates = range(len(self.bundles)) if bundle_index < 0 else [bundle_index]
            for i in candidates:
                if req.is_subset_of(self.bundle_available[i]):
                    self.bundle_available[i].subtract(req)
                    return i
            return None

    def release(self, bundle_index: int, req: ResourceSet) -> None:
        with self.lock:
            self.bundle_available[bundle_index].add(req)


_TASKS_SUBMITTED = metrics.Counter("ray_tpu_tasks_submitted_total", "Tasks submitted")
_TASKS_FINISHED = metrics.Counter("ray_tpu_tasks_finished_total", "Tasks finished", tag_keys=("state",))
_TASK_EXEC_SECONDS = metrics.Histogram("ray_tpu_task_exec_seconds", "Task execution wall time")


class LocalRuntime(CoreRuntime):
    is_local = True

    def __init__(
        self,
        num_cpus: Optional[int] = None,
        num_tpus: Optional[int] = None,
        resources: Optional[Dict[str, float]] = None,
        job_id: Optional[JobID] = None,
    ) -> None:
        if num_cpus is None:
            # Threads carry no real isolation; a too-small default only causes
            # queueing, so floor at 8 for usable parallelism on small hosts.
            num_cpus = max(os.cpu_count() or 1, 8)
        if num_tpus is None:
            num_tpus = _detect_tpu_chips()
        total = ResourceSet({CPU: num_cpus, **(resources or {})})
        if num_tpus:
            total[TPU] = float(num_tpus)
        try:
            import psutil

            total[MEMORY] = float(psutil.virtual_memory().available)
        except Exception:
            total[MEMORY] = 8 * 1024**3
        total[OBJECT_STORE_MEMORY] = float(config.object_store_memory_bytes)
        self.node_id = NodeID.from_random()
        total[f"node:{self.node_id.hex()}"] = 1.0
        self._pool = _ResourcePool(total)
        self._store = InProcessStore()
        self._job_id = job_id or JobID.from_int(1)
        self._pending: List[_PendingTask] = []
        self._pending_lock = threading.Lock()
        self._tasks: Dict[TaskID, _PendingTask] = {}
        self._actors: Dict[ActorID, _LocalActor] = {}
        self._named_actors: Dict[Tuple[str, str], ActorID] = {}
        self._actor_lock = threading.Lock()
        self._pgs: Dict[PlacementGroupID, _PlacementGroup] = {}
        # streaming generators: task hex -> in-process stream directory entry
        self._streams: Dict[str, Any] = {}
        self._shutdown = False
        self._started_at = time.time()
        # Reusable executor threads (the WorkerPool analogue). Sized well
        # above the CPU resource cap because tasks may block in nested get();
        # _GrowingThreadPool spawns past max_workers rather than deadlock.
        self._exec_pool = _GrowingThreadPool(soft_limit=256)

    # ------------------------------------------------------------------ objects
    def put(self, value: Any) -> ObjectRef:
        w = global_worker()
        oid = w.next_put_id()
        self._store.seal(oid, value=value)
        return ObjectRef(oid)

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float]) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        out: List[Any] = []
        for ref in refs:
            e = self._store.entry(ref.id)
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            try:
                value = e.future.result(timeout=remaining)
            except concurrent.futures.TimeoutError:
                raise exc.GetTimeoutError(
                    f"get() timed out after {timeout}s waiting for {ref.id.hex()[:16]}"
                ) from None
            if isinstance(value, _StoredError):
                err = value.error
                if isinstance(err, exc.TaskError):
                    raise err.as_instanceof_cause()
                raise err
            out.append(value)
        return out

    def wait(
        self,
        refs: Sequence[ObjectRef],
        num_returns: int,
        timeout: Optional[float],
        fetch_local: bool,
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        futures = [self._store.entry(r.id).future for r in refs]
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            pending = [f for f in futures if not f.done()]
            n_done = len(futures) - len(pending)
            if n_done >= num_returns or not pending:
                break
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            if remaining == 0.0:
                break
            concurrent.futures.wait(
                pending, timeout=remaining, return_when=concurrent.futures.FIRST_COMPLETED
            )
        ready, not_ready = [], []
        for r, f in zip(refs, futures):
            (ready if f.done() and len(ready) < num_returns else not_ready).append(r)
        return ready, not_ready

    def free(self, refs: Sequence[ObjectRef]) -> None:
        for r in refs:
            self._store.free(r.id)

    def object_sizes(self, refs: Sequence[ObjectRef]) -> List[Optional[int]]:
        out: List[Optional[int]] = []
        for r in refs:
            e = self._store.entry(r.id, create=False)
            size = None
            if e is not None and e.future.done():
                v = e.future.result()
                size = getattr(v, "nbytes", None)
                if size is None:
                    try:
                        size = len(v)  # bytes-like
                    except TypeError:
                        size = None
            out.append(size)
        return out

    def release(self, oid: ObjectID) -> None:
        # Zero refcount in the only process: drop the value.
        self._store.free(oid)

    # ------------------------------------------------------------------- tasks
    def submit_task(self, spec: TaskSpec, func: Callable, args: tuple, kwargs: dict) -> List[ObjectRef]:
        if self._shutdown:
            raise RuntimeError("runtime is shut down")
        if not self._feasible(spec):
            raise ValueError(
                f"Task {spec.name} requires {dict(spec.resources)} which exceeds cluster capacity "
                f"{dict(self._pool.total)}"
            )
        _TASKS_SUBMITTED.inc()
        if spec.generator:
            from ray_tpu.core.streaming import LocalStreamState

            self._streams[spec.task_id.binary().hex()] = LocalStreamState()
            return_refs: List[ObjectRef] = []
        else:
            return_refs = [ObjectRef(oid) for oid in spec.return_ids()]
        task = _PendingTask(spec=spec, func=func, args=args, kwargs=kwargs)
        self._tasks[spec.task_id] = task
        w = global_worker()
        deps = spec.dependencies()
        for dep in deps:
            w.ref_counter.add_submitted(dep)
        task.unresolved_deps = len(deps)
        if deps:
            for dep in deps:
                e = self._store.entry(dep)
                e.future.add_done_callback(lambda _f, t=task: self._dep_resolved(t))
        else:
            self._enqueue(task)
        return return_refs

    def _dep_resolved(self, task: _PendingTask) -> None:
        with task.lock:
            task.unresolved_deps -= 1
            if task.unresolved_deps > 0 or task.dispatched:
                return
        self._enqueue(task)

    def _enqueue(self, task: _PendingTask) -> None:
        with self._pending_lock:
            self._pending.append(task)
        self._drain_pending()

    def _acquire_for(self, spec: TaskSpec) -> Optional[Tuple[Optional[_PlacementGroup], int]]:
        """Acquire resources for a task: from its placement-group bundle when
        PG-scheduled, else from the node pool. Returns (pg, bundle_idx)."""
        strat = spec.strategy
        if isinstance(strat, PlacementGroupSchedulingStrategy) and strat.placement_group is not None:
            pg = self._pgs.get(getattr(strat.placement_group, "id", None))
            if pg is None:
                return None
            idx = pg.try_acquire(strat.placement_group_bundle_index, spec.resources)
            if idx is None:
                return None
            return (pg, idx)
        if self._pool.try_acquire(spec.resources):
            return (None, -1)
        return None

    def _drain_pending(self) -> None:
        while True:
            dispatched_one = False
            with self._pending_lock:
                for i, task in enumerate(self._pending):
                    with task.lock:
                        if task.dispatched or task.unresolved_deps > 0:
                            continue
                        if task.cancelled:
                            task.dispatched = True
                            self._pending.pop(i)
                            err = exc.TaskCancelledError(task.spec.task_id.hex())
                            for oid in task.spec.return_ids():
                                self._store.seal(oid, error=err)
                            self._stream_mark_error(task.spec)
                            self._tasks.pop(task.spec.task_id, None)
                            dispatched_one = True
                            break
                        grant = self._acquire_for(task.spec)
                        if grant is None:
                            continue
                        task.dispatched = True
                    self._pending.pop(i)
                    self._exec_pool.submit(self._execute_task, task, grant)
                    dispatched_one = True
                    break
            if not dispatched_one:
                return

    def _resolve_args(self, args: tuple, kwargs: dict) -> Tuple[tuple, dict, Optional[BaseException]]:
        def resolve(v: Any) -> Any:
            if isinstance(v, ObjectRef):
                value = self._store.entry(v.id).future.result()
                if isinstance(value, _StoredError):
                    raise _DepFailed(value.error)
                return value
            return v

        try:
            r_args = tuple(resolve(a) for a in args)
            r_kwargs = {k: resolve(v) for k, v in kwargs.items()}
            return r_args, r_kwargs, None
        except _DepFailed as d:
            return (), {}, d.error

    def _execute_task(self, task: _PendingTask, grant: Tuple[Optional[_PlacementGroup], int]) -> None:
        spec = task.spec
        w = global_worker()
        return_ids = spec.return_ids()
        attempts = 0
        try:
            while True:
                if task.cancelled:
                    err: BaseException = exc.TaskCancelledError(spec.task_id.hex())
                    for oid in return_ids:
                        self._store.seal(oid, error=err)
                    self._stream_mark_error(spec)
                    _TASKS_FINISHED.inc(tags={"state": "cancelled"})
                    return
                r_args, r_kwargs, dep_err = self._resolve_args(task.args, task.kwargs)
                if dep_err is not None:
                    for oid in return_ids:
                        self._store.seal(oid, error=dep_err)
                    self._stream_mark_error(spec)
                    _TASKS_FINISHED.inc(tags={"state": "dep_failed"})
                    return
                w.set_task_context(spec.task_id, None, spec.name, attempt=attempts)
                start = time.monotonic()
                try:
                    result = task.func(*r_args, **r_kwargs)
                    if spec.generator:
                        self._drive_generator(spec, result)
                    else:
                        self._store_returns(spec, return_ids, result)
                    _TASK_EXEC_SECONDS.observe(time.monotonic() - start)
                    _TASKS_FINISHED.inc(tags={"state": "ok"})
                    return
                except BaseException as e:  # noqa: BLE001
                    attempts += 1
                    retryable = spec.retry_exceptions and attempts <= spec.max_retries
                    if retryable and not spec.generator:
                        logger.info("Task %s failed (attempt %d), retrying: %s", spec.name, attempts, e)
                        continue
                    err = exc.TaskError.from_exception(e, spec.name, pid=os.getpid(),
                                                       node_id=self.node_id.hex())
                    for oid in return_ids:
                        self._store.seal(oid, error=err)
                    self._stream_mark_error(spec)
                    _TASKS_FINISHED.inc(tags={"state": "error"})
                    return
                finally:
                    w.set_task_context(None)
            _flush_profile_local()
        finally:
            pg, idx = grant
            if pg is not None:
                pg.release(idx, spec.resources)
            else:
                self._pool.release(spec.resources)
            for dep in spec.dependencies():
                w.ref_counter.remove_submitted(dep)
            self._tasks.pop(spec.task_id, None)
            self._drain_pending()

    def _store_returns(self, spec: TaskSpec, return_ids: List[ObjectID], result: Any) -> None:
        if spec.num_returns == 1:
            self._store.seal(return_ids[0], value=result)
            return
        if not isinstance(result, (tuple, list)) or len(result) != spec.num_returns:
            err = exc.TaskError(
                spec.name,
                f"Task declared num_returns={spec.num_returns} but returned "
                f"{type(result).__name__} of length "
                f"{len(result) if isinstance(result, (tuple, list)) else 'n/a'}",
            )
            for oid in return_ids:
                self._store.seal(oid, error=err)
            return
        for oid, v in zip(return_ids, result):
            self._store.seal(oid, value=v)

    # ------------------------------------------------------------- streaming
    def _drive_generator(self, spec: TaskSpec, result: Any) -> None:
        """Producer side of num_returns='streaming': seal each yielded item
        as its own object, report it to the stream directory, respect
        consumer backpressure. Mid-stream exceptions become an error ITEM
        followed by end-of-stream (no retries of partially-consumed streams)."""
        import inspect

        from ray_tpu.core.streaming import stream_item_id

        task_hex = spec.task_id.binary().hex()
        st = self._streams.get(task_hex)
        if inspect.isasyncgen(result):
            from ray_tpu.core.streaming import iter_async_gen

            result = iter_async_gen(result)
        elif not inspect.isgenerator(result):
            raise TypeError(
                f"num_returns='streaming' requires a generator function; "
                f"{spec.name} returned {type(result).__name__}"
            )
        if st is None:  # stream already closed+reaped before execution began
            result.close()
            return
        idx = 0
        try:
            for item in result:
                oid = stream_item_id(task_hex, idx)
                self._store.seal(oid, value=item)
                alive = st.put(idx, oid.hex(), spec.generator_backpressure)
                idx += 1
                if not alive:
                    result.close()
                    break
        except BaseException as e:  # noqa: BLE001 - delivered as an error item
            err = exc.TaskError.from_exception(e, spec.name, pid=os.getpid(),
                                               node_id=self.node_id.hex())
            oid = stream_item_id(task_hex, idx)
            self._store.seal(oid, error=err)
            st.put(idx, oid.hex(), 0)
            st.end(idx + 1)
            return
        st.end(idx)

    async def _drive_async_generator(self, spec: TaskSpec, agen: Any) -> None:
        """Async-actor variant of _drive_generator (async-generator methods).
        Backpressure waits run off-loop so other coroutine calls proceed."""
        from ray_tpu.core.streaming import stream_item_id

        task_hex = spec.task_id.binary().hex()
        st = self._streams.get(task_hex)
        if st is None:
            await agen.aclose()
            return
        loop = asyncio.get_running_loop()
        idx = 0
        try:
            async for item in agen:
                oid = stream_item_id(task_hex, idx)
                self._store.seal(oid, value=item)
                alive = await loop.run_in_executor(
                    None, st.put, idx, oid.hex(), spec.generator_backpressure
                )
                idx += 1
                if not alive:
                    await agen.aclose()
                    break
        except BaseException as e:  # noqa: BLE001 - delivered as an error item
            err = exc.TaskError.from_exception(e, spec.name, pid=os.getpid(),
                                               node_id=self.node_id.hex())
            oid = stream_item_id(task_hex, idx)
            self._store.seal(oid, error=err)
            st.put(idx, oid.hex(), 0)
            st.end(idx + 1)
            return
        st.end(idx)

    def _stream_mark_error(self, spec: TaskSpec) -> None:
        """A pre-execution failure sealed error objects into the fixed
        returns; surface it to a streaming consumer as item 0 + end."""
        if not spec.generator:
            return
        st = self._streams.get(spec.task_id.binary().hex())
        if st is None or st.finished:
            return
        st.put(0, spec.return_ids()[0].hex(), 0)
        st.end(1)

    def stream_next(self, task_hex: str, index: int, timeout: Optional[float]):
        st = self._streams.get(task_hex)
        if st is None:
            raise ValueError(f"unknown or closed stream {task_hex[:16]}")
        try:
            kind, value = st.next(index, timeout)
        except TimeoutError:
            raise exc.GetTimeoutError(
                f"stream item {index} of {task_hex[:16]} not ready in {timeout}s"
            ) from None
        if kind == "end" and index >= value:
            self._streams.pop(task_hex, None)  # fully consumed: reap state
        return kind, value

    def stream_close(self, task_hex: str) -> None:
        st = self._streams.pop(task_hex, None)
        if st is None:
            return
        st.close()
        with st.cond:
            for idx, oid_hex in st.items.items():
                if idx >= st.delivered:  # never handed to the consumer
                    self._store.free(ObjectID.from_hex(oid_hex))

    def cancel(self, ref: ObjectRef, force: bool, recursive: bool) -> None:
        task = self._tasks.get(ref.id.task_id())
        if task is None:
            return
        task.cancelled = True
        # Lock order everywhere else is _pending_lock -> task.lock
        # (_drain_pending); never nest _pending_lock inside task.lock here or
        # a concurrent cancel + dispatch can deadlock the whole runtime.
        with task.lock:
            claimed = not task.dispatched
            if claimed:
                task.dispatched = True
        if claimed:
            err = exc.TaskCancelledError(task.spec.task_id.hex())
            for oid in task.spec.return_ids():
                self._store.seal(oid, error=err)
            self._stream_mark_error(task.spec)
            with self._pending_lock:
                if task in self._pending:
                    self._pending.remove(task)

    # ------------------------------------------------------------------ actors
    def create_actor(self, spec: TaskSpec, cls: type, args: tuple, kwargs: dict) -> ActorID:
        if not self._feasible(spec):
            raise ValueError(
                f"Actor {spec.name} requires {dict(spec.resources)} which exceeds capacity "
                f"{dict(self._pool.total)}"
            )
        grant = None
        deadline = time.monotonic() + 60.0
        while grant is None:
            grant = self._acquire_for(spec)
            if grant is None:
                if time.monotonic() > deadline:
                    raise exc.PlacementGroupError(
                        f"Could not acquire resources {dict(spec.resources)} for actor {spec.name}"
                    )
                time.sleep(0.005)
        actor = _LocalActor(self, spec, cls, args, kwargs)
        actor._grant = grant  # released on death
        with self._actor_lock:
            name = (spec.runtime_env or {}).get("__actor_name__")
            if name:
                ns = (spec.runtime_env or {}).get("__actor_namespace__", "default")
                if (ns, name) in self._named_actors:
                    pg, idx = grant
                    (pg.release(idx, spec.resources) if pg else self._pool.release(spec.resources))
                    raise ValueError(f"Actor name '{name}' already taken in namespace '{ns}'")
                self._named_actors[(ns, name)] = spec.actor_id
            self._actors[spec.actor_id] = actor
        # creation return: sealed by actor thread
        ObjectRef(spec.return_ids()[0])  # register ref for the creation object
        actor.start()
        return spec.actor_id

    def submit_actor_task(self, actor_id: ActorID, spec: TaskSpec, args: tuple, kwargs: dict) -> List[ObjectRef]:
        actor = self._actors.get(actor_id)
        if spec.generator:
            from ray_tpu.core.streaming import LocalStreamState

            self._streams[spec.task_id.binary().hex()] = LocalStreamState()
            refs: List[ObjectRef] = []
        else:
            refs = [ObjectRef(oid) for oid in spec.return_ids()]
        if actor is None:
            err = exc.ActorDiedError(actor_id.hex(), "unknown or shut down actor")
            for oid in spec.return_ids():
                self._store.seal(oid, error=err)
            self._stream_mark_error(spec)
            return refs
        if spec.max_pending_calls > 0 and actor.mailbox.qsize() >= spec.max_pending_calls:
            raise exc.PendingCallsLimitExceededError(
                f"Actor {actor_id.hex()[:8]} has {actor.mailbox.qsize()} pending calls "
                f"(max_pending_calls={spec.max_pending_calls})"
            )
        w = global_worker()
        for dep in spec.dependencies():
            w.ref_counter.add_submitted(dep)
        call = _ActorCall(spec, spec.actor_method_name, args, kwargs)
        if actor.state == "DEAD":
            err = actor.death_cause or exc.ActorDiedError(actor_id.hex(), "actor is dead")
            for oid in spec.return_ids():
                self._store.seal(oid, error=err)
            self._stream_mark_error(spec)
            return refs
        actor.mailbox.put(call)
        # Re-check after enqueue: if the actor died between the check and the
        # put, the consumer loop may already have drained — drain again so the
        # call's returns are error-sealed rather than hanging (seal is
        # idempotent, so double-drain is safe).
        if actor.state == "DEAD":
            actor._drain_dead()
        return refs

    def _execute_actor_call(self, actor: _LocalActor, call: _ActorCall) -> None:
        w = global_worker()
        spec = call.spec
        r_args, r_kwargs, dep_err = self._resolve_args(call.args, call.kwargs)
        if dep_err is not None:
            for oid in call.return_ids:
                self._store.seal(oid, error=dep_err)
            self._stream_mark_error(spec)
            for dep in spec.dependencies():
                w.ref_counter.remove_submitted(dep)
            return
        w.set_task_context(spec.task_id, actor.actor_id, spec.name)
        start = time.monotonic()
        try:
            if call.func_name == "__rtpu_channel_loop__":
                # compiled-DAG stage loop hook (ray_tpu/dag/compiled.py)
                import functools as _functools

                from ray_tpu.dag.compiled import channel_loop

                method = _functools.partial(channel_loop, actor.instance)
            else:
                method = getattr(actor.instance, call.func_name)
            result = method(*r_args, **r_kwargs)
            if spec.generator:
                self._drive_generator(spec, result)
            else:
                self._store_returns(spec, call.return_ids, result)
            _TASK_EXEC_SECONDS.observe(time.monotonic() - start)
        except BaseException as e:  # noqa: BLE001
            err = exc.TaskError.from_exception(e, spec.name, pid=os.getpid(), node_id=self.node_id.hex())
            for oid in call.return_ids:
                self._store.seal(oid, error=err)
            self._stream_mark_error(spec)
            if isinstance(e, (SystemExit, KeyboardInterrupt)):
                actor.kill()
        finally:
            w.set_task_context(None)
            _flush_profile_local()
            for dep in spec.dependencies():
                w.ref_counter.remove_submitted(dep)

    async def _execute_actor_call_async(self, actor: _LocalActor, call: _ActorCall) -> None:
        w = global_worker()
        spec = call.spec
        loop = asyncio.get_running_loop()
        # Resolve ObjectRef args off-loop so dependency waits don't stall
        # other concurrent coroutine calls on this actor.
        r_args, r_kwargs, dep_err = await loop.run_in_executor(
            None, self._resolve_args, call.args, call.kwargs
        )
        if dep_err is not None:
            for oid in call.return_ids:
                self._store.seal(oid, error=dep_err)
            for dep in spec.dependencies():
                w.ref_counter.remove_submitted(dep)
            return
        try:
            import inspect

            method = getattr(actor.instance, call.func_name)
            w.set_task_context(spec.task_id, actor.actor_id, spec.name)
            if spec.generator and inspect.isasyncgenfunction(method):
                await self._drive_async_generator(spec, method(*r_args, **r_kwargs))
            else:
                result = await method(*r_args, **r_kwargs)
                if spec.generator:
                    # run the (sync) generator off-loop: its body is user code
                    await asyncio.get_running_loop().run_in_executor(
                        None, self._drive_generator, spec, result
                    )
                else:
                    self._store_returns(spec, call.return_ids, result)
        except BaseException as e:  # noqa: BLE001
            err = exc.TaskError.from_exception(e, spec.name, pid=os.getpid(), node_id=self.node_id.hex())
            for oid in call.return_ids:
                self._store.seal(oid, error=err)
            self._stream_mark_error(spec)
        finally:
            w.set_task_context(None)
            _flush_profile_local()
            for dep in spec.dependencies():
                w.ref_counter.remove_submitted(dep)

    def kill_actor(self, actor_id: ActorID, no_restart: bool) -> None:
        actor = self._actors.get(actor_id)
        if actor is None:
            return
        actor.kill()
        self._on_actor_dead(actor)

    def _on_actor_dead(self, actor: _LocalActor) -> None:
        grant = getattr(actor, "_grant", None)
        if grant is not None:
            actor._grant = None
            pg, idx = grant
            if pg is not None:
                pg.release(idx, actor.spec.resources)
            else:
                self._pool.release(actor.spec.resources)
            self._drain_pending()
        with self._actor_lock:
            for key, aid in list(self._named_actors.items()):
                if aid == actor.actor_id:
                    del self._named_actors[key]

    def get_named_actor(self, name: str, namespace: Optional[str]) -> ActorID:
        ns = namespace or "default"
        with self._actor_lock:
            aid = self._named_actors.get((ns, name))
        if aid is None:
            raise ValueError(f"Failed to look up actor '{name}' in namespace '{ns}'")
        return aid

    def list_named_actors(self, all_namespaces: bool = False, namespace: str = "default") -> List[str]:
        with self._actor_lock:
            if all_namespaces:
                return [name for (_ns, name) in self._named_actors]
            return [name for (ns, name) in self._named_actors if ns == namespace]

    def actor_state(self, actor_id: ActorID) -> str:
        a = self._actors.get(actor_id)
        return a.state if a else "DEAD"

    # --------------------------------------------------------------- placement
    def create_placement_group(self, bundles: List[Dict[str, float]], strategy: str, name: str) -> PlacementGroupID:
        pg_id = PlacementGroupID.of(self._job_id)
        sets = [ResourceSet(b) for b in bundles]
        need = ResourceSet()
        for s in sets:
            need.add(s)
        # Reserve against the node pool (single virtual node: every strategy
        # is satisfiable iff the total fits).
        if not self._pool.try_acquire(need):
            if not need.is_subset_of(self._pool.total):
                raise exc.PlacementGroupError(
                    f"Infeasible placement group: needs {dict(need)}, cluster has {dict(self._pool.total)}"
                )
            # feasible but busy: reserve lazily by waiting
            deadline = time.monotonic() + 60.0
            while not self._pool.try_acquire(need):
                if time.monotonic() > deadline:
                    raise exc.PlacementGroupError("Timed out reserving placement group resources")
                time.sleep(0.005)
        self._pgs[pg_id] = _PlacementGroup(pg_id, sets, strategy, name)
        return pg_id

    def remove_placement_group(self, pg_id: PlacementGroupID) -> None:
        pg = self._pgs.pop(pg_id, None)
        if pg is not None:
            total = ResourceSet()
            for b in pg.bundles:
                total.add(b)
            self._pool.release(total)
            self._drain_pending()

    def placement_group_ready(self, pg_id: PlacementGroupID, timeout: Optional[float]) -> bool:
        return pg_id in self._pgs

    def placement_group_table(self) -> Dict[str, Dict]:
        return {
            pg.id.hex(): {
                "name": pg.name,
                "strategy": pg.strategy,
                "bundles": [dict(b) for b in pg.bundles],
                "state": "CREATED",
            }
            for pg in self._pgs.values()
        }

    # ----------------------------------------------------------------- cluster
    def _feasible(self, spec: TaskSpec) -> bool:
        strat = spec.strategy
        if isinstance(strat, PlacementGroupSchedulingStrategy) and strat.placement_group is not None:
            pg = self._pgs.get(getattr(strat.placement_group, "id", None))
            if pg is None:
                return False
            idx = strat.placement_group_bundle_index
            if idx >= len(pg.bundles):
                raise ValueError(
                    f"placement_group_bundle_index={idx} out of range "
                    f"(group has {len(pg.bundles)} bundles)"
                )
            if idx >= 0:
                return spec.resources.is_subset_of(pg.bundles[idx])
            return any(spec.resources.is_subset_of(b) for b in pg.bundles)
        return self._pool.feasible(spec.resources)

    def nodes(self) -> List[Dict[str, Any]]:
        return [
            {
                "NodeID": self.node_id.hex(),
                "Alive": True,
                "NodeManagerAddress": "127.0.0.1",
                "Resources": dict(self._pool.total),
                "Labels": {},
                "is_head": True,
            }
        ]

    def cluster_resources(self) -> Dict[str, float]:
        return dict(self._pool.total)

    def available_resources(self) -> Dict[str, float]:
        with self._pool.lock:
            return dict(self._pool.available)

    def shutdown(self) -> None:
        self._shutdown = True
        for actor in list(self._actors.values()):
            actor.kill()
        self._actors.clear()
        self._pgs.clear()
        # wake every blocked waiter (get(), _resolve_args, nested task
        # dependencies): leaving them parked would block interpreter exit —
        # concurrent.futures' atexit joins ALL executor threads, including
        # an actor-pool thread stuck resolving an object that will now never
        # be sealed (observed as a post-suite interpreter hang, r5)
        self._store.close(exc.RayTpuError("ray_tpu runtime is shut down"))

    # ---------------------------------------------------------------------- kv
    _kv: Dict[str, bytes]

    def kv_put(self, key: str, value: bytes) -> None:
        if not hasattr(self, "_kv"):
            self._kv = {}
        self._kv[key] = value

    def kv_get(self, key: str) -> Optional[bytes]:
        return getattr(self, "_kv", {}).get(key)

    def kv_del(self, key: str) -> None:
        getattr(self, "_kv", {}).pop(key, None)

    def kv_keys(self, prefix: str = "") -> List[str]:
        return [k for k in getattr(self, "_kv", {}) if k.startswith(prefix)]


class _DepFailed(Exception):
    def __init__(self, error: BaseException):
        self.error = error


def _detect_tpu_chips() -> int:
    """Count TPU chips without forcing a jax import/device init."""
    import sys

    if "jax" in sys.modules:
        try:
            import jax

            return sum(1 for d in jax.devices() if d.platform == "tpu")
        except Exception:
            return 0
    return 0


def _flush_profile_local() -> None:
    """Move any ray_tpu.profile() spans into the local-runtime span log
    (no agent in-process; read back via ray_tpu.profiling.local_spans())."""
    try:
        from ray_tpu import profiling

        profiling.flush_local()
    except Exception:  # noqa: BLE001 - observability is best-effort
        pass
