from ray_tpu.job.sdk import JobStatus, JobSubmissionClient

__all__ = ["JobSubmissionClient", "JobStatus"]
