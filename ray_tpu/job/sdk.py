"""Job submission SDK.

Reference capability: python/ray/dashboard/modules/job/sdk.py:35
(JobSubmissionClient, submit_job:125, get_job_status, get_job_logs,
stop_job) — there an HTTP client against the dashboard's job head; here a
thin RPC client against the head node agent (the job supervisor), with job
metadata mirrored in GCS KV so status is queryable from anywhere.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from ray_tpu.core.rpc import SyncRpcClient


def list_jobs_from_gcs(gcs: SyncRpcClient) -> List[Dict[str, Any]]:
    """Single source of truth for the job-KV schema (shared by the SDK and
    the state API)."""
    out = []
    for key in gcs.call("kv_keys", prefix="job:"):
        raw = gcs.call("kv_get", key=key)
        if raw:
            try:
                out.append(json.loads(raw))
            except ValueError:
                pass
    return out


class JobStatus:
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


class JobSubmissionClient:
    """address = GCS host:port (jobs run on the head node's agent)."""

    def __init__(self, address: str):
        self.gcs = SyncRpcClient(address)
        nodes = [n for n in self.gcs.call("get_nodes") if n["Alive"]]
        if not nodes:
            raise RuntimeError(f"no alive nodes at {address}")
        head = next((n for n in nodes if n.get("is_head")), nodes[0])
        self.agent = SyncRpcClient(head["NodeManagerAddress"])

    def submit_job(
        self,
        entrypoint: str,
        env: Optional[Dict[str, str]] = None,
        working_dir: Optional[str] = None,
    ) -> str:
        """entrypoint: shell command, e.g. "python train.py --epochs 3".
        The driver process gets RAY_TPU_ADDRESS so ray_tpu.init() inside it
        connects to this cluster."""
        return self.agent.call(
            "submit_job", entrypoint=entrypoint, env=env, working_dir=working_dir
        )

    def get_job_status(self, job_id: str) -> Optional[str]:
        raw = self.gcs.call("kv_get", key=f"job:{job_id}")
        if raw is None:
            return None
        return json.loads(raw)["status"]

    def get_job_info(self, job_id: str) -> Optional[Dict[str, Any]]:
        raw = self.gcs.call("kv_get", key=f"job:{job_id}")
        return json.loads(raw) if raw else None

    def get_job_logs(self, job_id: str, tail_bytes: int = 65536) -> str:
        return self.agent.call(
            "job_logs", job_id=job_id, tail_bytes=tail_bytes
        ).decode(errors="replace")

    def read_job_logs_from(self, job_id: str, offset: int,
                           max_bytes: int = 65536) -> tuple:
        """Absolute-offset streaming read: returns (text, next_offset).
        Followers use this instead of the sliding tail (which silently stops
        advancing once a log exceeds the tail window)."""
        out = self.agent.call(
            "job_logs", job_id=job_id, tail_bytes=max_bytes, offset=offset
        )
        return out["data"].decode(errors="replace"), out["offset"]

    def stop_job(self, job_id: str) -> bool:
        return self.agent.call("stop_job", job_id=job_id)

    def list_jobs(self) -> List[Dict[str, Any]]:
        return list_jobs_from_gcs(self.gcs)

    def wait_until_finished(self, job_id: str, timeout: float = 300.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in (JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.STOPPED):
                return status
            time.sleep(0.25)
        raise TimeoutError(f"job {job_id} still running after {timeout}s")

    def close(self) -> None:
        self.agent.close()
        self.gcs.close()
