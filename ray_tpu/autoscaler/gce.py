"""GCE TPU-VM cloud provider (skeleton behind the CloudProvider interface).

Reference capability: python/ray/autoscaler/_private/gcp/node_provider.py +
tpu_command_runner.py. A TPU slice is provisioned as ONE queued-resource /
tpu-vm create call; every host of the slice then starts a node agent joining
the same GCS with a shared slice label — exactly the contract
FakeCloudProvider simulates, so the autoscaler/InstanceManager logic above
is identical in CI and on a real cloud.

This provider shells out to ``gcloud`` (no cloud SDK dependency baked into
the image); it raises a clear error when gcloud is unavailable. Methods are
deliberately thin: each maps to one control-plane call, and poll() derives
instance state from ``gcloud compute tpus tpu-vm list``.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import time
import uuid
from typing import Any, Dict, List

from ray_tpu.autoscaler.instance_manager import (
    FAILED, REQUESTED, RUNNING, STARTING, TERMINATED, CloudProvider, Instance,
)
from ray_tpu.utils.logging import get_logger

logger = get_logger("autoscaler.gce")

# gcloud state -> instance-manager state
_STATE_MAP = {
    "CREATING": STARTING,
    "READY": RUNNING,
    "REPAIRING": STARTING,
    "DELETING": TERMINATED,
    "TERMINATED": TERMINATED,
    "PREEMPTED": FAILED,
}


class GceTpuProvider(CloudProvider):
    """TPU-VM slices via gcloud (one create per slice; accelerator_type like
    "v5litepod-16" determines the host count)."""

    def __init__(self, project: str, zone: str, gcs_address: str,
                 runtime_version: str = "tpu-ubuntu2204-base",
                 startup_script: str = ""):
        if shutil.which("gcloud") is None:
            raise RuntimeError(
                "GceTpuProvider requires the gcloud CLI on PATH. Install the "
                "Google Cloud SDK, or use FakeCloudProvider for local testing."
            )
        self.project = project
        self.zone = zone
        self.gcs_address = gcs_address
        self.runtime_version = runtime_version
        # startup script: every host starts a node agent pointed at the GCS
        # with the slice label (mirrors FakeCloudProvider._launch)
        self.startup_script = startup_script or (
            "python -m ray_tpu.core.node.agent "
            f"--gcs {gcs_address} "
            "--label ray_tpu.io/slice=$(curl -s -H 'Metadata-Flavor: Google' "
            "http://metadata/computeMetadata/v1/instance/attributes/"
            "instance-id)"
        )
        self._instances: Dict[str, Instance] = {}
        # groups whose gcloud delete failed: retried by poll() until it
        # lands (the group is already drained, so nothing else re-triggers
        # terminate() for it). gid -> earliest next retry time; the backoff
        # keeps a hanging delete (300s subprocess timeout) from stalling
        # every poll cycle.
        self._pending_deletes: Dict[str, float] = {}
        self.delete_retry_s = 60.0
        # group id -> consecutive polls absent from `tpu-vm list` (grace
        # against transiently partial/empty list responses)
        self._missing_polls: Dict[str, int] = {}

    def _gcloud(self, *args: str) -> Any:
        try:
            out = subprocess.run(
                ["gcloud", *args, "--project", self.project, "--zone",
                 self.zone, "--format", "json"],
                capture_output=True, text=True, timeout=300,
            )
        except subprocess.SubprocessError as e:
            # normalize hangs (TimeoutExpired) etc. into the RuntimeError the
            # retry machinery catches — a hung delete must still enter
            # _pending_deletes
            raise RuntimeError(f"gcloud {' '.join(args[:3])}: {e!r}") from e
        if out.returncode != 0:
            raise RuntimeError(f"gcloud {' '.join(args[:3])}: {out.stderr[:500]}")
        return json.loads(out.stdout or "null")

    def request_group(self, group_config: Dict[str, Any]) -> List[Instance]:
        accel = group_config.get("accelerator_type", "v5litepod-16")
        hosts = int(group_config.get("hosts", 4))
        name = f"rtpu-{uuid.uuid4().hex[:8]}"
        self._gcloud(
            "compute", "tpus", "tpu-vm", "create", name,
            "--accelerator-type", accel,
            "--version", group_config.get("runtime_version", self.runtime_version),
            "--metadata", f"startup-script={self.startup_script},instance-id={name}",
        )
        out = []
        for h in range(hosts):
            inst = Instance(
                instance_id=f"{name}/{h}", group_id=name,
                node_config=dict(group_config), state=REQUESTED,
            )
            self._instances[inst.instance_id] = inst
            out.append(inst)
        return out

    def poll(self) -> None:
        try:
            listed = self._gcloud("compute", "tpus", "tpu-vm", "list") or []
        except RuntimeError:
            logger.exception("tpu-vm list failed")
            return
        states = {n["name"].rsplit("/", 1)[-1]: n.get("state", "") for n in listed}
        live_groups = {i.group_id for i in self._instances.values()
                       if i.state not in (TERMINATED, FAILED)}
        for gid in live_groups:
            if gid in states:
                self._missing_polls.pop(gid, None)
            else:
                self._missing_polls[gid] = self._missing_polls.get(gid, 0) + 1
        # retry failed deletes (the group was already drained, so no other
        # path re-issues them). A pending group confirmed absent — the same
        # 2-consecutive-poll grace as below, so one partial listing can't
        # leak a live VM — is already gone server-side; don't shell out a
        # doomed NOT_FOUND delete for it.
        for gid, next_retry in list(self._pending_deletes.items()):
            if gid not in states and self._missing_polls.get(gid, 0) >= 2:
                self._pending_deletes.pop(gid, None)
                self._finish_group(gid)
            elif gid in states and time.monotonic() >= next_retry:
                if self._try_delete(gid):
                    self._pending_deletes.pop(gid, None)
                    self._finish_group(gid)
                else:
                    # recompute the clock AFTER the attempt: a delete that
                    # blocked to its 300s subprocess timeout must still get
                    # a full backoff window, not an already-expired one
                    self._pending_deletes[gid] = (
                        time.monotonic() + self.delete_retry_s)
        for inst in self._instances.values():
            if inst.state in (TERMINATED, FAILED):
                continue
            if inst.group_id in self._pending_deletes:
                # delete in flight: freeze the state machine so a still-READY
                # listing can't resurrect a drained slice back to RUNNING
                continue
            cloud_state = states.get(inst.group_id)
            if cloud_state is None:
                # the TPU VM is absent from the listing. A REQUESTED instance
                # may simply not appear yet; for anything past that, require
                # two consecutive absent polls (one transient partial/empty
                # list response must not strand a live slice) before
                # declaring it externally deleted.
                if inst.state != REQUESTED and \
                        self._missing_polls.get(inst.group_id, 0) >= 2:
                    inst.transition(TERMINATED)
                continue
            mapped = _STATE_MAP.get(cloud_state, inst.state)
            if mapped != inst.state:
                inst.transition(mapped)
        # drop counters for groups with no live instances left (group names
        # are fresh uuids, so stale entries would otherwise accumulate)
        still_live = {i.group_id for i in self._instances.values()
                      if i.state not in (TERMINATED, FAILED)}
        for gid in list(self._missing_polls):
            if gid not in still_live:
                del self._missing_polls[gid]

    def _try_delete(self, group_id: str) -> bool:
        try:
            self._gcloud("compute", "tpus", "tpu-vm", "delete",
                         group_id, "--quiet")
            return True
        except RuntimeError:
            logger.exception("tpu-vm delete failed for %s", group_id)
            return False

    def _finish_group(self, group_id: str) -> None:
        self._missing_polls.pop(group_id, None)
        for p in self._instances.values():
            if p.group_id == group_id and p.state != TERMINATED:
                p.transition(TERMINATED)

    def terminate(self, instance: Instance) -> None:
        # deleting the TPU VM removes every host of the slice; peers are
        # transitioned together, so later terminate() calls for the same
        # group fast-path out here
        if instance.state == TERMINATED:
            return
        gid = instance.group_id
        if gid in self._pending_deletes:
            return  # delete already queued; poll() keeps retrying it
        if self._try_delete(gid):
            self._finish_group(gid)
        else:
            self._pending_deletes[gid] = time.monotonic() + self.delete_retry_s

    def instances(self) -> List[Instance]:
        return list(self._instances.values())
