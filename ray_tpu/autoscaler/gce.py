"""GCE TPU-VM cloud provider (skeleton behind the CloudProvider interface).

Reference capability: python/ray/autoscaler/_private/gcp/node_provider.py +
tpu_command_runner.py. A TPU slice is provisioned as ONE queued-resource /
tpu-vm create call; every host of the slice then starts a node agent joining
the same GCS with a shared slice label — exactly the contract
FakeCloudProvider simulates, so the autoscaler/InstanceManager logic above
is identical in CI and on a real cloud.

This provider shells out to ``gcloud`` (no cloud SDK dependency baked into
the image); it raises a clear error when gcloud is unavailable. Methods are
deliberately thin: each maps to one control-plane call, and poll() derives
instance state from ``gcloud compute tpus tpu-vm list``.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import uuid
from typing import Any, Dict, List

from ray_tpu.autoscaler.instance_manager import (
    FAILED, REQUESTED, RUNNING, STARTING, TERMINATED, CloudProvider, Instance,
)
from ray_tpu.utils.logging import get_logger

logger = get_logger("autoscaler.gce")

# gcloud state -> instance-manager state
_STATE_MAP = {
    "CREATING": STARTING,
    "READY": RUNNING,
    "REPAIRING": STARTING,
    "DELETING": TERMINATED,
    "TERMINATED": TERMINATED,
    "PREEMPTED": FAILED,
}


class GceTpuProvider(CloudProvider):
    """TPU-VM slices via gcloud (one create per slice; accelerator_type like
    "v5litepod-16" determines the host count)."""

    def __init__(self, project: str, zone: str, gcs_address: str,
                 runtime_version: str = "tpu-ubuntu2204-base",
                 startup_script: str = ""):
        if shutil.which("gcloud") is None:
            raise RuntimeError(
                "GceTpuProvider requires the gcloud CLI on PATH. Install the "
                "Google Cloud SDK, or use FakeCloudProvider for local testing."
            )
        self.project = project
        self.zone = zone
        self.gcs_address = gcs_address
        self.runtime_version = runtime_version
        # startup script: every host starts a node agent pointed at the GCS
        # with the slice label (mirrors FakeCloudProvider._launch)
        self.startup_script = startup_script or (
            "python -m ray_tpu.core.node.agent "
            f"--gcs {gcs_address} "
            "--label ray_tpu.io/slice=$(curl -s -H 'Metadata-Flavor: Google' "
            "http://metadata/computeMetadata/v1/instance/attributes/"
            "instance-id)"
        )
        self._instances: Dict[str, Instance] = {}

    def _gcloud(self, *args: str) -> Any:
        out = subprocess.run(
            ["gcloud", *args, "--project", self.project, "--zone", self.zone,
             "--format", "json"],
            capture_output=True, text=True, timeout=300,
        )
        if out.returncode != 0:
            raise RuntimeError(f"gcloud {' '.join(args[:3])}: {out.stderr[:500]}")
        return json.loads(out.stdout or "null")

    def request_group(self, group_config: Dict[str, Any]) -> List[Instance]:
        accel = group_config.get("accelerator_type", "v5litepod-16")
        hosts = int(group_config.get("hosts", 4))
        name = f"rtpu-{uuid.uuid4().hex[:8]}"
        self._gcloud(
            "compute", "tpus", "tpu-vm", "create", name,
            "--accelerator-type", accel,
            "--version", group_config.get("runtime_version", self.runtime_version),
            "--metadata", f"startup-script={self.startup_script},instance-id={name}",
        )
        out = []
        for h in range(hosts):
            inst = Instance(
                instance_id=f"{name}/{h}", group_id=name,
                node_config=dict(group_config), state=REQUESTED,
            )
            self._instances[inst.instance_id] = inst
            out.append(inst)
        return out

    def poll(self) -> None:
        try:
            listed = self._gcloud("compute", "tpus", "tpu-vm", "list") or []
        except RuntimeError:
            logger.exception("tpu-vm list failed")
            return
        states = {n["name"].rsplit("/", 1)[-1]: n.get("state", "") for n in listed}
        for inst in self._instances.values():
            if inst.state in (TERMINATED, FAILED):
                continue
            cloud_state = states.get(inst.group_id)
            mapped = _STATE_MAP.get(cloud_state or "", inst.state)
            if mapped != inst.state:
                inst.transition(mapped)

    def terminate(self, instance: Instance) -> None:
        # deleting the TPU VM removes every host of the slice
        peers = [i for i in self._instances.values()
                 if i.group_id == instance.group_id and i.state != TERMINATED]
        try:
            self._gcloud("compute", "tpus", "tpu-vm", "delete",
                         instance.group_id, "--quiet")
        except RuntimeError:
            logger.exception("tpu-vm delete failed for %s", instance.group_id)
        for p in peers:
            p.transition(TERMINATED)

    def instances(self) -> List[Instance]:
        return list(self._instances.values())
