"""Cluster launcher: ``up / down / exec / attach`` against a cluster YAML.

Reference capability: python/ray/autoscaler/_private/commands.py (`ray
up/down/attach/exec` driving NodeProvider plugins). Redesign for this
runtime: the head (GCS + head agent) starts as detached local processes;
worker nodes come from the YAML's provider — "local" spawns agent
subprocesses on this machine (the CI/test path, the FakeMultiNodeProvider
analogue), "gce" drives the queued-resource TPU provider
(autoscaler/gce.py). Cluster state (addresses + pids + provider handles)
persists under ~/.ray_tpu/clusters/<name>.json so down/exec/attach work
from any later shell.

YAML shape:

```yaml
cluster_name: demo
provider:
  type: local            # or: gce (project/zone/accelerator fields)
head:
  num_cpus: 4
workers:
  count: 2
  num_cpus: 2
```
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import uuid
from typing import Any, Dict, List, Optional

CLUSTERS_DIR = os.path.expanduser("~/.ray_tpu/clusters")


def _state_path(name: str) -> str:
    return os.path.join(CLUSTERS_DIR, f"{name}.json")


def load_state(name: str) -> Optional[Dict[str, Any]]:
    try:
        with open(_state_path(name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _save_state(name: str, state: Dict[str, Any]) -> None:
    os.makedirs(CLUSTERS_DIR, exist_ok=True)
    with open(_state_path(name), "w") as f:
        json.dump(state, f, indent=2)


def load_config(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    if not isinstance(cfg, dict) or not cfg.get("cluster_name"):
        raise ValueError("cluster YAML needs a 'cluster_name'")
    provider = cfg.get("provider") or {"type": "local"}
    if provider.get("type") not in ("local", "gce"):
        raise ValueError(f"unknown provider type {provider.get('type')!r}")
    cfg["provider"] = provider
    cfg.setdefault("head", {})
    cfg.setdefault("workers", {"count": 0})
    return cfg


def _wait_ready(path: str, proc: subprocess.Popen, what: str,
                timeout: float = 60.0) -> str:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            content = open(path).read().strip()
            if content:
                return content
        if proc.poll() is not None:
            raise RuntimeError(f"{what} exited with {proc.returncode}")
        time.sleep(0.05)
    raise TimeoutError(f"{what} not ready in {timeout}s")


def _start_agent(gcs_address: str, session_dir: str, node_cfg: Dict[str, Any],
                 head: bool = False) -> int:
    ready = os.path.join(session_dir, f"agent-{uuid.uuid4().hex[:6]}.ready")
    log = open(os.path.join(session_dir,
                            f"agent-{'head' if head else uuid.uuid4().hex[:6]}.log"),
               "ab")
    cmd = [sys.executable, "-m", "ray_tpu.core.node.agent",
           "--gcs", gcs_address, "--session-dir", session_dir,
           "--ready-file", ready]
    if node_cfg.get("num_cpus") is not None:
        cmd += ["--num-cpus", str(node_cfg["num_cpus"])]
    if node_cfg.get("num_tpus"):
        cmd += ["--num-tpus", str(node_cfg["num_tpus"])]
    for k, v in (node_cfg.get("resources") or {}).items():
        cmd += ["--resource", f"{k}={v}"]
    for k, v in (node_cfg.get("labels") or {}).items():
        cmd += ["--label", f"{k}={v}"]
    if head:
        cmd += ["--head"]
    env = dict(os.environ, RAY_TPU_SESSION_DIR=session_dir)
    proc = subprocess.Popen(cmd, env=env, stdout=log,
                            stderr=subprocess.STDOUT, start_new_session=True)
    _wait_ready(ready, proc, "node agent")
    return proc.pid


def up(config: Dict[str, Any]) -> Dict[str, Any]:
    """Bring the cluster up; idempotent-ish (a live state file is an error —
    run down first). Returns the saved state."""
    name = config["cluster_name"]
    if load_state(name):
        raise RuntimeError(
            f"cluster '{name}' already has state; run `down` first")
    session_dir = f"/tmp/ray_tpu/cluster-{name}-{uuid.uuid4().hex[:6]}"
    os.makedirs(session_dir, exist_ok=True)
    pids: List[int] = []
    worker_handles: List[str] = []
    try:
        # head: GCS + head agent as detached process groups
        ready = os.path.join(session_dir, "gcs.ready")
        gcs_log = open(os.path.join(session_dir, "gcs.log"), "ab")
        gcs = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.gcs.server", "--ready-file", ready],
            env=dict(os.environ, RAY_TPU_SESSION_DIR=session_dir),
            stdout=gcs_log, stderr=subprocess.STDOUT, start_new_session=True)
        pids.append(gcs.pid)
        gcs_address = _wait_ready(ready, gcs, "GCS")
        pids.append(_start_agent(gcs_address, session_dir,
                                 config.get("head") or {}, head=True))
        workers = config.get("workers") or {}
        provider_cfg = config["provider"]
        if provider_cfg["type"] == "local":
            for _ in range(int(workers.get("count", 0))):
                pid = _start_agent(gcs_address, session_dir, workers)
                pids.append(pid)
                worker_handles.append(f"pid:{pid}")
        else:  # gce: queued-resource TPU workers join over the network
            from ray_tpu.autoscaler.gce import GceTpuProvider

            provider = GceTpuProvider(gcs_address=gcs_address, **{
                k: v for k, v in provider_cfg.items() if k != "type"})
            for _ in range(int(workers.get("count", 0))):
                worker_handles.append(provider.create_node(dict(workers)))
    except BaseException:
        # a half-launched cluster with no state file would orphan detached
        # process groups that `down` can never find — kill what we started
        for pid in reversed(pids):
            try:
                os.killpg(os.getpgid(pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        raise
    state = {
        "cluster_name": name,
        "gcs_address": gcs_address,
        "session_dir": session_dir,
        "provider": provider_cfg,
        "pids": pids,
        "worker_handles": worker_handles,
        "created_at": time.time(),
    }
    _save_state(name, state)
    return state


def down(name: str) -> None:
    state = load_state(name)
    if not state:
        raise RuntimeError(f"no state for cluster '{name}'")
    if state["provider"]["type"] == "gce" and state["worker_handles"]:
        from ray_tpu.autoscaler.gce import GceTpuProvider

        provider = GceTpuProvider(gcs_address=state["gcs_address"], **{
            k: v for k, v in state["provider"].items() if k != "type"})
        for handle in state["worker_handles"]:
            try:
                provider.terminate_node(handle)
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
    for pid in reversed(state.get("pids", [])):
        try:
            os.killpg(os.getpgid(pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
    try:
        os.unlink(_state_path(name))
    except OSError:
        pass


def exec_cmd(name: str, command: List[str],
             capture: bool = False) -> subprocess.CompletedProcess:
    """Run a command against the cluster (RAY_TPU_ADDRESS injected). With a
    local provider this runs on this machine — which IS every node's
    machine; remote-provider exec would ride SSH and is not wired here."""
    state = load_state(name)
    if not state:
        raise RuntimeError(f"no state for cluster '{name}'")
    env = dict(os.environ, RAY_TPU_ADDRESS=state["gcs_address"],
               RAY_TPU_SESSION_DIR=state["session_dir"])
    return subprocess.run(command, env=env, capture_output=capture, text=True)


def attach(name: str) -> int:
    """Interactive shell with the cluster's environment exported."""
    state = load_state(name)
    if not state:
        raise RuntimeError(f"no state for cluster '{name}'")
    shell = os.environ.get("SHELL", "/bin/sh")
    print(f"attached to '{name}' (RAY_TPU_ADDRESS={state['gcs_address']}); "
          "exit the shell to detach")
    return subprocess.call(
        [shell], env=dict(os.environ, RAY_TPU_ADDRESS=state["gcs_address"]))


def list_clusters() -> List[Dict[str, Any]]:
    out = []
    if os.path.isdir(CLUSTERS_DIR):
        for fname in sorted(os.listdir(CLUSTERS_DIR)):
            if fname.endswith(".json"):
                st = load_state(fname[:-5])
                if st:
                    out.append(st)
    return out
