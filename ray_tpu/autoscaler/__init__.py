from ray_tpu.autoscaler.autoscaler import (
    AutoscalerConfig,
    SliceAutoscaler,
    SliceAutoscalerConfig,
    StandardAutoscaler,
)
from ray_tpu.autoscaler.instance_manager import (
    CloudProvider,
    FakeCloudProvider,
    Instance,
    InstanceManager,
)
from ray_tpu.autoscaler.node_provider import LocalNodeProvider, NodeProvider

__all__ = [
    "AutoscalerConfig", "StandardAutoscaler", "NodeProvider",
    "LocalNodeProvider", "SliceAutoscaler", "SliceAutoscalerConfig",
    "CloudProvider", "FakeCloudProvider", "Instance", "InstanceManager",
]
