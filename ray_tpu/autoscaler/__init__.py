from ray_tpu.autoscaler.autoscaler import AutoscalerConfig, StandardAutoscaler
from ray_tpu.autoscaler.node_provider import LocalNodeProvider, NodeProvider

__all__ = ["AutoscalerConfig", "StandardAutoscaler", "NodeProvider",
           "LocalNodeProvider"]
