"""StandardAutoscaler: demand-driven elastic node pool.

Reference capability: python/ray/autoscaler/_private/autoscaler.py
(StandardAutoscaler: update loop reading LoadMetrics, launching via a
NodeProvider, terminating idle nodes) + the v2 instance-manager split.
Redesign: the demand signal is the GCS's own unmet-placement ledger
(rpc_autoscaler_state) — no separate metrics pipeline to run — and the loop
is a plain thread the operator owns (CLI/head process), provider-agnostic.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.core.rpc import SyncRpcClient
from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.utils.logging import get_logger

logger = get_logger("autoscaler")


@dataclasses.dataclass
class AutoscalerConfig:
    min_workers: int = 0
    max_workers: int = 4
    # what one launched worker provides (node_config for the provider)
    worker_node_config: Dict[str, Any] = dataclasses.field(
        default_factory=lambda: {"num_cpus": 1})
    idle_timeout_s: float = 60.0
    update_interval_s: float = 1.0
    # launch at most this many nodes per update tick (upscaling_speed-lite)
    max_launches_per_tick: int = 2


class StandardAutoscaler:
    def __init__(self, gcs_address: str, provider: NodeProvider,
                 config: Optional[AutoscalerConfig] = None):
        self.gcs = SyncRpcClient(gcs_address)
        self.provider = provider
        self.config = config or AutoscalerConfig()
        self._idle_since: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.launched = 0
        self.terminated = 0

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        self.gcs.close()

    def _loop(self) -> None:
        while not self._stop.wait(self.config.update_interval_s):
            try:
                self.update()
            except Exception:  # noqa: BLE001 - the loop must survive hiccups
                logger.exception("autoscaler update failed")

    # ----------------------------------------------------------------- logic
    def update(self) -> None:
        state = self.gcs.call("autoscaler_state", window_s=10.0)
        self._maybe_scale_up(state)
        self._maybe_scale_down(state)

    def _maybe_scale_up(self, state: Dict[str, Any]) -> None:
        shapes: List[Dict[str, float]] = state["unmet_shapes"]
        workers = self.provider.non_terminated_nodes()
        if not shapes and len(workers) >= self.config.min_workers:
            return
        capacity = dict(self.config.worker_node_config.get("resources") or {})
        capacity["CPU"] = float(self.config.worker_node_config.get("num_cpus", 1))
        if self.config.worker_node_config.get("num_tpus"):
            capacity["TPU"] = float(self.config.worker_node_config["num_tpus"])

        def fits(shape: Dict[str, float]) -> bool:
            return all(capacity.get(k, 0.0) >= v for k, v in shape.items())

        # bin-pack-lite: how many workers would absorb the unmet shapes
        needed = 0
        room: Dict[str, float] = {}
        for shape in shapes:
            if not shape or not fits(shape):
                continue  # a worker of this type can never satisfy it
            if not all(room.get(k, 0.0) >= v for k, v in shape.items()):
                needed += 1
                room = dict(capacity)
            for k, v in shape.items():
                room[k] = room.get(k, 0.0) - v
        needed = max(needed, self.config.min_workers - len(workers))
        budget = self.config.max_workers - len(workers)
        to_launch = min(needed, budget, self.config.max_launches_per_tick)
        for _ in range(max(0, to_launch)):
            handle = self.provider.create_node(self.config.worker_node_config)
            self.launched += 1
            logger.info("scaled up: launched %s (%d workers)", handle,
                        len(self.provider.non_terminated_nodes()))

    def _maybe_scale_down(self, state: Dict[str, Any]) -> None:
        if state["unmet_shapes"]:
            self._idle_since.clear()
            return
        now = time.monotonic()
        # idle = full availability (nothing leased), NOTHING dispatching
        # (queued work holds no resources yet but must block scale-down), on
        # a non-head alive node
        idle_nodes = {
            n for n, info in state["nodes"].items()
            if info["alive"] and not info["is_head"]
            and not info.get("load", {}).get("dispatching")
            and all(
                abs(info["available"].get(k, 0.0) - v) < 1e-9
                for k, v in info["total"].items()
            )
        }
        for n in list(self._idle_since):
            if n not in idle_nodes:
                del self._idle_since[n]
        workers = self.provider.non_terminated_nodes()
        for n in idle_nodes:
            self._idle_since.setdefault(n, now)
        if len(workers) <= self.config.min_workers:
            return
        # terminate the LONGEST-idle provider node past the timeout. Mapping
        # GCS node ids to provider handles is provider-specific; the local
        # provider launches one agent per handle, so we retire handles while
        # any node has been idle past the deadline (conservative: one/tick).
        expired = [n for n, t in self._idle_since.items()
                   if now - t > self.config.idle_timeout_s]
        if not expired or not workers:
            return
        # terminate the handle whose agent address matches THE idle node —
        # never an arbitrary worker (which could be mid-task)
        addr_to_handle = {
            self.provider.node_address_of(h): h for h in workers
        }
        for node_id in expired:
            addr = state["nodes"].get(node_id, {}).get("address")
            handle = addr_to_handle.get(addr)
            if handle is None:
                self._idle_since.pop(node_id, None)  # not ours to manage
                continue
            # drain at the GCS FIRST (placements stop instantly) so in-flight
            # scheduling doesn't target a node that's about to vanish; the
            # health checker would otherwise lag by seconds
            try:
                self.gcs.call("drain_node", node_id=node_id)
            except Exception:  # noqa: BLE001
                pass
            self.provider.terminate_node(handle)
            self.terminated += 1
            self._idle_since.pop(node_id, None)
            logger.info("scaled down: terminated %s / node %s (idle > %.0fs)",
                        handle, node_id[:8], self.config.idle_timeout_s)
            break  # at most one per tick (conservative)


@dataclasses.dataclass
class SliceAutoscalerConfig:
    """Slice-gang autoscaling: capacity is added/removed in whole SLICES
    (reference: v2 instance manager node groups; TPU queued resources)."""

    max_groups: int = 2
    # one group = `hosts` machines that join as one slice
    group_config: Dict[str, Any] = dataclasses.field(
        default_factory=lambda: {
            "hosts": 4, "num_cpus": 1, "num_tpus": 4, "slice_label": "v5e-16",
        })
    idle_timeout_s: float = 30.0
    update_interval_s: float = 0.5


class SliceAutoscaler:
    """Demand-driven SLICE scaling over an InstanceManager: unmet TPU demand
    requests whole slice groups (hosts provision atomically via the cloud
    provider); a fully-idle group past the timeout drains every host first,
    then terminates as a unit."""

    def __init__(self, gcs_address: str, manager, config: Optional[SliceAutoscalerConfig] = None):
        from ray_tpu.autoscaler.instance_manager import RUNNING

        self._RUNNING = RUNNING
        self.gcs = SyncRpcClient(gcs_address)
        self.manager = manager
        self.config = config or SliceAutoscalerConfig()
        self._group_idle_since: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.groups_launched = 0
        self.groups_terminated = 0

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="slice-autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        self.gcs.close()

    def _loop(self) -> None:
        while not self._stop.wait(self.config.update_interval_s):
            try:
                self.update()
            except Exception:  # noqa: BLE001
                logger.exception("slice autoscaler update failed")

    def update(self) -> None:
        self.manager.poll()
        state = self.gcs.call("autoscaler_state", window_s=10.0)
        self._maybe_scale_up(state)
        self._maybe_scale_down(state)

    def _chips_per_group(self) -> float:
        cfg = self.config.group_config
        return float(cfg.get("hosts", 1)) * float(cfg.get("num_tpus", 0) or 0)

    def _maybe_scale_up(self, state: Dict[str, Any]) -> None:
        unmet_chips = sum(s.get("TPU", 0.0) for s in state["unmet_shapes"])
        if unmet_chips <= 0:
            return
        per_group = self._chips_per_group()
        if per_group <= 0:
            return
        needed = -(-int(unmet_chips) // int(per_group))  # ceil
        active = len(self.manager.active_groups())
        to_launch = min(needed, self.config.max_groups - active)
        for _ in range(max(0, to_launch)):
            self.manager.request_group(self.config.group_config)
            self.groups_launched += 1
            logger.info("slice scale-up: requested group (%d active)",
                        len(self.manager.active_groups()))

    def _maybe_scale_down(self, state: Dict[str, Any]) -> None:
        if state["unmet_shapes"]:
            self._group_idle_since.clear()
            return
        nodes = state["nodes"]
        by_address = {info["address"]: (nid, info) for nid, info in nodes.items()}
        now = time.monotonic()
        node_ids_by_address = {a: nid for a, (nid, _) in by_address.items()}
        idle_groups = []
        for group_id, members in self.manager.active_groups().items():
            running = [i for i in members if i.state == self._RUNNING]
            if len(running) < len(members) or not members:
                continue  # still provisioning: not a scale-down candidate
            def _idle(inst) -> bool:
                rec = by_address.get(inst.address)
                if rec is None:
                    return False
                _, info = rec
                return (info["alive"] and not info.get("load", {}).get("dispatching")
                        and all(abs(info["available"].get(k, 0.0) - v) < 1e-9
                                for k, v in info["total"].items()))
            if all(_idle(i) for i in running):
                idle_groups.append(group_id)
        for g in list(self._group_idle_since):
            if g not in idle_groups:
                del self._group_idle_since[g]
        for g in idle_groups:
            self._group_idle_since.setdefault(g, now)
        expired = [g for g, t in self._group_idle_since.items()
                   if now - t > self.config.idle_timeout_s]
        if expired:
            g = expired[0]  # one group per tick (conservative)
            self.manager.drain_and_terminate_group(g, node_ids_by_address)
            self.groups_terminated += 1
            self._group_idle_since.pop(g, None)
            logger.info("slice scale-down: terminated group %s", g)
