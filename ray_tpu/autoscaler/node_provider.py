"""Node providers: how the autoscaler actually obtains/terminates machines.

Reference capability: python/ray/autoscaler/node_provider.py (NodeProvider
interface) + _private/fake_multi_node/node_provider.py:236 (subprocess nodes
for e2e autoscaler tests). Cloud/TPU-pod providers implement the same three
methods against their control planes (GKE, queued resources, etc.).
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time
import uuid
from typing import Any, Dict, List, Optional


class NodeProvider:
    def create_node(self, node_config: Dict[str, Any]) -> str:
        """Launch one node; returns an opaque node handle id."""
        raise NotImplementedError

    def terminate_node(self, handle: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_address_of(self, handle: str) -> Optional[str]:
        """Agent RPC address of a launched node, when known (lets the
        autoscaler drain the node at the GCS before terminating)."""
        return None


class LocalNodeProvider(NodeProvider):
    """Subprocess node agents on this machine (the fake_multi_node analogue):
    real processes, real RPC — the autoscaler e2e path without a cloud."""

    def __init__(self, gcs_address: str, session_dir: Optional[str] = None):
        self.gcs_address = gcs_address
        self.session_dir = session_dir or tempfile.mkdtemp(prefix="ray_tpu_autoscale_")
        self._procs: Dict[str, subprocess.Popen] = {}
        self._addresses: Dict[str, str] = {}

    def create_node(self, node_config: Dict[str, Any]) -> str:
        handle = f"local-{uuid.uuid4().hex[:8]}"
        ready = os.path.join(self.session_dir, f"{handle}.ready")
        log = open(os.path.join(self.session_dir, f"{handle}.log"), "ab")
        cmd = [
            sys.executable, "-m", "ray_tpu.core.node.agent",
            "--gcs", self.gcs_address,
            "--session-dir", self.session_dir,
            "--ready-file", ready,
            "--num-cpus", str(int(node_config.get("num_cpus", 1))),
        ]
        if node_config.get("num_tpus"):
            cmd += ["--num-tpus", str(int(node_config["num_tpus"]))]
        for k, v in (node_config.get("resources") or {}).items():
            cmd += ["--resource", f"{k}={v}"]
        for k, v in (node_config.get("labels") or {}).items():
            cmd += ["--label", f"{k}={v}"]
        env = dict(os.environ)
        # the agent module must be importable regardless of the caller's cwd
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (pkg_root, env.get("PYTHONPATH", "")) if p)
        env.setdefault("JAX_PLATFORMS", "cpu")  # agents never hold the chip
        proc = subprocess.Popen(cmd, env=env, stdout=log,
                                stderr=subprocess.STDOUT, start_new_session=True)
        deadline = time.monotonic() + 40
        address = ""
        while time.monotonic() < deadline:
            if os.path.exists(ready):
                address = open(ready).read().strip()
                if address:
                    break
            if proc.poll() is not None:
                raise RuntimeError(f"node {handle} exited with {proc.returncode}")
            time.sleep(0.05)
        self._procs[handle] = proc
        self._addresses[handle] = address
        return handle

    def node_address_of(self, handle: str) -> Optional[str]:
        return self._addresses.get(handle)

    def terminate_node(self, handle: str) -> None:
        proc = self._procs.pop(handle, None)
        if proc is not None:
            import signal

            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except Exception:  # noqa: BLE001
                proc.kill()

    def non_terminated_nodes(self) -> List[str]:
        return [h for h, p in self._procs.items() if p.poll() is None]
