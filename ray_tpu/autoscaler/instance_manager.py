"""Instance manager: explicit cloud-instance lifecycle for the autoscaler.

Reference capability: python/ray/autoscaler/v2/instance_manager/ (instance
states REQUESTED -> ALLOCATED -> RAY_RUNNING -> RAY_STOPPING -> TERMINATED,
reconciler.py) + _private/fake_multi_node/node_provider.py:236 (subprocess
fake cloud for e2e tests). TPU twist: instances belong to SLICE GROUPS — a
v5e-16 "instance request" is 4 hosts that must provision atomically and join
the cluster under one slice label (TPU queued-resources semantics: the whole
slice becomes ready or nothing does).
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.utils.logging import get_logger

logger = get_logger("autoscaler.instances")

# lifecycle states (reference: instance_manager/common.py InstanceStatus)
REQUESTED = "REQUESTED"
STARTING = "STARTING"
RUNNING = "RUNNING"
DRAINING = "DRAINING"
TERMINATED = "TERMINATED"
FAILED = "FAILED"


@dataclass
class Instance:
    instance_id: str
    group_id: str           # slice group (one host groups are their own group)
    state: str = REQUESTED
    node_config: Dict[str, Any] = field(default_factory=dict)
    address: str = ""       # node agent RPC address once RUNNING
    created_at: float = field(default_factory=time.monotonic)
    state_since: float = field(default_factory=time.monotonic)
    error: str = ""

    def transition(self, state: str) -> None:
        logger.info("instance %s: %s -> %s", self.instance_id, self.state, state)
        self.state = state
        self.state_since = time.monotonic()


class CloudProvider:
    """Async cloud control plane: request/poll/terminate. Implementations:
    FakeCloudProvider (subprocess nodes, CI) and GceTpuProvider (skeleton,
    real TPU VMs via gcloud)."""

    def request_group(self, group_config: Dict[str, Any]) -> List[Instance]:
        """Ask for one group (1 host, or a whole slice). Returns REQUESTED
        instances immediately; provisioning is asynchronous."""
        raise NotImplementedError

    def poll(self) -> None:
        """Advance async state (REQUESTED->STARTING->RUNNING / FAILED)."""
        raise NotImplementedError

    def terminate(self, instance: Instance) -> None:
        raise NotImplementedError

    def instances(self) -> List[Instance]:
        raise NotImplementedError


class FakeCloudProvider(CloudProvider):
    """Simulated cloud with real subprocess node agents: instances move
    REQUESTED -> STARTING (provision_delay_s) -> RUNNING (agent process up,
    registered at the GCS). A slice group's hosts move together: the group
    becomes RUNNING only when EVERY host's agent is up (atomic slice
    semantics); one host failing fails the whole group."""

    def __init__(self, gcs_address: str, session_dir: Optional[str] = None,
                 provision_delay_s: float = 0.5):
        self.gcs_address = gcs_address
        self.session_dir = session_dir or tempfile.mkdtemp(prefix="ray_tpu_fakecloud_")
        self.provision_delay_s = provision_delay_s
        self._instances: Dict[str, Instance] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------- requests
    def request_group(self, group_config: Dict[str, Any]) -> List[Instance]:
        hosts = int(group_config.get("hosts", 1))
        group_id = f"grp-{uuid.uuid4().hex[:8]}"
        out = []
        with self._lock:
            for _ in range(hosts):
                inst = Instance(
                    instance_id=f"i-{uuid.uuid4().hex[:8]}",
                    group_id=group_id,
                    node_config=dict(group_config),
                )
                self._instances[inst.instance_id] = inst
                out.append(inst)
        logger.info("requested group %s: %d host(s)", group_id, hosts)
        return out

    # ---------------------------------------------------------------- poll
    def poll(self) -> None:
        now = time.monotonic()
        with self._lock:
            insts = list(self._instances.values())
        for inst in insts:
            if inst.state == REQUESTED and now - inst.state_since >= self.provision_delay_s:
                try:
                    self._launch(inst)
                    inst.transition(STARTING)
                except Exception as e:  # noqa: BLE001
                    inst.error = str(e)
                    inst.transition(FAILED)
                    self._fail_group(inst.group_id)
            elif inst.state == STARTING:
                ready = os.path.join(self.session_dir, f"{inst.instance_id}.ready")
                proc = self._procs.get(inst.instance_id)
                if proc is not None and proc.poll() is not None:
                    inst.error = f"agent exited with {proc.returncode}"
                    inst.transition(FAILED)
                    self._fail_group(inst.group_id)
                elif os.path.exists(ready):
                    address = open(ready).read().strip()
                    if address:
                        inst.address = address
                        inst.transition(RUNNING)

    def _fail_group(self, group_id: str) -> None:
        """Slice atomicity: one failed host dooms its whole group."""
        for other in self._instances.values():
            if other.group_id == group_id and other.state not in (FAILED, TERMINATED):
                self.terminate(other)

    def _launch(self, inst: Instance) -> None:
        cfg = inst.node_config
        ready = os.path.join(self.session_dir, f"{inst.instance_id}.ready")
        log = open(os.path.join(self.session_dir, f"{inst.instance_id}.log"), "ab")
        cmd = [
            sys.executable, "-m", "ray_tpu.core.node.agent",
            "--gcs", self.gcs_address,
            "--session-dir", self.session_dir,
            "--ready-file", ready,
            "--num-cpus", str(int(cfg.get("num_cpus", 1))),
        ]
        if cfg.get("num_tpus"):
            cmd += ["--num-tpus", str(int(cfg["num_tpus"]))]
        labels = dict(cfg.get("labels") or {})
        if cfg.get("slice_label"):
            # every host of the group shares ONE slice label: collectives on
            # the slice ride ICI (STRICT_PACK treats it as one domain)
            labels["ray_tpu.io/slice"] = f"{cfg['slice_label']}-{inst.group_id}"
        for k, v in labels.items():
            cmd += ["--label", f"{k}={v}"]
        for k, v in (cfg.get("resources") or {}).items():
            cmd += ["--resource", f"{k}={v}"]
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (pkg_root, env.get("PYTHONPATH", "")) if p)
        env.setdefault("JAX_PLATFORMS", "cpu")
        self._procs[inst.instance_id] = subprocess.Popen(
            cmd, env=env, stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True,
        )

    # ----------------------------------------------------------- terminate
    def terminate(self, instance: Instance) -> None:
        if instance.state == TERMINATED:
            return
        proc = self._procs.pop(instance.instance_id, None)
        if proc is not None:
            import signal

            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except Exception:  # noqa: BLE001
                try:
                    proc.kill()
                except Exception:  # noqa: BLE001
                    pass
        instance.transition(TERMINATED)

    def instances(self) -> List[Instance]:
        with self._lock:
            return list(self._instances.values())


class InstanceManager:
    """Reconciles instance state against group targets and drains before
    terminating (reference: v2 reconciler + RAY_STOPPING draining)."""

    def __init__(self, provider: CloudProvider, gcs_call=None):
        self.provider = provider
        self._gcs_call = gcs_call  # fn(method, **kw) for drain_node

    # views
    def running(self) -> List[Instance]:
        return [i for i in self.provider.instances() if i.state == RUNNING]

    def active_groups(self) -> Dict[str, List[Instance]]:
        """group_id -> instances, excluding terminated/failed groups."""
        groups: Dict[str, List[Instance]] = {}
        for i in self.provider.instances():
            if i.state in (TERMINATED, FAILED):
                continue
            groups.setdefault(i.group_id, []).append(i)
        return groups

    def request_group(self, group_config: Dict[str, Any]) -> List[Instance]:
        return self.provider.request_group(group_config)

    def poll(self) -> None:
        self.provider.poll()

    def drain_and_terminate_group(self, group_id: str,
                                  node_ids_by_address: Dict[str, str]) -> None:
        """Slice scale-down: drain every host at the GCS (placements stop
        instantly), then terminate the whole group."""
        members = [i for i in self.provider.instances()
                   if i.group_id == group_id and i.state not in (TERMINATED, FAILED)]
        for inst in members:
            inst.transition(DRAINING)
            node_id = node_ids_by_address.get(inst.address)
            if node_id and self._gcs_call is not None:
                try:
                    self._gcs_call("drain_node", node_id=node_id)
                except Exception:  # noqa: BLE001
                    pass
        for inst in members:
            self.provider.terminate(inst)
