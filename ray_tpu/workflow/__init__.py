"""Durable workflows: checkpointed DAG execution with resume.

Reference capability: python/ray/workflow/ (api.py run/resume_async/
list_all/get_output, workflow_executor.py, workflow_storage.py — durable
step results + metadata under a storage prefix, exactly-once step semantics
via idempotent checkpoint commits). Redesign: a workflow is a ray_tpu.dag
graph; each node gets a deterministic step id (graph position + function
name); the executor walks the graph, skipping any step whose checkpoint
exists in storage and persisting each fresh result before it is consumed.
Crash + resume(workflow_id) therefore replays only incomplete steps.

Storage layout (under <storage>/<workflow_id>/):
    meta.pkl            pickled DAG + status
    steps/<step_id>.pkl pickled step result (checkpoint)
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.dag import DAGNode
from ray_tpu.utils.logging import get_logger

logger = get_logger("workflow")

_storage_dir: Optional[str] = None


def init(storage: Optional[str] = None) -> None:
    """Set the durable storage root (default: ~/.ray_tpu/workflows)."""
    global _storage_dir
    _storage_dir = storage or os.path.expanduser("~/.ray_tpu/workflows")
    os.makedirs(_storage_dir, exist_ok=True)


def _root() -> str:
    if _storage_dir is None:
        init()
    return _storage_dir  # type: ignore[return-value]


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_root(), workflow_id)


def _atomic_write(path: str, data: bytes) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # atomic commit: a crash never leaves a torn file


# --------------------------------------------------------------------------- #
# Events (reference: python/ray/workflow/event_listener.py — workflows that
# block on external signals, durably: once the event step's checkpoint is
# committed, resume() never waits again) and dynamic continuations
# (reference: workflow.continuation — a step may RETURN a new sub-DAG which
# runs in its place, checkpointed under the same step).
# --------------------------------------------------------------------------- #
class EventListener:
    """Subclass and implement poll_for_event (blocking; return the event
    payload). Instantiated INSIDE the event step's task."""

    def poll_for_event(self, *args, **kwargs) -> Any:
        raise NotImplementedError


class KVEventListener(EventListener):
    """Built-in listener: waits for a cluster-KV key to appear (external
    systems signal by ray_tpu.kv_put). Returns the key's bytes."""

    def poll_for_event(self, key: str, poll_interval_s: float = 0.2,
                       timeout_s: Optional[float] = None):
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        while True:
            value = ray_tpu.kv_get(key)
            if value is not None:
                return value
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"no event on KV key {key!r} in {timeout_s}s")
            time.sleep(poll_interval_s)


class TimerListener(EventListener):
    """Fires at an absolute unix timestamp (durable sleep)."""

    def poll_for_event(self, fire_at: float):
        while True:
            now = time.time()
            if now >= fire_at:
                return fire_at
            time.sleep(min(1.0, fire_at - now))


def _poll_event_task(payload: bytes):
    listener_cls, args, kwargs = cloudpickle.loads(payload)
    return listener_cls().poll_for_event(*args, **kwargs)


def wait_for_event(listener_cls, *args, **kwargs) -> DAGNode:
    """A DAG node that blocks until the listener fires. As a FunctionNode it
    checkpoints like any step: the event is consumed EXACTLY ONCE across
    crash/resume (reference: event_listener.py + checkpointed event step)."""
    if not (isinstance(listener_cls, type) and issubclass(listener_cls, EventListener)):
        raise TypeError("wait_for_event needs an EventListener subclass")
    fn = ray_tpu.remote(_poll_event_task)
    fn._name = f"event_{listener_cls.__name__}"
    return fn.bind(cloudpickle.dumps((listener_cls, args, kwargs)))


class Continuation:
    __slots__ = ("dag",)

    def __init__(self, dag: DAGNode):
        self.dag = dag


def continuation(dag: DAGNode) -> Continuation:
    """Return this from a workflow step to continue INTO a dynamically-built
    sub-DAG: the sub-DAG runs in the step's place and its result becomes the
    step's checkpointed value. Sub-steps checkpoint individually, so a crash
    mid-continuation replays only the incomplete tail. Requirement (same as
    the reference): the parent step must rebuild the same sub-DAG shape on
    re-execution."""
    if not isinstance(dag, DAGNode):
        raise TypeError("continuation() takes a DAG node")
    return Continuation(dag)


def _step_id(node: DAGNode, order: Dict[int, int]) -> str:
    name = type(node).__name__
    fn = getattr(node, "_fn", None)
    if fn is not None:
        name = getattr(fn, "_name", None) or getattr(
            getattr(fn, "_function", None), "__name__", name
        )
    return f"{order[id(node)]:04d}_{name}"


class WorkflowExecution:
    def __init__(self, workflow_id: str, dag: DAGNode):
        self.workflow_id = workflow_id
        self.dag = dag
        self.dir = _wf_dir(workflow_id)
        self.steps_dir = os.path.join(self.dir, "steps")
        os.makedirs(self.steps_dir, exist_ok=True)
        # deterministic step ids: depth-first order over the (stable) graph
        self._order = {id(n): i for i, n in enumerate(dag.walk())}
        self._inputs: Optional[tuple] = None  # (args, kwargs) of this run

    # ------------------------------------------------------------- metadata
    def _write_meta(self, status: str, error: str = "") -> None:
        _atomic_write(os.path.join(self.dir, "meta.pkl"), cloudpickle.dumps({
            "workflow_id": self.workflow_id,
            "status": status,
            "error": error,
            "dag": self.dag,
            # original run() (args, kwargs): replayed by resume() so
            # InputNode steps see the same inputs on every attempt
            "inputs": self._inputs,
            "updated_at": time.time(),
        }))

    # ------------------------------------------------------------ execution
    def _ckpt_path(self, step_id: str) -> str:
        return os.path.join(self.steps_dir, f"{step_id}.pkl")

    def _load_ckpt(self, step_id: str):
        path = self._ckpt_path(step_id)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return cloudpickle.loads(f.read())

    def run(self, *args, **kwargs) -> Any:
        self._inputs = (args, kwargs)
        self._write_meta("RUNNING")
        try:
            result = self._run_node(self.dag, args, kwargs)
            self._write_meta("SUCCESSFUL")
            return result
        except BaseException as e:
            self._write_meta("FAILED", error=repr(e))
            raise

    def _run_node(self, node: DAGNode, args: tuple, kwargs: dict) -> Any:
        """Execute with per-FunctionNode checkpointing: completed steps are
        fed back as literal values, so only incomplete subgraphs re-run."""
        from ray_tpu.dag import (
            ClassMethodNode, ClassNode, FunctionNode, MultiOutputNode,
        )
        from ray_tpu.dag import _ExecutionContext

        ctx = _ExecutionContext(args, kwargs)
        memo_values: Dict[int, Any] = {}

        def resolve(n: DAGNode):
            if id(n) in memo_values:
                return memo_values[id(n)]
            if isinstance(n, FunctionNode):
                sid = _step_id(n, self._order)
                ckpt = self._load_ckpt(sid)
                if ckpt is not None:
                    value = ckpt["value"]
                else:
                    r_args = tuple(resolve(a) if isinstance(a, DAGNode) else a
                                   for a in n._args)
                    r_kwargs = {k: resolve(v) if isinstance(v, DAGNode) else v
                                for k, v in n._kwargs.items()}
                    ref = n._fn.remote(*r_args, **r_kwargs)
                    value = ray_tpu.get(ref)
                    # dynamic continuation: the step returned a sub-DAG to
                    # run in its place; its nodes get fresh deterministic
                    # ids and checkpoint individually, and the FINAL value
                    # lands under THIS step's checkpoint
                    while isinstance(value, Continuation):
                        base = (max(self._order.values()) + 1
                                if self._order else 0)
                        for j, sub in enumerate(value.dag.walk()):
                            if id(sub) not in self._order:
                                self._order[id(sub)] = base + j
                        value = resolve(value.dag)
                    # checkpoint BEFORE the value is consumed downstream:
                    # a crash after this line never re-runs the step
                    _atomic_write(self._ckpt_path(sid),
                                  cloudpickle.dumps({"value": value}))
                memo_values[id(n)] = value
                return value
            if isinstance(n, MultiOutputNode):
                value = [resolve(o) for o in n._outputs]
                memo_values[id(n)] = value
                return value
            if isinstance(n, (ClassNode, ClassMethodNode)):
                # actor steps are not durable (reference: workflows support
                # virtual actors separately); execute live each run. But their
                # DAG-node arguments MUST resolve through this checkpoint-
                # aware path first — seeding ctx.memo so the live _resolve
                # below picks up checkpointed values instead of re-running
                # function parents (duplicate side effects on resume).
                for child in n.walk():
                    if child is n or isinstance(child, (ClassNode, ClassMethodNode)):
                        continue  # actor chain stays live; resolved below
                    if child not in ctx.memo:
                        ctx.memo[child] = resolve(child)
                value = ray_tpu.get(n._resolve(ctx)) if isinstance(
                    n, ClassMethodNode) else n._resolve(ctx)
                memo_values[id(n)] = value
                return value
            value = n._resolve(ctx)
            memo_values[id(n)] = value
            return value

        return resolve(self.dag)


# -------------------------------------------------------------------- api
def run(dag: DAGNode, *args, workflow_id: Optional[str] = None, **kwargs) -> Any:
    """Execute a DAG durably; returns the final value (reference:
    workflow.run). Steps checkpoint as they complete; re-running the same
    workflow_id resumes instead of restarting."""
    workflow_id = workflow_id or f"wf-{int(time.time() * 1000):x}"
    return WorkflowExecution(workflow_id, dag).run(*args, **kwargs)


def resume(workflow_id: str) -> Any:
    """Resume an interrupted workflow from its last checkpoints (reference:
    workflow.resume). The DAG is loaded from durable metadata, so the
    original driver script is not needed."""
    meta_path = os.path.join(_wf_dir(workflow_id), "meta.pkl")
    if not os.path.exists(meta_path):
        raise ValueError(f"no workflow '{workflow_id}' in {_root()}")
    with open(meta_path, "rb") as f:
        meta = cloudpickle.loads(f.read())
    dag = meta["dag"]
    inputs = meta.get("inputs")
    if inputs is None:
        from ray_tpu.dag import InputNode

        if any(isinstance(n, InputNode) for n in dag.walk()):
            raise ValueError(
                f"workflow '{workflow_id}' has an InputNode but no recorded "
                "run() inputs (written by an older version?); cannot resume "
                "without the original arguments"
            )
        inputs = ((), {})
    args, kwargs = inputs
    return WorkflowExecution(workflow_id, dag).run(*args, **kwargs)


def get_status(workflow_id: str) -> Optional[str]:
    meta_path = os.path.join(_wf_dir(workflow_id), "meta.pkl")
    if not os.path.exists(meta_path):
        return None
    with open(meta_path, "rb") as f:
        return cloudpickle.loads(f.read())["status"]


def list_all() -> List[Dict[str, Any]]:
    out = []
    root = _root()
    for wid in sorted(os.listdir(root)):
        meta_path = os.path.join(root, wid, "meta.pkl")
        if not os.path.exists(meta_path):
            continue
        with open(meta_path, "rb") as f:
            meta = cloudpickle.loads(f.read())
        out.append({"workflow_id": wid, "status": meta["status"],
                    "updated_at": meta["updated_at"]})
    return out


def delete(workflow_id: str) -> None:
    import shutil

    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)
