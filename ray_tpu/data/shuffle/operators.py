"""Physical operators of the streaming shuffle.

``ShuffleMapOp`` launches one partitioner task per upstream block AS IT
LANDS — there is no driver-side collect-every-ref barrier like
``AllToAllOp``. Exchanges that need global knowledge first (sort
boundaries, repartition row counts) run a streaming plan phase: a tiny
sample task per block overlaps with upstream production, and the full
partitioner fan-out starts the moment the last sample returns.

``ShuffleReduceOp`` dispatches reduce tasks once the partition table is
complete, gated by the coordinator's spill-aware admission budget; outputs
emit head-of-line in reducer order, so a sorted dataset streams out
globally ordered. Partition refs are dropped as each reduce finishes —
distributed GC reclaims exchange intermediates while the shuffle runs.

In cluster mode the partition blocks move over the raw-frame transfer
plane: a reduce task's argument pull fans out through the agent's
TransferManager (striped multi-source pulls under the global
in-flight-bytes budget), with the whole partition set resolved through one
batched GCS holder lookup."""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.data.execution.interfaces import (
    ExecutionContext,
    PhysicalOperator,
    RefBundle,
)
from ray_tpu.data.shuffle.coordinator import ShuffleCoordinator
from ray_tpu.data.shuffle.spec import ShuffleSpec


class ShuffleMapOp(PhysicalOperator):
    """Map-side partitioner: one ``num_returns=n_out`` split task per input
    block, launched as blocks arrive. Produces no executor-visible bundles —
    partition refs go straight into the coordinator's table; the partition
    blocks themselves are AT REST in the object store (spillable), so they
    are deliberately not charged against the streaming memory budget."""

    num_cpus = 1.0

    def __init__(self, spec: ShuffleSpec, coord: ShuffleCoordinator,
                 concurrency: Optional[int] = None):
        super().__init__(f"shuffle_map({spec.name})")
        from ray_tpu.core.config import config

        self.spec = spec
        self.coord = coord
        self.n_out = coord.n_out
        self.concurrency_cap = concurrency or config.data_default_op_concurrency
        self._next_idx = 0
        # plan phase state (sort boundaries / repartition row counts)
        self._plan_ready = not spec.needs_plan
        self._plan_ref: Optional[ObjectRef] = None
        self._buffered: Deque[Tuple[int, RefBundle]] = deque()
        self._samples: Dict[int, Any] = {}
        self._sample_refs: Dict[ObjectRef, Tuple[int, float]] = {}
        # map-task tracking: last return ref -> (block idx, all refs, t0)
        self._map_refs: Dict[ObjectRef, Tuple[int, List[ObjectRef], float]] = {}
        self._split_remote = None
        self._sample_remote = None

    # ------------------------------------------------------------------ setup
    def start(self, ctx: ExecutionContext) -> None:
        spec_map, n_out = self.spec.map_fn, self.n_out

        @ray_tpu.remote(num_cpus=self.num_cpus, num_returns=n_out,
                        name=f"data::{self.name}")
        def split_task(block, idx, plan):
            return spec_map(block, n_out, idx, plan)

        self._split_remote = split_task
        if self.spec.needs_plan:
            spec_sample = self.spec.sample_fn

            @ray_tpu.remote(num_cpus=1, name=f"data::{self.name}::sample")
            def sample_task(block, idx):
                return spec_sample(block, idx)

            self._sample_remote = sample_task
        self.coord.sample_baseline()

    # ------------------------------------------------------------- scheduling
    def can_dispatch(self) -> bool:
        if self._finished:
            return False
        if self.input_queue:
            return True
        if self._plan_ready:
            return bool(self._buffered)
        # plan pending: computable once every sample returned and no more
        # blocks can arrive
        return (self._inputs_complete and not self._sample_refs
                and not self.input_queue)

    def dispatch(self, ctx: ExecutionContext) -> None:
        if self.input_queue:
            bundle = self.input_queue.popleft()
            idx = self._next_idx
            self._next_idx += 1
            if self.spec.needs_plan:
                ref = self._sample_remote.remote(bundle.ref, idx)
                self._sample_refs[ref] = (idx, self.stats.on_task_submitted())
                self._buffered.append((idx, bundle))
            else:
                self._launch_map(idx, bundle)
            return
        if not self._plan_ready:
            if self._sample_refs or not self._inputs_complete:
                return
            plan = self.spec.plan_fn(
                [self._samples[i] for i in sorted(self._samples)], self.n_out)
            self._plan_ref = ray_tpu.put(plan)
            self._plan_ready = True
        if self._buffered:
            idx, bundle = self._buffered.popleft()
            self._launch_map(idx, bundle)

    def _launch_map(self, idx: int, bundle: RefBundle) -> None:
        out = self._split_remote.remote(bundle.ref, idx, self._plan_ref)
        refs = list(out) if isinstance(out, (list, tuple)) else [out]
        # the LAST return seals last: its completion implies every sibling
        # partition ref of this map task is ready to probe and consume
        self._map_refs[refs[-1]] = (idx, refs, self.stats.on_task_submitted())

    # ------------------------------------------------------------ completions
    def active_refs(self) -> List[ObjectRef]:
        return list(self._sample_refs) + list(self._map_refs)

    def num_active_tasks(self) -> int:
        return len(self._sample_refs) + len(self._map_refs)

    def process_completions(self, ctx: ExecutionContext,
                            ready: Optional[List[ObjectRef]] = None) -> bool:
        if ready is None:
            refs = self.active_refs()
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0.05) \
                if refs else ([], [])
        progressed = False
        for ref in ready:
            if ref in self._sample_refs:
                idx, t0 = self._sample_refs.pop(ref)
                self._samples[idx] = ray_tpu.get(ref)
                self.stats.on_task_finished(t0)
                progressed = True
            elif ref in self._map_refs:
                idx, refs, t0 = self._map_refs.pop(ref)
                sizes = ctx.probe_sizes(refs)
                self.coord.add_map_output(idx, refs, sizes)
                self.stats.on_task_finished(t0)
                self.stats.blocks_out += len(refs)
                self.stats.bytes_out += sum(s or 0 for s in sizes)
                self.stats.last_output_at = time.perf_counter()
                progressed = True
        if (self.all_inputs_done() and not self._buffered
                and self.coord.expected_maps is None
                and (self._plan_ready or self._next_idx == 0)):
            # every map task is launched: the reduce side now knows the
            # final partition-table height
            self.coord.expected_maps = self._next_idx
        return progressed

    def completed(self) -> bool:
        if self._finished:
            return True
        done = (self.all_inputs_done() and not self._buffered
                and not self._sample_refs and not self._map_refs)
        if done and self.coord.expected_maps is None:
            self.coord.expected_maps = self._next_idx
        return done

    def mark_finished(self) -> None:
        super().mark_finished()
        self._buffered.clear()
        if self.coord.expected_maps is None:
            self.coord.expected_maps = 0

    # ------------------------------------------------------ memory accounting
    def queued_output_bytes(self) -> int:
        # partition blocks are at rest in the store and spill under
        # pressure; charging them against the streaming budget would wedge
        # the pipeline (every map must run before ANY reduce can drain)
        return 0


class ShuffleReduceOp(PhysicalOperator):
    """Reduce-side pull scheduler: dispatches reduce task ``j`` over
    partition ``j`` of every map output once the table is complete and the
    spill-aware admission budget allows. Ordered head-of-line emission in
    reducer order keeps global sort order intact."""

    num_cpus = 1.0

    def __init__(self, spec: ShuffleSpec, coord: ShuffleCoordinator,
                 concurrency: Optional[int] = None):
        super().__init__(f"shuffle_reduce({spec.name})")
        from ray_tpu.core.config import config

        self.spec = spec
        self.coord = coord
        self.n_out = coord.n_out
        self.concurrency_cap = concurrency or config.data_default_op_concurrency
        self._next_j = 0
        # (j, ref, t0) in dispatch (= reducer index) order
        self._pending: Deque[Tuple[int, ObjectRef, float]] = deque()
        self._done: Dict[int, Optional[int]] = {}  # j -> size, once finished
        self._by_ref: Dict[ObjectRef, Tuple[int, float]] = {}
        self._reduce_remote = None
        self.stats.extra = self.coord.stats

    def start(self, ctx: ExecutionContext) -> None:
        spec_reduce = self.spec.reduce_fn

        @ray_tpu.remote(num_cpus=self.num_cpus, name=f"data::{self.name}")
        def reduce_task(j, *parts):
            return spec_reduce(j, *parts)

        self._reduce_remote = reduce_task

    # ------------------------------------------------------------- scheduling
    def can_dispatch(self) -> bool:
        if self._finished or self._next_j >= self.n_out:
            return False
        if not self.coord.maps_complete() or self.coord.num_maps == 0:
            return False
        return self.coord.admit(self._next_j)

    def dispatch(self, ctx: ExecutionContext) -> None:
        j = self._next_j
        self._next_j += 1
        refs = self.coord.partition_refs(j)
        ref = self._reduce_remote.remote(j, *refs)
        t0 = self.stats.on_task_submitted()
        self._pending.append((j, ref, t0))
        self._by_ref[ref] = (j, t0)

    # ------------------------------------------------------------ completions
    def active_refs(self) -> List[ObjectRef]:
        return list(self._by_ref)

    def num_active_tasks(self) -> int:
        # tracked-but-not-yet-emitted counts against the concurrency cap
        # (ordered emission: a straggling head-of-line reduce pauses
        # dispatches instead of piling finished outputs behind it)
        return len(self._pending)

    def process_completions(self, ctx: ExecutionContext,
                            ready: Optional[List[ObjectRef]] = None) -> bool:
        if ready is None:
            refs = list(self._by_ref)
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0.05) \
                if refs else ([], [])
        else:
            ready = [r for r in ready if r in self._by_ref]
        if ready:
            sizes = ctx.probe_sizes(ready)
            for ref, size in zip(ready, sizes):
                j, t0 = self._by_ref.pop(ref)
                self._done[j] = size
                self.stats.on_task_finished(t0)
                self.coord.mark_reduced(j)
        produced = False
        while self._pending and self._pending[0][0] in self._done:
            j, ref, _t0 = self._pending.popleft()
            if not self._finished:
                self._emit(RefBundle(ref, size_bytes=self._done[j]), ctx)
                produced = True
        return produced or bool(ready)

    def completed(self) -> bool:
        if self._finished:
            return True
        if not self._inputs_complete or not self.coord.maps_complete():
            return False
        if self.coord.num_maps == 0:
            return True
        return self._next_j >= self.n_out and not self._pending

    def shutdown(self) -> None:
        self.coord.finalize_metrics()

    # ------------------------------------------------------ memory accounting
    def internal_bytes(self) -> int:
        # an in-flight reduce holds its whole partition set plus its output:
        # charge the admitted sets so the ResourceManager sees exchange
        # bytes like any other operator's (satellite: no more budget bypass)
        inflight = [j for j, _r, _t in self._pending if j not in self._done]
        return sum(self.coord.partition_bytes(j) for j in inflight) + \
            len(inflight) * self.estimated_output_bytes_per_block()
