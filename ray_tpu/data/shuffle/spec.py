"""Shuffle exchange specs: the partition functions of one all-to-all.

One ``ShuffleSpec`` fully describes an exchange:

- ``map_fn(block, n_out, block_idx, plan)`` splits one input block into
  ``n_out`` per-reducer partition blocks (runs in a remote partitioner
  task);
- ``reduce_fn(j, *parts)`` combines partition ``j`` of every map output
  into one output block (runs in a remote reduce task);
- optional plan phase for exchanges that need global knowledge before
  partitioning: ``sample_fn(block, block_idx)`` extracts a tiny sample per
  block (sort boundary candidates, repartition row counts) and
  ``plan_fn(samples, n_out)`` turns the collected samples into the plan
  object every map task receives.

The SAME spec drives both the streaming operators (``shuffle.operators``)
and the legacy ``AllToAllOp`` barrier exchange (``data/executor.py``), so
flipping ``RTPU_STREAMING_SHUFFLE`` changes scheduling, never data.

Columnar kernels (``RTPU_COLUMNAR_EXCHANGE``, captured at DRIVER spec
construction so one exchange never mixes kernel variants across workers):
partitioning runs as ONE stable ``np.argsort(assign)`` + ``searchsorted``
boundary slices instead of n× ``take(nonzero(assign == j))`` scans; the
sort map pre-sorts its partition slices by key and the sort reduce k-way
merges the already-sorted runs with vectorized ``searchsorted`` position
arithmetic instead of ``concat + pc.sort_indices`` over the full
partition set. Blocks whose key column has no fast columnar layout
(pyobj / strings / nulls / NaNs) fall back to the row-object kernels per
block; the reduce detects unsorted runs and falls back to the full
re-sort, so mixed-format exchanges stay correct. The stable-sort /
merge-in-block-order discipline makes every kernel variant byte-identical
on ties, which is what keeps ``RTPU_COLUMNAR_EXCHANGE=0`` a pure A/B.

Determinism: every RNG here is seeded from the BLOCK INDEX (stable position
in the upstream stream), never from dispatch/completion order — a seeded
``random_shuffle`` produces identical rows no matter how maps interleave.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

_MASK64 = (1 << 64) - 1


def derive_rng(seed: Optional[int], *stream: int):
    """Deterministic per-(seed, stream...) generator. ``None`` seed stays
    nondeterministic. Components are masked to uint64 so negative seeds and
    large indices feed SeedSequence legally."""
    import numpy as np

    if seed is None:
        return np.random.default_rng(None)
    return np.random.default_rng(
        np.random.SeedSequence([seed & _MASK64, *[s & _MASK64 for s in stream]])
    )


def _schema_preserving_concat(parts: List[Any], schema: Any = None):
    """Concat partition blocks, keeping the schema when every part is empty
    (a column-less output block breaks downstream column refs). ``schema``
    is the spec-threaded fallback for the degenerate case where no part
    carries one."""
    from ray_tpu.data.block import concat_blocks

    nonempty = [p for p in parts if p.num_rows]
    if not nonempty:
        for p in parts:
            if p.num_columns:
                return p.slice(0, 0)
        return concat_blocks([], schema=schema)
    return concat_blocks(nonempty)


# ------------------------------------------------------------ columnar kernels
def _legacy_scatter(block, assign, n: int):
    """n× selection scans — the row-object partition kernel."""
    import numpy as np

    return tuple(block.take(np.nonzero(assign == j)[0]) for j in range(n))


def _vectorized_scatter(block, assign, n: int):
    """Single-pass partition: one stable argsort of the assignment vector,
    one gather, then zero-copy boundary slices. The stable sort preserves
    each partition's original row order, so the output is byte-identical
    to ``_legacy_scatter`` — at 1 table scan instead of n."""
    import numpy as np

    order = np.argsort(assign, kind="stable")
    starts = np.searchsorted(assign[order], np.arange(n + 1))
    reordered = block.take(order)
    return tuple(reordered.slice(int(starts[j]), int(starts[j + 1] - starts[j]))
                 for j in range(n))


def _stable_order(keys, descending: bool):
    """Stable sort permutation: ties keep original order for ascending AND
    descending (a stable descending sort is the reverse of a stable
    ascending sort of the reversed array)."""
    import numpy as np

    if not descending:
        return np.argsort(keys, kind="stable")
    s = np.argsort(keys[::-1], kind="stable")
    return (len(keys) - 1 - s)[::-1]


def _asc_keys(k, descending: bool):
    """Map keys to an ascending-comparable domain for merge arithmetic.
    Descending uses bitwise NOT for ints/bools (monotone-decreasing with no
    int64-min negation overflow) and negation for floats; temporals reorder
    through their int64 representation."""
    import numpy as np

    if not descending:
        return k
    if k.dtype.kind in "mM":
        k = k.view(np.int64)
    if k.dtype.kind in "iub":
        return np.invert(k)
    return -k


def _merge_sorted_asc(key_arrays):
    """K-way merge of ascending runs via vectorized position arithmetic:
    element i of run A lands at ``i + searchsorted(B, A[i], left)``; ties
    resolve left-run-first, so merging in block-index order reproduces
    exactly what a stable sort of the concatenation would do. Balanced
    pairwise folding keeps total work O(rows · log runs). Returns gather
    indices into the concatenation of the runs."""
    import numpy as np

    items = []
    off = 0
    for ka in key_arrays:
        items.append((ka, np.arange(off, off + len(ka), dtype=np.int64)))
        off += len(ka)
    if not items:
        return np.empty(0, dtype=np.int64)
    while len(items) > 1:
        nxt = []
        for i in range(0, len(items) - 1, 2):
            (ka, ia), (kb, ib) = items[i], items[i + 1]
            pos_a = np.arange(len(ka)) + np.searchsorted(kb, ka, side="left")
            pos_b = np.arange(len(kb)) + np.searchsorted(ka, kb, side="right")
            mk = np.empty(len(ka) + len(kb), dtype=np.result_type(ka, kb))
            mi = np.empty(len(ka) + len(kb), dtype=np.int64)
            mk[pos_a] = ka
            mk[pos_b] = kb
            mi[pos_a] = ia
            mi[pos_b] = ib
            nxt.append((mk, mi))
        if len(items) % 2:
            nxt.append(items[-1])
        items = nxt
    return items[0][1]


class ShuffleSpec:
    """Partition functions + shape of one exchange. ``num_partitions`` is
    the stage-pinned reducer count (None = infer from the upstream block
    count, falling back to ``config.shuffle_default_partitions``).
    ``schema`` optionally pins the exchange's output schema so an all-empty
    reduce still emits a typed (never column-less) block."""

    def __init__(self, name: str,
                 map_fn: Callable,
                 reduce_fn: Callable,
                 num_partitions: Optional[int] = None,
                 sample_fn: Optional[Callable] = None,
                 plan_fn: Optional[Callable] = None,
                 infer_cap: Optional[int] = None,
                 schema: Any = None):
        self.name = name
        self.map_fn = map_fn
        self.reduce_fn = reduce_fn
        self.num_partitions = num_partitions
        self.sample_fn = sample_fn
        self.plan_fn = plan_fn
        self.infer_cap = infer_cap
        self.schema = schema

    @property
    def needs_plan(self) -> bool:
        return self.plan_fn is not None

    def resolve_partitions(self, upstream_hint: Optional[int]) -> int:
        from ray_tpu.core.config import config

        if self.num_partitions is not None:
            return max(1, self.num_partitions)
        n = upstream_hint or config.shuffle_default_partitions
        if self.infer_cap is not None:
            n = min(n, self.infer_cap)
        return max(1, n)


# --------------------------------------------------------------- random_shuffle
def random_shuffle_spec(seed: Optional[int],
                        schema: Any = None) -> ShuffleSpec:
    """Rows scatter to uniform-random reducers in map tasks; each reduce
    permutes within its partition. Map RNG streams off the block index
    (stream tag 0), reduce RNG off the reducer index (stream tag 1)."""
    from ray_tpu.core.config import columnar_exchange_enabled

    columnar = columnar_exchange_enabled()

    def map_fn(block, n, idx, _plan=None):
        rng = derive_rng(seed, 0, idx)
        assign = rng.integers(0, n, block.num_rows)
        outs = (_vectorized_scatter(block, assign, n) if columnar
                else _legacy_scatter(block, assign, n))
        return outs if n > 1 else outs[0]

    def reduce_fn(j, *parts):
        combined = _schema_preserving_concat(list(parts), schema)
        rng = derive_rng(seed, 1, j)
        if combined.num_rows:
            combined = combined.take(rng.permutation(combined.num_rows))
        return combined

    return ShuffleSpec("random_shuffle", map_fn, reduce_fn, schema=schema)


# ------------------------------------------------------------------ repartition
def repartition_spec(num_blocks: int, schema: Any = None) -> ShuffleSpec:
    """Order-preserving repartition: the plan phase counts rows per block,
    computes global output boundaries, and each map slices its block's
    overlap with every output range."""

    def sample_fn(block, _idx):
        return block.num_rows

    def plan_fn(counts: List[int], n: int):
        total = sum(counts)
        per, rem = divmod(total, n)
        out_sizes = [per + (1 if j < rem else 0) for j in range(n)]
        out_bounds = []
        acc = 0
        for s in out_sizes:
            out_bounds.append((acc, acc + s))
            acc += s
        plans = []
        g = 0
        for c in counts:
            g0, g1 = g, g + c
            plan = []
            for (o0, o1) in out_bounds:
                lo, hi = max(g0, o0), min(g1, o1)
                plan.append((lo - g0, max(lo, hi) - g0) if hi > lo else (0, 0))
            plans.append(plan)
            g += c
        return plans

    def map_fn(block, n, idx, plan):
        from ray_tpu.data.block import BlockAccessor

        acc = BlockAccessor(block)
        outs = [acc.slice(s, e) for (s, e) in plan[idx]]
        return tuple(outs) if n > 1 else outs[0]

    def reduce_fn(_j, *parts):
        return _schema_preserving_concat(list(parts), schema)

    return ShuffleSpec(f"repartition({num_blocks})", map_fn, reduce_fn,
                       num_partitions=num_blocks,
                       sample_fn=sample_fn, plan_fn=plan_fn, schema=schema)


# ------------------------------------------------------------------------- sort
def _dedupe_boundaries(bounds, flat, n: int):
    """Boundary hygiene for skewed keys. A value may occupy several
    boundary ranks either because it is genuinely heavy (>= 1/n of the
    samples — the duplicates are KEPT: they encode how many reducer slots
    the tied rows spread across, see ``_range_assign``) or as a
    small-sample artifact, in which case the duplicate is advanced to the
    next distinct sample value so distinct keys keep distinct boundaries.
    Boundaries that run off the top are dropped (their reducers stay
    empty) rather than duplicated."""
    import numpy as np

    total = len(flat)
    out: list = []
    for b in bounds:
        keep = True
        while out and b <= out[-1]:
            cnt = (np.searchsorted(flat, b, side="right")
                   - np.searchsorted(flat, b, side="left"))
            if cnt * n >= total:
                break  # genuinely heavy: keep the duplicate rank
            nxt = np.searchsorted(flat, out[-1], side="right")
            if nxt >= total:
                keep = False
                break
            b = flat[nxt]
        if keep:
            out.append(b)
    return np.asarray(out) if out else np.array([])


def _range_assign(col, bounds, n: int, descending: bool, idx: int):
    """Reducer assignment for a range partition with deterministic tie
    spreading: a boundary value duplicated in ``bounds`` marks a heavy key
    whose rows round-robin across the value's whole reducer span instead
    of funneling into one reducer (every reducer in the span may legally
    hold the tied value — global sort order is preserved). Offsets derive
    from (block index, row occurrence), never completion order."""
    import numpy as np

    bounds = np.asarray(bounds)
    assign = np.searchsorted(bounds, col, side="right")
    if len(bounds):
        lo_b = np.searchsorted(bounds, bounds, side="left")
        hi_b = np.searchsorted(bounds, bounds, side="right")
        for v in np.unique(bounds[(hi_b - lo_b) >= 2]):
            lo = int(np.searchsorted(bounds, v, side="left"))
            hi = int(np.searchsorted(bounds, v, side="right"))
            rows = np.nonzero(col == v)[0]
            if len(rows):
                assign[rows] = lo + ((np.arange(len(rows)) + idx)
                                     % (hi - lo + 1))
    if descending:
        assign = (n - 1) - assign
    return assign


def sort_spec(key: str, descending: bool,
              num_blocks: Optional[int], schema: Any = None) -> ShuffleSpec:
    """Range-partition sort: the plan phase samples boundary candidates per
    block (overlapping with mapping-side upstream production), maps
    range-split on the sampled boundaries, reduces sorted-merge. Columnar:
    the map pre-sorts each partition slice by key (stable), and the reduce
    merges the pre-sorted runs in block-index order — equal keys land in
    (block, original row) order under every kernel combination."""
    from ray_tpu.core.config import columnar_exchange_enabled

    columnar = columnar_exchange_enabled()

    def sample_fn(block, idx):
        import numpy as np

        col = block.column(key).to_numpy(zero_copy_only=False)
        if len(col) == 0:
            return np.array([])
        k = min(64, len(col))
        pick = derive_rng(0, 2, idx).choice(len(col), size=k, replace=False)
        return col[pick]

    def plan_fn(samples, n: int):
        import numpy as np

        flat = (np.concatenate([s for s in samples if len(s)])
                if any(len(s) for s in samples) else np.array([0.0]))
        flat.sort()
        if n <= 1:
            return np.array([])
        bounds = flat[np.linspace(0, len(flat) - 1, n + 1)[1:-1].astype(int)]
        return _dedupe_boundaries(bounds, flat, n)

    def map_fn(block, n, idx, bounds):
        import numpy as np

        col = block.column(key).to_numpy(zero_copy_only=False)
        assign = _range_assign(col, bounds, n, descending, idx)
        if not columnar:
            outs = _legacy_scatter(block, assign, n)
            return outs if n > 1 else outs[0]
        from ray_tpu.data.block import sort_key_array

        keys_np = sort_key_array(block, key)
        if keys_np is None:
            # no fast key layout: partition vectorized, leave runs unsorted
            # (the reduce detects this and falls back to the full re-sort)
            outs = _vectorized_scatter(block, assign, n)
            return outs if n > 1 else outs[0]
        order = np.argsort(assign, kind="stable")
        starts = np.searchsorted(assign[order], np.arange(n + 1))
        segs = []
        for j in range(n):
            seg = order[starts[j]:starts[j + 1]]
            segs.append(seg[_stable_order(keys_np[seg], descending)])
        reordered = block.take(np.concatenate(segs))
        outs = tuple(
            reordered.slice(int(starts[j]), int(starts[j + 1] - starts[j]))
            for j in range(n))
        return outs if n > 1 else outs[0]

    def _merge_parts(parts):
        """Columnar reduce fast path: verify every run is pre-sorted with a
        fast key layout, then k-way merge. None = take the fallback."""
        import numpy as np

        from ray_tpu.data.block import concat_blocks, sort_key_array

        keys = []
        for p in parts:
            k = sort_key_array(p, key)
            if k is None:
                return None
            ka = _asc_keys(k, descending)
            if len(ka) > 1 and not np.all(ka[1:] >= ka[:-1]):
                return None  # a fallback map left this run unsorted
            keys.append(ka)
        return concat_blocks(parts).take(_merge_sorted_asc(keys))

    def reduce_fn(_j, *parts):
        import pyarrow.compute as pc

        nonempty = [p for p in parts if p.num_rows]
        if not nonempty:
            return _schema_preserving_concat(list(parts), schema)
        if columnar:
            merged = _merge_parts(nonempty)
            if merged is not None:
                return merged
        combined = _schema_preserving_concat(nonempty, schema)
        order = "descending" if descending else "ascending"
        return combined.take(pc.sort_indices(combined, sort_keys=[(key, order)]))

    return ShuffleSpec(f"sort({key})", map_fn, reduce_fn,
                       num_partitions=num_blocks,
                       sample_fn=sample_fn, plan_fn=plan_fn, schema=schema)


# -------------------------------------------------------------- groupby + aggs
def aggregate_spec(keys: List[str], aggs: List[Any],
                   num_blocks: Optional[int],
                   schema: Any = None) -> Optional[ShuffleSpec]:
    """Hash-partition groupby: maps pre-combine per-group partials and hash-
    scatter them; reduces merge partials and finalize. Keyless (global)
    aggregation returns None — a single-output barrier is already optimal."""
    if not keys:
        return None
    names = ",".join(a.name for a in aggs)
    from ray_tpu.core.config import columnar_exchange_enabled

    columnar = columnar_exchange_enabled()

    def map_fn(block, n, _idx, _plan=None):
        from ray_tpu.data.aggregate import make_partial
        from ray_tpu.data.executor import _stable_hash_partition

        partial = make_partial(block, keys, aggs)
        if n == 1:
            return partial
        assign = _stable_hash_partition(partial, keys, n)
        outs = (_vectorized_scatter(partial, assign, n) if columnar
                else _legacy_scatter(partial, assign, n))
        return outs

    def reduce_fn(_j, *parts):
        from ray_tpu.data.aggregate import make_partial, merge_partials

        expected = {c for a in aggs for c, _ in a.merge_aggs()}
        norm = [p if expected.issubset(set(p.column_names))
                else make_partial(p, keys, aggs) for p in parts]
        return merge_partials(norm, keys, aggs)

    return ShuffleSpec(f"aggregate({','.join(keys)}:{names})",
                       map_fn, reduce_fn, num_partitions=num_blocks,
                       infer_cap=8, schema=schema)
