"""Shuffle exchange specs: the partition functions of one all-to-all.

One ``ShuffleSpec`` fully describes an exchange:

- ``map_fn(block, n_out, block_idx, plan)`` splits one input block into
  ``n_out`` per-reducer partition blocks (runs in a remote partitioner
  task);
- ``reduce_fn(j, *parts)`` combines partition ``j`` of every map output
  into one output block (runs in a remote reduce task);
- optional plan phase for exchanges that need global knowledge before
  partitioning: ``sample_fn(block, block_idx)`` extracts a tiny sample per
  block (sort boundary candidates, repartition row counts) and
  ``plan_fn(samples, n_out)`` turns the collected samples into the plan
  object every map task receives.

The SAME spec drives both the streaming operators (``shuffle.operators``)
and the legacy ``AllToAllOp`` barrier exchange (``data/executor.py``), so
flipping ``RTPU_STREAMING_SHUFFLE`` changes scheduling, never data.

Determinism: every RNG here is seeded from the BLOCK INDEX (stable position
in the upstream stream), never from dispatch/completion order — a seeded
``random_shuffle`` produces identical rows no matter how maps interleave.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

_MASK64 = (1 << 64) - 1


def derive_rng(seed: Optional[int], *stream: int):
    """Deterministic per-(seed, stream...) generator. ``None`` seed stays
    nondeterministic. Components are masked to uint64 so negative seeds and
    large indices feed SeedSequence legally."""
    import numpy as np

    if seed is None:
        return np.random.default_rng(None)
    return np.random.default_rng(
        np.random.SeedSequence([seed & _MASK64, *[s & _MASK64 for s in stream]])
    )


def _schema_preserving_concat(parts: List[Any]):
    """Concat partition blocks, keeping the schema when every part is empty
    (a column-less output block breaks downstream column refs)."""
    from ray_tpu.data.block import concat_blocks

    nonempty = [p for p in parts if p.num_rows]
    if not nonempty and parts:
        return parts[0].slice(0, 0)
    return concat_blocks(nonempty)


class ShuffleSpec:
    """Partition functions + shape of one exchange. ``num_partitions`` is
    the stage-pinned reducer count (None = infer from the upstream block
    count, falling back to ``config.shuffle_default_partitions``)."""

    def __init__(self, name: str,
                 map_fn: Callable,
                 reduce_fn: Callable,
                 num_partitions: Optional[int] = None,
                 sample_fn: Optional[Callable] = None,
                 plan_fn: Optional[Callable] = None,
                 infer_cap: Optional[int] = None):
        self.name = name
        self.map_fn = map_fn
        self.reduce_fn = reduce_fn
        self.num_partitions = num_partitions
        self.sample_fn = sample_fn
        self.plan_fn = plan_fn
        self.infer_cap = infer_cap

    @property
    def needs_plan(self) -> bool:
        return self.plan_fn is not None

    def resolve_partitions(self, upstream_hint: Optional[int]) -> int:
        from ray_tpu.core.config import config

        if self.num_partitions is not None:
            return max(1, self.num_partitions)
        n = upstream_hint or config.shuffle_default_partitions
        if self.infer_cap is not None:
            n = min(n, self.infer_cap)
        return max(1, n)


# --------------------------------------------------------------- random_shuffle
def random_shuffle_spec(seed: Optional[int]) -> ShuffleSpec:
    """Rows scatter to uniform-random reducers in map tasks; each reduce
    permutes within its partition. Map RNG streams off the block index
    (stream tag 0), reduce RNG off the reducer index (stream tag 1)."""

    def map_fn(block, n, idx, _plan=None):
        import numpy as np

        rng = derive_rng(seed, 0, idx)
        assign = rng.integers(0, n, block.num_rows)
        outs = tuple(block.take(np.nonzero(assign == j)[0]) for j in range(n))
        return outs if n > 1 else outs[0]

    def reduce_fn(j, *parts):
        combined = _schema_preserving_concat(list(parts))
        rng = derive_rng(seed, 1, j)
        if combined.num_rows:
            combined = combined.take(rng.permutation(combined.num_rows))
        return combined

    return ShuffleSpec("random_shuffle", map_fn, reduce_fn)


# ------------------------------------------------------------------ repartition
def repartition_spec(num_blocks: int) -> ShuffleSpec:
    """Order-preserving repartition: the plan phase counts rows per block,
    computes global output boundaries, and each map slices its block's
    overlap with every output range."""

    def sample_fn(block, _idx):
        return block.num_rows

    def plan_fn(counts: List[int], n: int):
        total = sum(counts)
        per, rem = divmod(total, n)
        out_sizes = [per + (1 if j < rem else 0) for j in range(n)]
        out_bounds = []
        acc = 0
        for s in out_sizes:
            out_bounds.append((acc, acc + s))
            acc += s
        plans = []
        g = 0
        for c in counts:
            g0, g1 = g, g + c
            plan = []
            for (o0, o1) in out_bounds:
                lo, hi = max(g0, o0), min(g1, o1)
                plan.append((lo - g0, max(lo, hi) - g0) if hi > lo else (0, 0))
            plans.append(plan)
            g += c
        return plans

    def map_fn(block, n, idx, plan):
        from ray_tpu.data.block import BlockAccessor

        acc = BlockAccessor(block)
        outs = [acc.slice(s, e) for (s, e) in plan[idx]]
        return tuple(outs) if n > 1 else outs[0]

    def reduce_fn(_j, *parts):
        return _schema_preserving_concat(list(parts))

    return ShuffleSpec(f"repartition({num_blocks})", map_fn, reduce_fn,
                       num_partitions=num_blocks,
                       sample_fn=sample_fn, plan_fn=plan_fn)


# ------------------------------------------------------------------------- sort
def sort_spec(key: str, descending: bool,
              num_blocks: Optional[int]) -> ShuffleSpec:
    """Range-partition sort: the plan phase samples boundary candidates per
    block (overlapping with mapping-side upstream production), maps
    range-split on the sampled boundaries, reduces sorted-merge."""

    def sample_fn(block, idx):
        import numpy as np

        col = block.column(key).to_numpy(zero_copy_only=False)
        if len(col) == 0:
            return np.array([])
        k = min(64, len(col))
        pick = derive_rng(0, 2, idx).choice(len(col), size=k, replace=False)
        return col[pick]

    def plan_fn(samples, n: int):
        import numpy as np

        flat = (np.concatenate([s for s in samples if len(s)])
                if any(len(s) for s in samples) else np.array([0.0]))
        flat.sort()
        if n <= 1:
            return np.array([])
        return flat[np.linspace(0, len(flat) - 1, n + 1)[1:-1].astype(int)]

    def map_fn(block, n, _idx, bounds):
        import numpy as np

        col = block.column(key).to_numpy(zero_copy_only=False)
        assign = np.searchsorted(bounds, col, side="right")
        if descending:
            assign = (n - 1) - assign
        outs = tuple(block.take(np.nonzero(assign == j)[0]) for j in range(n))
        return outs if n > 1 else outs[0]

    def reduce_fn(_j, *parts):
        import pyarrow.compute as pc

        combined = _schema_preserving_concat(list(parts))
        if not combined.num_rows:
            return combined
        order = "descending" if descending else "ascending"
        return combined.take(pc.sort_indices(combined, sort_keys=[(key, order)]))

    return ShuffleSpec(f"sort({key})", map_fn, reduce_fn,
                       num_partitions=num_blocks,
                       sample_fn=sample_fn, plan_fn=plan_fn)


# -------------------------------------------------------------- groupby + aggs
def aggregate_spec(keys: List[str], aggs: List[Any],
                   num_blocks: Optional[int]) -> Optional[ShuffleSpec]:
    """Hash-partition groupby: maps pre-combine per-group partials and hash-
    scatter them; reduces merge partials and finalize. Keyless (global)
    aggregation returns None — a single-output barrier is already optimal."""
    if not keys:
        return None
    names = ",".join(a.name for a in aggs)

    def map_fn(block, n, _idx, _plan=None):
        import numpy as np

        from ray_tpu.data.aggregate import make_partial
        from ray_tpu.data.executor import _stable_hash_partition

        partial = make_partial(block, keys, aggs)
        if n == 1:
            return partial
        assign = _stable_hash_partition(partial, keys, n)
        return tuple(partial.take(np.nonzero(assign == j)[0]) for j in range(n))

    def reduce_fn(_j, *parts):
        from ray_tpu.data.aggregate import make_partial, merge_partials

        expected = {c for a in aggs for c, _ in a.merge_aggs()}
        norm = [p if expected.issubset(set(p.column_names))
                else make_partial(p, keys, aggs) for p in parts]
        return merge_partials(norm, keys, aggs)

    return ShuffleSpec(f"aggregate({','.join(keys)}:{names})",
                       map_fn, reduce_fn, num_partitions=num_blocks,
                       infer_cap=8)
