"""Streaming distributed shuffle subsystem on the raw-frame data plane.

Reference capability: Exoshuffle (shuffle as a library over a generic
object store) + the push/pull hybrid architecture of Magnet. The subsystem
replaces the ``AllToAllOp`` barrier for sort / groupby / repartition /
random_shuffle:

- ``spec.ShuffleSpec``: the partition functions of one exchange (map-side
  split, reduce-side combine, optional boundary-sampling plan), shared by
  the streaming operators AND the legacy barrier path so A/B runs produce
  identical data;
- ``coordinator.ShuffleCoordinator``: the driver-side partition table —
  which map produced which per-reducer block, admission accounting, and
  per-shuffle stats (bytes exchanged, spill, admission stalls);
- ``operators.ShuffleMapOp`` / ``ShuffleReduceOp``: the physical operators
  the planner compiles shuffle stages into when
  ``config.streaming_shuffle_enabled()`` (env ``RTPU_STREAMING_SHUFFLE=0``
  falls back to the barrier exchange).
"""

from ray_tpu.data.shuffle.coordinator import ShuffleCoordinator
from ray_tpu.data.shuffle.operators import ShuffleMapOp, ShuffleReduceOp
from ray_tpu.data.shuffle.spec import ShuffleSpec

__all__ = [
    "ShuffleCoordinator",
    "ShuffleMapOp",
    "ShuffleReduceOp",
    "ShuffleSpec",
]
