"""Driver-side shuffle coordination: partition table, spill-aware reduce
admission, and per-shuffle stats.

The coordinator is shared by one ``ShuffleMapOp``/``ShuffleReduceOp`` pair.
Map tasks deposit their per-reducer partition refs here as they complete
(in any order — the table is keyed by block index, so downstream
determinism never depends on completion order); the reduce op asks
``admit()`` before dispatching reduce ``j``.

Spill-aware admission: the bytes of every ADMITTED-but-unfinished reduce's
partition set are tracked against ``admission_budget`` (a fraction of the
Data memory budget). A shuffle whose working set exceeds aggregate arena
memory simply defers reduce admission — un-admitted partition blocks stay
at rest in the object store, which spills them under pressure and restores
them when the reduce task's pull arrives — instead of OOMing the arena.
One reduce is always admissible (a budget must throttle, never wedge)."""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ray_tpu.core.object_ref import ObjectRef


class ShuffleCoordinator:
    def __init__(self, name: str, n_out: int,
                 admission_budget: Optional[int] = None):
        from ray_tpu.core.config import config

        self.name = name
        self.n_out = n_out
        if admission_budget is None:
            admission_budget = int(config.object_store_memory_bytes
                                   * config.data_memory_fraction
                                   * config.shuffle_admission_memory_fraction)
        self.admission_budget = max(1, admission_budget)
        # block index -> [partition ref per reducer], parallel sizes table
        self._parts: Dict[int, List[ObjectRef]] = {}
        self._sizes: Dict[int, List[Optional[int]]] = {}
        self.expected_maps: Optional[int] = None
        # ---- admission accounting
        self._admitted: set = set()
        self._reduced: set = set()
        self._inflight_bytes = 0
        self._stall_started: Optional[float] = None
        # ---- per-shuffle stats (surfaced through Dataset.stats())
        self.stats: Dict[str, Any] = {
            "maps": 0, "reduces": 0, "partitions": n_out,
            "exchange_bytes": 0, "admission_stall_s": 0.0,
            "admission_deferrals": 0, "spill_bytes": 0, "stripe_pulls": 0,
            # columnar exchange decode accounting: bytes of Arrow columns
            # reconstructed as zero-copy views over exchange payloads vs
            # bytes that took the copy/decode fallback (pyobj et al).
            # Cluster-wide worker counters + this driver process; local
            # mode records 0 (LocalRuntime never serializes blocks).
            "zero_copy_bytes": 0, "copied_bytes": 0,
        }
        self._baseline_metrics: Optional[Dict[str, int]] = None

    # ----------------------------------------------------------- partition table
    def add_map_output(self, block_idx: int, refs: List[ObjectRef],
                       sizes: List[Optional[int]]) -> None:
        self._parts[block_idx] = list(refs)
        self._sizes[block_idx] = list(sizes)
        self.stats["maps"] += 1
        self.stats["exchange_bytes"] += sum(s or 0 for s in sizes)

    @property
    def num_maps(self) -> int:
        return len(self._parts)

    def maps_complete(self) -> bool:
        return (self.expected_maps is not None
                and len(self._parts) >= self.expected_maps)

    def partition_refs(self, j: int) -> List[ObjectRef]:
        """Partition ``j`` of every map output, in BLOCK INDEX order — reduce
        input order must not depend on map completion order (seeded
        random_shuffle and order-preserving repartition rely on it)."""
        return [self._parts[i][j] for i in sorted(self._parts)]

    def partition_bytes(self, j: int) -> int:
        total = 0
        for i, sizes in self._sizes.items():
            s = sizes[j]
            if s is None:
                # unknown (sizes probe failed): assume the map's mean
                known = [x for x in sizes if x is not None]
                s = (sum(known) // len(known)) if known else 1 << 20
            total += s
        return total

    # ------------------------------------------------------------ reduce admission
    def admit(self, j: int) -> bool:
        """May reduce ``j`` dispatch now? Admits when nothing is in flight
        (liveness) or its partition set fits the remaining budget. Tracks
        stall time while a reduce is deferred."""
        if j in self._admitted:
            return True
        need = self.partition_bytes(j)
        if self._inflight_bytes > 0 and \
                self._inflight_bytes + need > self.admission_budget:
            if self._stall_started is None:
                self._stall_started = time.perf_counter()
                self.stats["admission_deferrals"] += 1
            return False
        if self._stall_started is not None:
            self.stats["admission_stall_s"] += \
                time.perf_counter() - self._stall_started
            self._stall_started = None
        self._admitted.add(j)
        self._inflight_bytes += need
        return True

    def mark_reduced(self, j: int) -> None:
        """Reduce ``j`` finished: release its admission bytes and drop the
        partition refs (the refs' only remaining holders) so distributed GC
        reclaims the intermediate blocks while the shuffle is still running."""
        if j in self._reduced:
            return
        self._reduced.add(j)
        self.stats["reduces"] += 1
        if j in self._admitted:
            self._inflight_bytes = max(
                0, self._inflight_bytes - self.partition_bytes(j))
        for i in self._parts:
            self._parts[i][j] = None

    def finished(self) -> bool:
        return self.maps_complete() and len(self._reduced) >= (
            self.n_out if self.num_maps else 0)

    # ------------------------------------------------------------------- metrics
    @staticmethod
    def _cluster_metrics() -> Dict[str, int]:
        """Best-effort cluster-wide spill/stripe counters (zeros when the
        runtime has no agents — local mode — or any RPC fails)."""
        out = {"spill_bytes": 0, "stripe_pulls": 0,
               "zero_copy_bytes": 0, "copied_bytes": 0}
        # the driver process decodes too (direct-data-plane gets of partition
        # blocks land here): fold its own counters into the cluster total
        from ray_tpu.core import serialization

        snap = serialization.arrow_decode_snapshot()
        out["zero_copy_bytes"] += snap["zero_copy_bytes"]
        out["copied_bytes"] += snap["copied_bytes"]
        try:
            from ray_tpu import api as _api

            runtime = _api.global_worker().runtime
            gcs = getattr(runtime, "gcs", None)
            if gcs is None:
                return out
            for info in gcs.call("get_nodes", timeout=5.0):
                if not info.get("Alive"):
                    continue
                try:
                    client = runtime._agent_client(info["NodeManagerAddress"])
                    ninfo = client.call("node_info", timeout=5.0)
                    usage = ninfo["store"]
                    out["spill_bytes"] += int(usage.get("spilled_bytes", 0))
                    decode = ninfo.get("decode") or {}
                    out["zero_copy_bytes"] += int(decode.get("zero_copy_bytes", 0))
                    out["copied_bytes"] += int(decode.get("copied_bytes", 0))
                    tstats = client.call("transfer_stats", timeout=5.0)
                    out["stripe_pulls"] += int(tstats.get("stripe_pulls", 0))
                except Exception:  # noqa: BLE001 - dead node mid-scan
                    continue
        except Exception:  # noqa: BLE001 - stats must never fail a shuffle
            pass
        return out

    def sample_baseline(self) -> None:
        self._baseline_metrics = self._cluster_metrics()

    def finalize_metrics(self) -> None:
        if self._baseline_metrics is None:
            return
        now = self._cluster_metrics()
        base = self._baseline_metrics
        self.stats["spill_bytes"] = max(
            0, now["spill_bytes"] - base["spill_bytes"])
        self.stats["stripe_pulls"] = max(
            0, now["stripe_pulls"] - base["stripe_pulls"])
        self.stats["zero_copy_bytes"] = max(
            0, now["zero_copy_bytes"] - base["zero_copy_bytes"])
        self.stats["copied_bytes"] = max(
            0, now["copied_bytes"] - base["copied_bytes"])
        self._baseline_metrics = None
