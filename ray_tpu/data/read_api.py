"""Data sources (reference: python/ray/data/read_api.py — 19 read_* entry
points; the core family implemented here, each producing read tasks that
execute in parallel on the cluster)."""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import block_from_batch, block_from_rows
from ray_tpu.data.dataset import Dataset


def _parallel_read(make_tasks: List[Callable[[], Any]], name: str) -> Dataset:
    """Each thunk becomes a remote read task producing one block. The
    streaming executor's InputData operator owns submission pacing
    (concurrency cap + memory budget), so reads never race ahead of the
    consumer."""
    from ray_tpu.data.execution.interfaces import ReadTaskSource

    return Dataset(ReadTaskSource(make_tasks, name))


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    import builtins

    parallelism = max(1, min(parallelism, n or 1))
    per = (n + parallelism - 1) // parallelism

    def make(lo: int, hi: int):
        return lambda: block_from_batch({"id": np.arange(lo, hi, dtype=np.int64)})

    tasks = [make(i * per, min((i + 1) * per, n))
             for i in builtins.range(parallelism) if i * per < n]
    return _parallel_read(tasks, "range")


def range_tensor(n: int, *, shape: tuple = (1,), parallelism: int = 8) -> Dataset:
    import builtins

    parallelism = max(1, min(parallelism, n or 1))
    per = (n + parallelism - 1) // parallelism

    def make(lo: int, hi: int):
        def thunk():
            count = hi - lo
            data = np.broadcast_to(
                np.arange(lo, hi, dtype=np.int64).reshape((count,) + (1,) * len(shape)),
                (count,) + shape,
            ).copy()
            return block_from_batch({"data": data})

        return thunk

    tasks = [make(i * per, min((i + 1) * per, n))
             for i in builtins.range(parallelism) if i * per < n]
    return _parallel_read(tasks, "range_tensor")


def from_items(items: List[Any], *, parallelism: int = 4) -> Dataset:
    import builtins

    parallelism = max(1, min(parallelism, len(items) or 1))
    per = (len(items) + parallelism - 1) // parallelism
    chunks = [items[i * per : (i + 1) * per] for i in builtins.range(parallelism)]
    chunks = [c for c in chunks if c]

    def make(chunk):
        return lambda: block_from_rows(chunk)

    return _parallel_read([make(c) for c in chunks], "items")


def from_numpy(arrays: Dict[str, np.ndarray]) -> Dataset:
    def thunk():
        return block_from_batch(arrays)

    return _parallel_read([thunk], "numpy")


def from_pandas(df) -> Dataset:
    import pyarrow as pa

    def thunk():
        return pa.Table.from_pandas(df, preserve_index=False)

    return _parallel_read([thunk], "pandas")


def from_arrow(table) -> Dataset:
    return _parallel_read([lambda: table], "arrow")


def _expand_paths(paths, suffixes: tuple) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                files += [os.path.join(root, f) for f in sorted(names)
                          if f.endswith(suffixes)]
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(f"no files found under {paths}")
    return files


def read_parquet(paths, **kwargs) -> Dataset:
    files = _expand_paths(paths, (".parquet",))

    def make(f):
        def thunk():
            import pyarrow.parquet as pq

            return pq.read_table(f)

        return thunk

    return _parallel_read([make(f) for f in files], "parquet")


def read_csv(paths, **kwargs) -> Dataset:
    files = _expand_paths(paths, (".csv",))

    def make(f):
        def thunk():
            import pyarrow.csv as pacsv

            return pacsv.read_csv(f)

        return thunk

    return _parallel_read([make(f) for f in files], "csv")


def read_json(paths, **kwargs) -> Dataset:
    files = _expand_paths(paths, (".json", ".jsonl"))

    def make(f):
        def thunk():
            import pyarrow.json as pajson

            return pajson.read_json(f)

        return thunk

    return _parallel_read([make(f) for f in files], "json")


def read_text(paths, **kwargs) -> Dataset:
    files = _expand_paths(paths, (".txt",))

    def make(f):
        def thunk():
            with open(f) as fh:
                return block_from_batch({"text": np.asarray(fh.read().splitlines(), dtype=object)})

        return thunk

    return _parallel_read([make(f) for f in files], "text")


def read_numpy(paths, **kwargs) -> Dataset:
    files = _expand_paths(paths, (".npy", ".npz"))

    def make(f):
        def thunk():
            arr = np.load(f, allow_pickle=False)
            if hasattr(arr, "files"):  # npz
                return block_from_batch({k: arr[k] for k in arr.files})
            return block_from_batch({"data": arr})

        return thunk

    return _parallel_read([make(f) for f in files], "numpy")


def read_binary_files(paths, **kwargs) -> Dataset:
    files = _expand_paths(paths, ())

    def make(f):
        def thunk():
            with open(f, "rb") as fh:
                data = fh.read()
            return block_from_rows([{"path": f, "bytes": data}])

        return thunk

    return _parallel_read([make(f) for f in files], "binary")


IMAGE_SUFFIXES = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp", ".tif", ".tiff")


def read_images(paths, *, size: Optional[tuple] = None, mode: str = "RGB",
                include_paths: bool = False, files_per_block: int = 64,
                **kwargs) -> Dataset:
    """Decode image files into an "image" tensor column (reference:
    read_images / _internal/datasource/image_datasource.py). ``size=(h, w)``
    resizes (bilinear) so blocks stack into one [N, h, w, C] tensor — the
    shape contract the BASELINE image-pipeline → TPU config needs; without
    ``size`` images keep native shapes (object column)."""
    files = _expand_paths(paths, IMAGE_SUFFIXES)
    groups = _chunks(files, files_per_block)

    def make(group):
        def thunk():
            from PIL import Image

            arrays, names = [], []
            for f in group:
                img = Image.open(f)
                if mode:
                    img = img.convert(mode)
                if size is not None:
                    img = img.resize((size[1], size[0]), Image.BILINEAR)
                arrays.append(np.asarray(img))
                names.append(f)
            if size is not None:
                batch = {"image": np.stack(arrays)}
                if include_paths:
                    batch["path"] = np.asarray(names, dtype=object)
                return block_from_batch(batch)
            rows = [{"image": a} for a in arrays]
            if include_paths:
                for r, f in zip(rows, names):
                    r["path"] = f
            # native shapes: ALWAYS the pyobj layout — a coincidentally
            # shape-uniform block would otherwise become a tensor column
            # with a schema incompatible with its sibling blocks
            return block_from_rows(rows, object_columns={"image"})

        return thunk

    return _parallel_read([make(g) for g in groups], "images")


def _chunks(seq: List[Any], n: int) -> List[List[Any]]:
    import builtins

    return [seq[i : i + n] for i in builtins.range(0, len(seq), n)]


def read_tfrecords(paths, *, verify_crc: bool = False, **kwargs) -> Dataset:
    """TFRecord files of tf.train.Example records (reference: read_tfrecords
    / tfrecords_datasource.py), decoded by the native wire codec in
    ray_tpu/data/tfrecord.py — no TensorFlow dependency. Scalar features
    unwrap to scalars; multi-value features stay lists."""
    files = _expand_paths(paths, (".tfrecord", ".tfrecords"))

    def make(f):
        def thunk():
            from ray_tpu.data.tfrecord import decode_example, read_records

            rows = []
            for payload in read_records(f, verify_crc=verify_crc):
                row = {}
                for name, values in decode_example(payload).items():
                    row[name] = values[0] if len(values) == 1 else values
                rows.append(row)
            return block_from_rows(rows)

        return thunk

    return _parallel_read([make(f) for f in files], "tfrecords")


def read_webdataset(paths, *, decode: bool = True, **kwargs) -> Dataset:
    """WebDataset tar archives (reference: read_webdataset /
    webdataset_datasource.py): members sharing a basename form one sample;
    extensions become columns. With ``decode=True``, jpg/png decode to
    arrays, ``.cls`` to int, ``.json`` to dicts, ``.txt`` to str, ``.npy``
    to arrays; unknown extensions stay raw bytes."""
    files = _expand_paths(paths, (".tar",))

    def decode_member(ext: str, data: bytes) -> Any:
        if not decode:
            return data
        if ext in ("jpg", "jpeg", "png", "bmp", "webp"):
            import io

            from PIL import Image

            return np.asarray(Image.open(io.BytesIO(data)))
        if ext == "cls":
            return int(data)
        if ext == "json":
            import json

            return json.loads(data)
        if ext == "txt":
            return data.decode()
        if ext == "npy":
            import io

            return np.load(io.BytesIO(data), allow_pickle=False)
        return data

    def make(f):
        def thunk():
            import tarfile

            samples: Dict[str, Dict[str, Any]] = {}
            order: List[str] = []
            with tarfile.open(f) as tar:
                for member in tar:
                    if not member.isfile():
                        continue
                    # WebDataset convention: key = full member path up to the
                    # FIRST dot of the basename (directories stay part of the
                    # key, so train/0001.* and val/0001.* are distinct samples)
                    dirname, base = os.path.split(member.name.lstrip("./"))
                    stem, _dot, ext = base.partition(".")
                    key = os.path.join(dirname, stem) if dirname else stem
                    if key not in samples:
                        samples[key] = {"__key__": key}
                        order.append(key)
                    data = tar.extractfile(member).read()
                    samples[key][ext.lower()] = decode_member(ext.lower(), data)
            rows = [samples[k] for k in order]
            # decoded images vary in shape globally: force pyobj layout for
            # any column holding ndarrays (same schema-stability argument as
            # read_images without size)
            nd_cols = {k for r in rows for k, v in r.items()
                       if isinstance(v, np.ndarray)}
            return block_from_rows(rows, object_columns=nd_cols or None)

        return thunk

    return _parallel_read([make(f) for f in files], "webdataset")
