"""Streaming executor: pull-based pipelined execution of the operator DAG.

Reference capability: python/ray/data/_internal/execution/streaming_executor.py
(:48, scheduling loop :272 — select_operator_to_run under resource budgets,
process_completed_tasks, backpressure via concurrency caps). Redesign:

- each logical stage becomes a pipelined pool of remote tasks over block
  refs; a stage keeps at most ``max_in_flight`` tasks outstanding
  (concurrency-cap backpressure, the reference's
  ConcurrencyCapBackpressurePolicy) and yields output refs as they finish
  — downstream stages consume while upstream still produces;
- blocks live in the object store; only ObjectRefs flow between stages
  (RefBundle equivalent);
- actor-pool stages (class-based map_batches) reuse stateful actors.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.utils.logging import get_logger

logger = get_logger("data.executor")

DEFAULT_MAX_IN_FLIGHT = 4


def _iter_completed(submit_iter: Iterator[ObjectRef], max_in_flight: int,
                    preserve_order: bool = True) -> Iterator[ObjectRef]:
    """Pipelines task submission: keeps up to max_in_flight outstanding,
    yields refs once complete (in submission order when preserve_order)."""
    pending: "collections.deque[ObjectRef]" = collections.deque()
    exhausted = False
    while True:
        while not exhausted and len(pending) < max_in_flight:
            try:
                pending.append(next(submit_iter))
            except StopIteration:
                exhausted = True
                break
        if not pending:
            return
        if preserve_order:
            head = pending.popleft()
            ray_tpu.wait([head], num_returns=1, timeout=None)
            yield head
        else:
            ready, _ = ray_tpu.wait(list(pending), num_returns=1, timeout=None)
            ref = ready[0]
            pending.remove(ref)
            yield ref


class Stage:
    """A transformation of a stream of block refs."""

    name = "stage"

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        raise NotImplementedError


class MapStage(Stage):
    def __init__(
        self,
        name: str,
        block_fn: Callable,  # Block -> Block (pickled to workers)
        max_in_flight: int = DEFAULT_MAX_IN_FLIGHT,
        num_cpus: float = 1.0,
        fn_constructor: Optional[Callable] = None,  # class-based: actor pool
        concurrency: Optional[int] = None,
    ):
        self.name = name
        self.block_fn = block_fn
        self.max_in_flight = max_in_flight
        self.num_cpus = num_cpus
        self.fn_constructor = fn_constructor
        self.concurrency = concurrency

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        if self.fn_constructor is not None:
            yield from self._execute_actor_pool(inputs)
            return
        block_fn = self.block_fn

        @ray_tpu.remote(num_cpus=self.num_cpus, name=f"data::{self.name}")
        def apply(block):
            return block_fn(block)

        def submitted() -> Iterator[ObjectRef]:
            for ref in inputs:
                yield apply.remote(ref)

        yield from _iter_completed(submitted(), self.max_in_flight)

    def _execute_actor_pool(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        """Stateful transform: a pool of actors (reference:
        ActorPoolMapOperator with autoscaling pool; fixed size here)."""
        ctor = self.fn_constructor
        block_fn = self.block_fn
        n = max(1, self.concurrency or 2)

        @ray_tpu.remote(num_cpus=self.num_cpus)
        class _MapWorker:
            def __init__(self):
                self.fn = ctor()

            def apply(self, block):
                return block_fn(block, self.fn)

        from ray_tpu.util.actor_pool import ActorPool

        actors = [_MapWorker.remote() for _ in range(n)]
        pool = ActorPool(actors)
        try:
            for out in pool.map(lambda a, ref: a.apply.remote(ref), inputs):
                # ActorPool.map yields VALUES; re-put to keep the ref stream
                yield ray_tpu.put(out)
        finally:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:  # noqa: BLE001
                    pass


class RepartitionStage(Stage):
    def __init__(self, num_blocks: int):
        self.name = f"repartition({num_blocks})"
        self.num_blocks = num_blocks

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        from ray_tpu.data.block import BlockAccessor, concat_blocks

        blocks = [ray_tpu.get(r) for r in inputs]
        if not blocks:
            return
        combined = concat_blocks(blocks)
        total = combined.num_rows
        per = max(1, total // self.num_blocks)
        acc = BlockAccessor(combined)
        for i in range(self.num_blocks):
            start = i * per
            end = total if i == self.num_blocks - 1 else min((i + 1) * per, total)
            if start >= total:
                break
            yield ray_tpu.put(acc.slice(start, end))


class ShuffleStage(Stage):
    """All-to-all random shuffle (reference: planner/exchange/ shuffle —
    two-phase map/reduce; single-driver merge tier here, upgrade TODO)."""

    def __init__(self, seed: Optional[int] = None):
        self.name = "random_shuffle"
        self.seed = seed

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        import numpy as np

        from ray_tpu.data.block import BlockAccessor, concat_blocks

        blocks = [ray_tpu.get(r) for r in inputs]
        if not blocks:
            return
        combined = concat_blocks(blocks)
        rng = np.random.default_rng(self.seed)
        perm = rng.permutation(combined.num_rows)
        shuffled = combined.take(perm)
        n = max(1, len(blocks))
        acc = BlockAccessor(shuffled)
        per = max(1, shuffled.num_rows // n)
        for i in range(n):
            start = i * per
            end = shuffled.num_rows if i == n - 1 else min((i + 1) * per, shuffled.num_rows)
            if start >= shuffled.num_rows:
                break
            yield ray_tpu.put(acc.slice(start, end))


class StreamingExecutor:
    def __init__(self, stages: List[Stage]):
        self.stages = stages

    def execute(self, source: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        stream = source
        for stage in self.stages:
            stream = stage.execute(stream)
        return stream
