"""Streaming executor: pull-based pipelined execution of the operator DAG.

Reference capability: python/ray/data/_internal/execution/streaming_executor.py
(:48, scheduling loop :272 — select_operator_to_run under resource budgets,
process_completed_tasks, backpressure via concurrency caps). Redesign:

- each logical stage becomes a pipelined pool of remote tasks over block
  refs; a stage keeps at most ``max_in_flight`` tasks outstanding
  (concurrency-cap backpressure, the reference's
  ConcurrencyCapBackpressurePolicy) and yields output refs as they finish
  — downstream stages consume while upstream still produces;
- blocks live in the object store; only ObjectRefs flow between stages
  (RefBundle equivalent);
- actor-pool stages (class-based map_batches) reuse stateful actors.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.utils.logging import get_logger

logger = get_logger("data.executor")

DEFAULT_MAX_IN_FLIGHT = 4


def _iter_completed(submit_iter: Iterator[ObjectRef], max_in_flight: int,
                    preserve_order: bool = True) -> Iterator[ObjectRef]:
    """Pipelines task submission: keeps up to max_in_flight outstanding,
    yields refs once complete (in submission order when preserve_order)."""
    pending: "collections.deque[ObjectRef]" = collections.deque()
    exhausted = False
    while True:
        while not exhausted and len(pending) < max_in_flight:
            try:
                pending.append(next(submit_iter))
            except StopIteration:
                exhausted = True
                break
        if not pending:
            return
        if preserve_order:
            head = pending.popleft()
            ray_tpu.wait([head], num_returns=1, timeout=None)
            yield head
        else:
            ready, _ = ray_tpu.wait(list(pending), num_returns=1, timeout=None)
            ref = ready[0]
            pending.remove(ref)
            yield ref


class Stage:
    """A transformation of a stream of block refs."""

    name = "stage"

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        raise NotImplementedError


class MapStage(Stage):
    def __init__(
        self,
        name: str,
        block_fn: Callable,  # Block -> Block (pickled to workers)
        max_in_flight: int = DEFAULT_MAX_IN_FLIGHT,
        num_cpus: float = 1.0,
        fn_constructor: Optional[Callable] = None,  # class-based: actor pool
        concurrency: Optional[int] = None,
    ):
        self.name = name
        self.block_fn = block_fn
        self.max_in_flight = max_in_flight
        self.num_cpus = num_cpus
        self.fn_constructor = fn_constructor
        self.concurrency = concurrency

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        if self.fn_constructor is not None:
            yield from self._execute_actor_pool(inputs)
            return
        block_fn = self.block_fn

        @ray_tpu.remote(num_cpus=self.num_cpus, name=f"data::{self.name}")
        def apply(block):
            return block_fn(block)

        def submitted() -> Iterator[ObjectRef]:
            for ref in inputs:
                yield apply.remote(ref)

        yield from _iter_completed(submitted(), self.max_in_flight)

    def _execute_actor_pool(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        """Stateful transform: a pool of actors (reference:
        ActorPoolMapOperator with autoscaling pool; fixed size here)."""
        ctor = self.fn_constructor
        block_fn = self.block_fn
        n = max(1, self.concurrency or 2)

        @ray_tpu.remote(num_cpus=self.num_cpus)
        class _MapWorker:
            def __init__(self):
                self.fn = ctor()

            def apply(self, block):
                return block_fn(block, self.fn)

        from ray_tpu.util.actor_pool import ActorPool

        actors = [_MapWorker.remote() for _ in range(n)]
        pool = ActorPool(actors)
        try:
            for out in pool.map(lambda a, ref: a.apply.remote(ref), inputs):
                # ActorPool.map yields VALUES; re-put to keep the ref stream
                yield ray_tpu.put(out)
        finally:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:  # noqa: BLE001
                    pass


def _exchange(inputs: Iterator[ObjectRef], num_outputs: Optional[int],
              split_fn: Callable, reduce_fn: Callable) -> Iterator[ObjectRef]:
    """Two-phase map/reduce exchange (reference: planner/exchange/
    shuffle_task_scheduler): map tasks split every input block into
    num_outputs partitions (refs only — block DATA never touches the
    driver, so datasets larger than any one store spill instead of OOM);
    reduce tasks combine partition j of every map output. Yields reduce
    output refs as they finish."""
    input_refs = list(inputs)
    if not input_refs:
        return
    n_out = num_outputs or len(input_refs)

    split_remote = ray_tpu.remote(num_returns=n_out, name="data::exchange_split")(
        split_fn
    ) if n_out > 1 else None

    # map phase: one split task per input block -> n_out partition refs each
    partitions: List[List[ObjectRef]] = []
    for i, ref in enumerate(input_refs):
        if n_out == 1:
            partitions.append([ref])
        else:
            out = split_remote.remote(ref, n_out, i)
            partitions.append(list(out) if isinstance(out, (list, tuple)) else [out])

    reduce_remote = ray_tpu.remote(name="data::exchange_reduce")(reduce_fn)
    reduce_refs = [
        reduce_remote.remote(j, *[parts[j] for parts in partitions])
        for j in range(n_out)
    ]
    for ref in reduce_refs:
        ray_tpu.wait([ref], num_returns=1, timeout=None)
        yield ref


class RepartitionStage(Stage):
    def __init__(self, num_blocks: int):
        self.name = f"repartition({num_blocks})"
        self.num_blocks = num_blocks

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        def split(block, n, _idx=0):
            from ray_tpu.data.block import BlockAccessor

            acc = BlockAccessor(block)
            total = block.num_rows
            per, rem = divmod(total, n)
            outs, start = [], 0
            for i in range(n):
                end = start + per + (1 if i < rem else 0)
                outs.append(acc.slice(start, end))
                start = end
            return tuple(outs) if n > 1 else outs[0]

        def reduce(_j, *parts):
            from ray_tpu.data.block import concat_blocks

            return concat_blocks(list(parts))

        yield from _exchange(inputs, self.num_blocks, split, reduce)


class ShuffleStage(Stage):
    """Distributed all-to-all random shuffle: rows scatter to random output
    partitions in map tasks, reduce tasks permute within their partition.
    No driver-side materialization (reference: planner/exchange/)."""

    def __init__(self, seed: Optional[int] = None):
        self.name = "random_shuffle"
        self.seed = seed

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        seed = self.seed

        def split(block, n, idx=0):
            import numpy as np

            rng = np.random.default_rng(None if seed is None else seed + idx)
            assign = rng.integers(0, n, block.num_rows)
            outs = tuple(block.take(np.nonzero(assign == j)[0]) for j in range(n))
            return outs if n > 1 else outs[0]

        def reduce(j, *parts):
            import numpy as np

            from ray_tpu.data.block import concat_blocks

            combined = concat_blocks(list(parts))
            rng = np.random.default_rng(None if seed is None else seed + 10_000 + j)
            return combined.take(rng.permutation(combined.num_rows))

        yield from _exchange(inputs, None, split, reduce)


class StageStats:
    """Per-stage execution statistics (reference: _internal/stats.py
    DatasetStats — wall time, block count, rows; collected at the stage
    boundaries the executor already owns)."""

    def __init__(self, name: str):
        self.name = name
        self.wall_s = 0.0
        self.blocks_out = 0
        self.rows_out = 0

    def row(self) -> Dict[str, Any]:
        return {"stage": self.name, "wall_s": round(self.wall_s, 4),
                "blocks": self.blocks_out, "rows": self.rows_out}


class StreamingExecutor:
    def __init__(self, stages: List[Stage], collect_rows: bool = False):
        self.stages = stages
        self.stats: List[StageStats] = []
        # row counting requires a driver-side metadata peek per block; off by
        # default on the hot path, on for Dataset.stats() runs
        self._collect_rows = collect_rows

    def _wrap(self, stage: Stage, stream: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        import time as _time

        st = StageStats(stage.name)
        self.stats.append(st)

        class _TimedUpstream:
            """Accounts time spent pulling from upstream so a stage's wall_s
            is ITS OWN work, not the cumulative pipeline time (pull-based
            chains execute upstream inside downstream's next())."""

            def __init__(self, it):
                self.it = iter(it)
                self.time_in_next = 0.0

            def __iter__(self):
                return self

            def __next__(self):
                t0 = _time.perf_counter()
                try:
                    return next(self.it)
                finally:
                    self.time_in_next += _time.perf_counter() - t0

        upstream = _TimedUpstream(stream)

        def gen() -> Iterator[ObjectRef]:
            it = stage.execute(upstream)
            while True:
                mark = upstream.time_in_next
                t0 = _time.perf_counter()
                try:
                    ref = next(it)
                except StopIteration:
                    st.wall_s += (_time.perf_counter() - t0) - (
                        upstream.time_in_next - mark)
                    return
                st.wall_s += (_time.perf_counter() - t0) - (
                    upstream.time_in_next - mark)
                st.blocks_out += 1
                if self._collect_rows:
                    try:
                        st.rows_out += ray_tpu.get(ref).num_rows
                    except Exception:  # noqa: BLE001
                        pass
                yield ref

        return gen()

    def execute(self, source: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        stream = source
        for stage in self.stages:
            stream = self._wrap(stage, stream)
        return stream

    def summary(self) -> str:
        lines = [f"{'stage':<28}{'wall_s':>10}{'blocks':>8}{'rows':>10}"]
        for st in self.stats:
            r = st.row()
            lines.append(f"{r['stage']:<28}{r['wall_s']:>10}{r['blocks']:>8}"
                         f"{r['rows'] if self._collect_rows else '-':>10}")
        return "\n".join(lines)
