"""Logical stages of the Data plan + the distributed all-to-all exchanges.

Reference capability: python/ray/data/_internal/logical_ops + planner/
exchange/. A ``Stage`` is a LOGICAL description of a transformation;
``ray_tpu.data.execution.planner`` compiles stages into physical operators
and ``execution.streaming_executor.StreamingExecutor`` runs them with
per-operator budgets and backpressure. The old flat per-stage in-flight
window (``_iter_completed``) is gone — pacing decisions live in the
executor's scheduling loop now, not in each stage.

The all-to-all stages (repartition/shuffle/sort/aggregate/zip) keep their
``execute(inputs) -> Iterator[ObjectRef]`` methods: that generator IS the
distributed exchange (split map tasks + reduce tasks; block data never
touches the driver), and the physical ``AllToAllOp`` drives it one output
block per scheduling step."""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.utils.logging import get_logger

logger = get_logger("data.executor")


class Stage:
    """A logical transformation of a stream of block refs."""

    name = "stage"

    def shuffle_spec(self):
        """The stage's ``ShuffleSpec`` (shuffle.spec) when it is an
        all-to-all exchange the streaming shuffle subsystem can drive; None
        compiles to the legacy ``AllToAllOp`` barrier (zip, keyless
        aggregate, non-exchange stages)."""
        return None


class MapStage(Stage):
    """Row/batch map (task pool, or actor pool when fn_constructor is set).
    Purely descriptive: execution lives in TaskPoolMapOp/ActorPoolMapOp."""

    def __init__(
        self,
        name: str,
        block_fn: Callable,  # Block -> Block (pickled to workers)
        num_cpus: float = 1.0,
        fn_constructor: Optional[Callable] = None,  # class-based: actor pool
        concurrency: Optional[int] = None,
    ):
        self.name = name
        self.block_fn = block_fn
        self.num_cpus = num_cpus
        self.fn_constructor = fn_constructor
        self.concurrency = concurrency


class LimitStage(Stage):
    """First-n-rows truncation; compiles to a LimitOp that short-circuits
    every upstream operator once satisfied."""

    def __init__(self, limit: int):
        self.name = f"limit({limit})"
        self.limit = limit


def _exchange(inputs: Iterator[ObjectRef], num_outputs: Optional[int],
              split_fn: Callable, reduce_fn: Callable) -> Iterator[ObjectRef]:
    """Two-phase map/reduce exchange (reference: planner/exchange/
    shuffle_task_scheduler): map tasks split every input block into
    num_outputs partitions (refs only — block DATA never touches the
    driver, so datasets larger than any one store spill instead of OOM);
    reduce tasks combine partition j of every map output. Yields reduce
    output refs as they finish."""
    input_refs = list(inputs)
    if not input_refs:
        return
    n_out = num_outputs or len(input_refs)

    split_remote = ray_tpu.remote(num_returns=n_out, name="data::exchange_split")(
        split_fn
    ) if n_out > 1 else None

    # map phase: one split task per input block -> n_out partition refs each
    partitions: List[List[ObjectRef]] = []
    for i, ref in enumerate(input_refs):
        if n_out == 1:
            partitions.append([ref])
        else:
            out = split_remote.remote(ref, n_out, i)
            partitions.append(list(out) if isinstance(out, (list, tuple)) else [out])

    reduce_remote = ray_tpu.remote(name="data::exchange_reduce")(reduce_fn)
    reduce_refs = [
        reduce_remote.remote(j, *[parts[j] for parts in partitions])
        for j in range(n_out)
    ]
    for ref in reduce_refs:
        ray_tpu.wait([ref], num_returns=1, timeout=None)
        yield ref


def _exchange_spec(spec, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
    """Barrier-mode exchange driven by a ``ShuffleSpec`` — the SAME
    partition functions the streaming ``ShuffleMapOp``/``ShuffleReduceOp``
    run, so ``RTPU_STREAMING_SHUFFLE=0`` changes scheduling, never data.
    Collects every input ref up front (the barrier), runs the optional plan
    phase (boundary samples / row counts), then split + reduce tasks."""
    input_refs = list(inputs)
    if not input_refs:
        return
    n_out = spec.resolve_partitions(len(input_refs))
    plan = None
    if spec.needs_plan:
        sample_remote = ray_tpu.remote(
            name=f"data::{spec.name}::sample")(spec.sample_fn)
        samples = ray_tpu.get(
            [sample_remote.remote(ref, i) for i, ref in enumerate(input_refs)])
        plan = spec.plan_fn(samples, n_out)

    map_fn = spec.map_fn

    def split(block, idx, plan_):
        return map_fn(block, n_out, idx, plan_)

    split_remote = ray_tpu.remote(
        num_returns=n_out, name=f"data::{spec.name}::map")(split)
    partitions: List[List[ObjectRef]] = []
    for i, ref in enumerate(input_refs):
        out = split_remote.remote(ref, i, plan)
        partitions.append(list(out) if isinstance(out, (list, tuple)) else [out])

    reduce_remote = ray_tpu.remote(name=f"data::{spec.name}::reduce")(
        spec.reduce_fn)
    reduce_refs = [
        reduce_remote.remote(j, *[parts[j] for parts in partitions])
        for j in range(n_out)
    ]
    for ref in reduce_refs:
        ray_tpu.wait([ref], num_returns=1, timeout=None)
        yield ref


class RepartitionStage(Stage):
    """Order-preserving repartition (reference: shuffle=False repartition —
    global row order is kept, so zip() after repartition stays aligned)."""

    def __init__(self, num_blocks: int):
        self.name = f"repartition({num_blocks})"
        self.num_blocks = num_blocks

    def shuffle_spec(self):
        from ray_tpu.data.shuffle.spec import repartition_spec

        return repartition_spec(self.num_blocks)

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        yield from _exchange_spec(self.shuffle_spec(), inputs)


class ShuffleStage(Stage):
    """Distributed all-to-all random shuffle: rows scatter to random output
    partitions in map tasks, reduce tasks permute within their partition.
    No driver-side materialization (reference: planner/exchange/). Map RNGs
    are derived from the BLOCK INDEX (shuffle.spec.derive_rng), never
    dispatch order, so a seeded shuffle is deterministic even when maps
    complete out of order."""

    def __init__(self, seed: Optional[int] = None):
        self.name = "random_shuffle"
        self.seed = seed

    def shuffle_spec(self):
        from ray_tpu.data.shuffle.spec import random_shuffle_spec

        return random_shuffle_spec(self.seed)

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        yield from _exchange_spec(self.shuffle_spec(), inputs)


class SortStage(Stage):
    """Distributed range-partition sort (reference: planner/exchange/
    sort_task_spec.py SortTaskSpec — sample boundaries, range-split map
    tasks, sorted-merge reduce tasks)."""

    def __init__(self, key: str, descending: bool = False,
                 num_blocks: Optional[int] = None):
        self.name = f"sort({key})"
        self.key = key
        self.descending = descending
        self.num_blocks = num_blocks

    def shuffle_spec(self):
        from ray_tpu.data.shuffle.spec import sort_spec

        return sort_spec(self.key, self.descending, self.num_blocks)

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        yield from _exchange_spec(self.shuffle_spec(), inputs)


class AggregateStage(Stage):
    """Hash-partition groupby + aggregate (reference: planner/exchange/
    aggregate_task_spec.py): map tasks pre-combine per-group partials
    (vectorized pyarrow group_by), reduce tasks merge partials and finalize.
    With no keys, a single global-aggregate output block."""

    def __init__(self, keys: List[str], aggs: List[Any],
                 num_blocks: Optional[int] = None):
        names = ",".join(a.name for a in aggs)
        self.name = f"aggregate({','.join(keys) or '-'}:{names})"
        self.keys = keys
        self.aggs = aggs
        self.num_blocks = num_blocks

    def shuffle_spec(self):
        from ray_tpu.data.shuffle.spec import aggregate_spec

        # keyless (global) aggregation returns None: a single-output
        # barrier combine is already optimal, no exchange to stream
        return aggregate_spec(self.keys, self.aggs, self.num_blocks)

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        spec = self.shuffle_spec()
        if spec is not None:
            yield from _exchange_spec(spec, inputs)
            return
        keys, aggs = self.keys, self.aggs
        input_refs = list(inputs)
        if not input_refs:
            return

        def split(block, n, _idx=0):
            return block  # n_out == 1: _exchange skips the split phase

        def reduce(_j, *parts):
            from ray_tpu.data.aggregate import make_partial, merge_partials

            # parts are RAW blocks (no split phase ran): combine them here
            expected = {c for a in aggs for c, _ in a.merge_aggs()}
            norm = [p if expected.issubset(set(p.column_names))
                    else make_partial(p, keys, aggs) for p in parts]
            return merge_partials(norm, keys, aggs)

        yield from _exchange(iter(input_refs), 1, split, reduce)


def _stable_hash_partition(table, keys: List[str], n: int):
    """Partition assignment stable ACROSS processes (python's str hash is
    per-process salted; numpy splitmix for ints, crc32 for anything else)."""
    import zlib

    import numpy as np

    h = np.zeros(table.num_rows, dtype=np.uint64)
    for k in keys:
        col = table.column(k)
        try:
            vals = col.to_numpy(zero_copy_only=False)
        except Exception:  # noqa: BLE001
            vals = np.array(col.to_pylist(), dtype=object)
        if np.issubdtype(vals.dtype, np.integer):
            x = vals.astype(np.uint64)
            x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
            x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
            h ^= x ^ (x >> np.uint64(31))
        else:
            h ^= np.array(
                [zlib.crc32(str(v).encode()) for v in vals], dtype=np.uint64
            )
    return (h % np.uint64(n)).astype(np.int64)


class ZipStage(Stage):
    """Column-zip with another dataset's block stream (reference:
    dataset.py Dataset.zip — aligns differing block boundaries, combines
    columns; right-side name collisions get a _1 suffix)."""

    def __init__(self, other_source: Callable[[], Iterator[ObjectRef]]):
        self.name = "zip"
        self.other_source = other_source

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        left = list(inputs)
        right = list(self.other_source())

        @ray_tpu.remote(name="data::zip_rows")
        def count_rows(block):
            return block.num_rows

        l_counts = ray_tpu.get([count_rows.remote(r) for r in left])
        r_counts = ray_tpu.get([count_rows.remote(r) for r in right])
        if sum(l_counts) != sum(r_counts):
            raise ValueError(
                f"zip(): datasets have different row counts "
                f"({sum(l_counts)} vs {sum(r_counts)})"
            )

        # aligned segments: union of both sides' cumulative boundaries
        def cum(counts):
            out, acc = [], 0
            for c in counts:
                acc += c
                out.append(acc)
            return out

        bounds = sorted(set(cum(l_counts)) | set(cum(r_counts)))

        @ray_tpu.remote(name="data::zip_slice")
        def zip_slice(lblock, loff, rblock, roff, length):
            import pyarrow as pa

            lpart = lblock.slice(loff, length)
            rpart = rblock.slice(roff, length)
            cols = {name: lpart.column(name) for name in lpart.column_names}
            for name in rpart.column_names:
                out_name = name if name not in cols else f"{name}_1"
                cols[out_name] = rpart.column(name)
            return pa.table(cols)

        start = 0
        for end in bounds:
            length = end - start
            if length <= 0:
                continue
            li, loff = _locate(l_counts, start)
            ri, roff = _locate(r_counts, start)
            yield zip_slice.remote(left[li], loff, right[ri], roff, length)
            start = end


def _locate(counts: List[int], global_row: int):
    """(block index, offset within block) of a global row index."""
    acc = 0
    for i, c in enumerate(counts):
        if global_row < acc + c:
            return i, global_row - acc
        acc += c
    raise IndexError(global_row)
