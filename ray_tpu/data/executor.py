"""Logical stages of the Data plan + the distributed all-to-all exchanges.

Reference capability: python/ray/data/_internal/logical_ops + planner/
exchange/. A ``Stage`` is a LOGICAL description of a transformation;
``ray_tpu.data.execution.planner`` compiles stages into physical operators
and ``execution.streaming_executor.StreamingExecutor`` runs them with
per-operator budgets and backpressure. The old flat per-stage in-flight
window (``_iter_completed``) is gone — pacing decisions live in the
executor's scheduling loop now, not in each stage.

The all-to-all stages (repartition/shuffle/sort/aggregate/zip) keep their
``execute(inputs) -> Iterator[ObjectRef]`` methods: that generator IS the
distributed exchange (split map tasks + reduce tasks; block data never
touches the driver), and the physical ``AllToAllOp`` drives it one output
block per scheduling step."""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.utils.logging import get_logger

logger = get_logger("data.executor")


class Stage:
    """A logical transformation of a stream of block refs."""

    name = "stage"


class MapStage(Stage):
    """Row/batch map (task pool, or actor pool when fn_constructor is set).
    Purely descriptive: execution lives in TaskPoolMapOp/ActorPoolMapOp."""

    def __init__(
        self,
        name: str,
        block_fn: Callable,  # Block -> Block (pickled to workers)
        num_cpus: float = 1.0,
        fn_constructor: Optional[Callable] = None,  # class-based: actor pool
        concurrency: Optional[int] = None,
    ):
        self.name = name
        self.block_fn = block_fn
        self.num_cpus = num_cpus
        self.fn_constructor = fn_constructor
        self.concurrency = concurrency


class LimitStage(Stage):
    """First-n-rows truncation; compiles to a LimitOp that short-circuits
    every upstream operator once satisfied."""

    def __init__(self, limit: int):
        self.name = f"limit({limit})"
        self.limit = limit


def _exchange(inputs: Iterator[ObjectRef], num_outputs: Optional[int],
              split_fn: Callable, reduce_fn: Callable) -> Iterator[ObjectRef]:
    """Two-phase map/reduce exchange (reference: planner/exchange/
    shuffle_task_scheduler): map tasks split every input block into
    num_outputs partitions (refs only — block DATA never touches the
    driver, so datasets larger than any one store spill instead of OOM);
    reduce tasks combine partition j of every map output. Yields reduce
    output refs as they finish."""
    input_refs = list(inputs)
    if not input_refs:
        return
    n_out = num_outputs or len(input_refs)

    split_remote = ray_tpu.remote(num_returns=n_out, name="data::exchange_split")(
        split_fn
    ) if n_out > 1 else None

    # map phase: one split task per input block -> n_out partition refs each
    partitions: List[List[ObjectRef]] = []
    for i, ref in enumerate(input_refs):
        if n_out == 1:
            partitions.append([ref])
        else:
            out = split_remote.remote(ref, n_out, i)
            partitions.append(list(out) if isinstance(out, (list, tuple)) else [out])

    reduce_remote = ray_tpu.remote(name="data::exchange_reduce")(reduce_fn)
    reduce_refs = [
        reduce_remote.remote(j, *[parts[j] for parts in partitions])
        for j in range(n_out)
    ]
    for ref in reduce_refs:
        ray_tpu.wait([ref], num_returns=1, timeout=None)
        yield ref


class RepartitionStage(Stage):
    """Order-preserving repartition (reference: shuffle=False repartition —
    global row order is kept, so zip() after repartition stays aligned)."""

    def __init__(self, num_blocks: int):
        self.name = f"repartition({num_blocks})"
        self.num_blocks = num_blocks

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        input_refs = list(inputs)
        if not input_refs:
            return
        n = self.num_blocks

        @ray_tpu.remote(name="data::repartition_rows")
        def count_rows(block):
            return block.num_rows

        counts = ray_tpu.get([count_rows.remote(r) for r in input_refs])
        total = sum(counts)
        per, rem = divmod(total, n)
        # global output boundaries: output j covers rows [out_start[j], out_end[j])
        out_sizes = [per + (1 if j < rem else 0) for j in range(n)]
        out_bounds = []
        acc = 0
        for s in out_sizes:
            out_bounds.append((acc, acc + s))
            acc += s
        # per-input-block slice plan: block i (global offset g) contributes
        # its overlap with each output range, preserving order
        offsets = []
        g = 0
        for c in counts:
            offsets.append(g)
            g += c
        plans = []
        for i, c in enumerate(counts):
            g0, g1 = offsets[i], offsets[i] + c
            plan = []
            for j, (o0, o1) in enumerate(out_bounds):
                lo, hi = max(g0, o0), min(g1, o1)
                plan.append((lo - g0, max(lo, hi) - g0) if hi > lo else (0, 0))
            plans.append(plan)

        def split(block, n_, idx=0):
            from ray_tpu.data.block import BlockAccessor

            acc_ = BlockAccessor(block)
            outs = [acc_.slice(s, e) for (s, e) in plans[idx]]
            return tuple(outs) if n_ > 1 else outs[0]

        def reduce(_j, *parts):
            from ray_tpu.data.block import concat_blocks

            nonempty = [p for p in parts if p.num_rows]
            if not nonempty and parts:
                # an output partition with no rows must still carry the
                # schema: a column-less block breaks downstream column refs
                return parts[0].slice(0, 0)
            return concat_blocks(nonempty)

        yield from _exchange(iter(input_refs), n, split, reduce)


class ShuffleStage(Stage):
    """Distributed all-to-all random shuffle: rows scatter to random output
    partitions in map tasks, reduce tasks permute within their partition.
    No driver-side materialization (reference: planner/exchange/)."""

    def __init__(self, seed: Optional[int] = None):
        self.name = "random_shuffle"
        self.seed = seed

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        seed = self.seed

        def split(block, n, idx=0):
            import numpy as np

            rng = np.random.default_rng(None if seed is None else seed + idx)
            assign = rng.integers(0, n, block.num_rows)
            outs = tuple(block.take(np.nonzero(assign == j)[0]) for j in range(n))
            return outs if n > 1 else outs[0]

        def reduce(j, *parts):
            import numpy as np

            from ray_tpu.data.block import concat_blocks

            combined = concat_blocks(list(parts))
            rng = np.random.default_rng(None if seed is None else seed + 10_000 + j)
            return combined.take(rng.permutation(combined.num_rows))

        yield from _exchange(inputs, None, split, reduce)


class SortStage(Stage):
    """Distributed range-partition sort (reference: planner/exchange/
    sort_task_spec.py SortTaskSpec — sample boundaries, range-split map
    tasks, sorted-merge reduce tasks)."""

    def __init__(self, key: str, descending: bool = False,
                 num_blocks: Optional[int] = None):
        self.name = f"sort({key})"
        self.key = key
        self.descending = descending
        self.num_blocks = num_blocks

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        key, descending = self.key, self.descending
        input_refs = list(inputs)
        if not input_refs:
            return
        n_out = self.num_blocks or len(input_refs)

        # 1. sample boundary candidates from every block (SortTaskSpec.
        # sample_boundaries equivalent)
        @ray_tpu.remote(name="data::sort_sample")
        def sample(block):
            import numpy as np

            col = block.column(key).to_numpy(zero_copy_only=False)
            if len(col) == 0:
                return np.array([])
            k = min(64, len(col))
            idx = np.random.default_rng(0).choice(len(col), size=k, replace=False)
            return col[idx]

        samples = ray_tpu.get([sample.remote(r) for r in input_refs])
        import numpy as np

        flat = np.concatenate([s for s in samples if len(s)]) if any(
            len(s) for s in samples) else np.array([0.0])
        flat.sort()
        # n_out-1 boundaries at even quantiles
        bounds = flat[np.linspace(0, len(flat) - 1, n_out + 1)[1:-1].astype(int)] \
            if n_out > 1 else np.array([])

        def split(block, n, _idx=0):
            import numpy as np

            col = block.column(key).to_numpy(zero_copy_only=False)
            assign = np.searchsorted(bounds, col, side="right")
            if descending:
                assign = (n - 1) - assign
            outs = tuple(block.take(np.nonzero(assign == j)[0]) for j in range(n))
            return outs if n > 1 else outs[0]

        def reduce(_j, *parts):
            import pyarrow.compute as pc

            from ray_tpu.data.block import concat_blocks

            combined = concat_blocks(list(parts))
            order = "descending" if descending else "ascending"
            return combined.take(pc.sort_indices(combined, sort_keys=[(key, order)]))

        yield from _exchange(iter(input_refs), n_out, split, reduce)


class AggregateStage(Stage):
    """Hash-partition groupby + aggregate (reference: planner/exchange/
    aggregate_task_spec.py): map tasks pre-combine per-group partials
    (vectorized pyarrow group_by), reduce tasks merge partials and finalize.
    With no keys, a single global-aggregate output block."""

    def __init__(self, keys: List[str], aggs: List[Any],
                 num_blocks: Optional[int] = None):
        names = ",".join(a.name for a in aggs)
        self.name = f"aggregate({','.join(keys) or '-'}:{names})"
        self.keys = keys
        self.aggs = aggs
        self.num_blocks = num_blocks

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        keys, aggs = self.keys, self.aggs
        input_refs = list(inputs)
        if not input_refs:
            return
        n_out = 1 if not keys else (self.num_blocks or min(len(input_refs), 8))

        def split(block, n, _idx=0):
            import numpy as np

            from ray_tpu.data.aggregate import make_partial
            from ray_tpu.data.block import BlockAccessor  # noqa: F401

            partial = make_partial(block, keys, aggs)
            if n == 1:
                return partial
            assign = _stable_hash_partition(partial, keys, n)
            return tuple(partial.take(np.nonzero(assign == j)[0]) for j in range(n))

        def reduce(_j, *parts):
            from ray_tpu.data.aggregate import make_partial, merge_partials

            # n_out==1 skips the split phase entirely (_exchange fast path):
            # parts are then RAW blocks — combine them here
            expected = {c for a in aggs for c, _ in a.merge_aggs()}
            norm = [p if expected.issubset(set(p.column_names))
                    else make_partial(p, keys, aggs) for p in parts]
            return merge_partials(norm, keys, aggs)

        yield from _exchange(iter(input_refs), n_out, split, reduce)


def _stable_hash_partition(table, keys: List[str], n: int):
    """Partition assignment stable ACROSS processes (python's str hash is
    per-process salted; numpy splitmix for ints, crc32 for anything else)."""
    import zlib

    import numpy as np

    h = np.zeros(table.num_rows, dtype=np.uint64)
    for k in keys:
        col = table.column(k)
        try:
            vals = col.to_numpy(zero_copy_only=False)
        except Exception:  # noqa: BLE001
            vals = np.array(col.to_pylist(), dtype=object)
        if np.issubdtype(vals.dtype, np.integer):
            x = vals.astype(np.uint64)
            x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
            x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
            h ^= x ^ (x >> np.uint64(31))
        else:
            h ^= np.array(
                [zlib.crc32(str(v).encode()) for v in vals], dtype=np.uint64
            )
    return (h % np.uint64(n)).astype(np.int64)


class ZipStage(Stage):
    """Column-zip with another dataset's block stream (reference:
    dataset.py Dataset.zip — aligns differing block boundaries, combines
    columns; right-side name collisions get a _1 suffix)."""

    def __init__(self, other_source: Callable[[], Iterator[ObjectRef]]):
        self.name = "zip"
        self.other_source = other_source

    def execute(self, inputs: Iterator[ObjectRef]) -> Iterator[ObjectRef]:
        left = list(inputs)
        right = list(self.other_source())

        @ray_tpu.remote(name="data::zip_rows")
        def count_rows(block):
            return block.num_rows

        l_counts = ray_tpu.get([count_rows.remote(r) for r in left])
        r_counts = ray_tpu.get([count_rows.remote(r) for r in right])
        if sum(l_counts) != sum(r_counts):
            raise ValueError(
                f"zip(): datasets have different row counts "
                f"({sum(l_counts)} vs {sum(r_counts)})"
            )

        # aligned segments: union of both sides' cumulative boundaries
        def cum(counts):
            out, acc = [], 0
            for c in counts:
                acc += c
                out.append(acc)
            return out

        bounds = sorted(set(cum(l_counts)) | set(cum(r_counts)))

        @ray_tpu.remote(name="data::zip_slice")
        def zip_slice(lblock, loff, rblock, roff, length):
            import pyarrow as pa

            lpart = lblock.slice(loff, length)
            rpart = rblock.slice(roff, length)
            cols = {name: lpart.column(name) for name in lpart.column_names}
            for name in rpart.column_names:
                out_name = name if name not in cols else f"{name}_1"
                cols[out_name] = rpart.column(name)
            return pa.table(cols)

        start = 0
        for end in bounds:
            length = end - start
            if length <= 0:
                continue
            li, loff = _locate(l_counts, start)
            ri, roff = _locate(r_counts, start)
            yield zip_slice.remote(left[li], loff, right[ri], roff, length)
            start = end


def _locate(counts: List[int], global_row: int):
    """(block index, offset within block) of a global row index."""
    acc = 0
    for i, c in enumerate(counts):
        if global_row < acc + c:
            return i, global_row - acc
        acc += c
    raise IndexError(global_row)
