"""Blocks: the unit of data movement (reference: python/ray/data/block.py —
Block = arrow table / pandas df; BlockAccessor for format-generic ops).

Canonical in-memory format is a pyarrow.Table; batches surface as
dict-of-numpy ("numpy", the TPU-friendly default), arrow, or pandas.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

Block = pa.Table
Batch = Union[Dict[str, np.ndarray], pa.Table, "pd.DataFrame"]  # noqa: F821


class _PyObjType(pa.ExtensionType):
    """Arbitrary-python-object column: per-row cloudpickle over binary
    storage (reference analogue: ArrowPythonObjectArray extension in
    python/ray/air/util/object_extensions). Carries ragged tensors, mixed
    types, and anything arrow has no native layout for."""

    def __init__(self) -> None:
        super().__init__(pa.binary(), "ray_tpu.pyobj")

    def __arrow_ext_serialize__(self) -> bytes:
        return b""

    @classmethod
    def __arrow_ext_deserialize__(cls, storage_type, serialized):
        return cls()


_PYOBJ_TYPE = _PyObjType()
try:
    pa.register_extension_type(_PYOBJ_TYPE)
except pa.ArrowKeyError:  # re-import (e.g. tests reloading the module)
    pass


def _pyobj_column(values: Any) -> pa.Array:
    import cloudpickle

    storage = pa.array([cloudpickle.dumps(v) for v in values], pa.binary())
    return pa.ExtensionArray.from_storage(_PYOBJ_TYPE, storage)


def _normalize_column(values: Any) -> pa.Array:
    if isinstance(values, pa.Array):
        return values
    try:
        arr = np.asarray(values)
    except ValueError:  # ragged tensors: per-row shapes differ
        return _pyobj_column(values)
    if arr.ndim > 1:
        if arr.dtype == object:
            return _pyobj_column(values)
        # tensor column: shape-preserving canonical arrow extension
        return pa.FixedShapeTensorArray.from_numpy_ndarray(
            np.ascontiguousarray(arr))
    if arr.dtype == object:
        try:
            return pa.array(values)  # str/bytes/None/uniform dicts
        except (pa.ArrowInvalid, pa.ArrowTypeError, pa.ArrowNotImplementedError):
            return _pyobj_column(values)
    return pa.array(arr)


def block_from_batch(batch: Batch) -> Block:
    if isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, dict):
        return pa.table({k: _normalize_column(v) for k, v in batch.items()})
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
    except ImportError:
        pass
    raise TypeError(f"cannot convert {type(batch).__name__} to a block")


def block_from_rows(rows: List[Any], object_columns: Optional[set] = None) -> Block:
    """``object_columns``: column names forced to the pyobj layout even when
    this block's values happen to be uniform — readers whose per-row shapes
    vary GLOBALLY (e.g. native-shape images) must not let a coincidentally-
    uniform block become a tensor column, or blocks get incompatible schemas
    and concat/iter_batches fails."""
    if rows and isinstance(rows[0], dict):
        cols: Dict[str, list] = {}
        for r in rows:
            for k, v in r.items():
                cols.setdefault(k, []).append(v)
        return pa.table({
            k: (_pyobj_column(v) if object_columns and k in object_columns
                else _normalize_column(v))
            for k, v in cols.items()
        })
    return pa.table({"item": _normalize_column(rows)})


class BlockAccessor:
    def __init__(self, block: Block):
        self.block = block

    @classmethod
    def for_block(cls, block: Block) -> "BlockAccessor":
        return cls(block)

    def num_rows(self) -> int:
        return self.block.num_rows

    def size_bytes(self) -> int:
        return self.block.nbytes

    def schema(self) -> pa.Schema:
        return self.block.schema

    def slice(self, start: int, end: int) -> Block:
        return self.block.slice(start, end - start)

    def to_arrow(self) -> pa.Table:
        return self.block

    def to_numpy(self) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        for name in self.block.column_names:
            out[name] = _column_to_numpy(self.block.column(name))
        return out

    def to_pandas(self):
        return self.block.to_pandas()

    def to_batch(self, batch_format: str) -> Batch:
        if batch_format in ("numpy", "default"):
            return self.to_numpy()
        if batch_format in ("pyarrow", "arrow"):
            return self.block
        if batch_format == "pandas":
            return self.to_pandas()
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        names = self.block.column_names
        # tensor columns: one bulk decode wins; pyobj columns: decode rows
        # LAZILY so take(1) doesn't unpickle a whole block
        tensors, pyobj = {}, {}
        for name in names:
            col = self.block.column(name)
            if isinstance(col.type, _PyObjType):
                storage = (col.combine_chunks()
                           if isinstance(col, pa.ChunkedArray) else col).storage
                pyobj[name] = storage
            elif _is_special_type(col.type):
                tensors[name] = _column_to_numpy(col)
        import cloudpickle

        def cell(name: str, i: int) -> Any:
            if name in tensors:
                return tensors[name][i]
            if name in pyobj:
                return cloudpickle.loads(pyobj[name][i].as_py())
            return self.block.column(name)[i].as_py()

        for i in range(self.block.num_rows):
            yield {name: cell(name, i) for name in names}


def _is_special_type(t: pa.DataType) -> bool:
    return isinstance(t, (pa.FixedShapeTensorType, _PyObjType)) or (
        pa.types.is_fixed_size_list(t)
    )


def _column_to_numpy(col) -> np.ndarray:
    """ChunkedArray/Array -> numpy, decoding tensor + pyobj extensions."""
    t = col.type
    if isinstance(t, pa.FixedShapeTensorType):
        combined = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
        if isinstance(combined, pa.ChunkedArray):  # empty table edge
            return np.zeros((0,) + tuple(t.shape))
        return combined.to_numpy_ndarray()
    if isinstance(t, _PyObjType):
        import cloudpickle

        storage = (col.combine_chunks() if isinstance(col, pa.ChunkedArray)
                   else col).storage
        out = np.empty(len(storage), dtype=object)
        for i, v in enumerate(storage):
            out[i] = cloudpickle.loads(v.as_py())
        return out
    if pa.types.is_fixed_size_list(t):  # legacy flat-tensor layout
        combined = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
        if isinstance(combined, pa.ChunkedArray):
            combined = combined.chunk(0)
        values = combined.values.to_numpy(zero_copy_only=False)
        return values.reshape(len(col), -1)
    return col.to_numpy(zero_copy_only=False)


def concat_blocks(blocks: List[Block],
                  schema: Optional[pa.Schema] = None) -> Block:
    """Concat, keeping a usable schema for the empty case: a schema-less
    ``pa.table({})`` breaks downstream schema checks (iter_batches column
    refs, zip alignment), so callers that know the exchange's schema thread
    it through here."""
    if not blocks:
        return schema.empty_table() if schema is not None else pa.table({})
    return pa.concat_tables(blocks)


# ------------------------------------------------------------- block formats
# Column-format classification for the columnar exchange: "fast" layouts
# (fixed-width primitives, FixedShapeTensor, fixed-size lists) reconstruct
# from IPC bytes as zero-copy views and have vectorized partition/sort
# kernels; everything else (pyobj extension, variable-width strings/binary,
# nested types) takes the row-object fallback and pays a copy/decode.
def is_fast_format(t: pa.DataType) -> bool:
    if isinstance(t, _PyObjType):
        return False
    if isinstance(t, pa.FixedShapeTensorType) or pa.types.is_fixed_size_list(t):
        return True
    return (pa.types.is_integer(t) or pa.types.is_floating(t)
            or pa.types.is_boolean(t) or pa.types.is_temporal(t)
            or pa.types.is_decimal(t))


def classify_table_bytes(table: Block) -> tuple:
    """(fast_bytes, fallback_bytes) over the table's columns — the split
    the exchange stats report as zero-copy vs copied bytes."""
    fast = fallback = 0
    for col in table.columns:
        if is_fast_format(col.type):
            fast += col.nbytes
        else:
            fallback += col.nbytes
    return fast, fallback


def sort_key_array(block: Block, key: str) -> Optional[np.ndarray]:
    """The key column as a numpy array the vectorized sort kernels can
    order with plain comparisons, or None when the column must take the
    pyarrow fallback: non-fast layout, nulls (to_numpy would widen to
    NaN), or float NaNs (comparison-based merge would misplace them
    relative to pc.sort_indices' nulls-last ordering)."""
    col = block.column(key)
    if not is_fast_format(col.type) or isinstance(
            col.type, pa.FixedShapeTensorType) or pa.types.is_fixed_size_list(
            col.type):
        return None
    if col.null_count:
        return None
    arr = col.to_numpy(zero_copy_only=False)
    if arr.dtype == object:
        return None
    if np.issubdtype(arr.dtype, np.floating) and np.isnan(arr).any():
        return None
    return arr
