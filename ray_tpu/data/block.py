"""Blocks: the unit of data movement (reference: python/ray/data/block.py —
Block = arrow table / pandas df; BlockAccessor for format-generic ops).

Canonical in-memory format is a pyarrow.Table; batches surface as
dict-of-numpy ("numpy", the TPU-friendly default), arrow, or pandas.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

Block = pa.Table
Batch = Union[Dict[str, np.ndarray], pa.Table, "pd.DataFrame"]  # noqa: F821


def _normalize_column(values: Any) -> pa.Array:
    if isinstance(values, pa.Array):
        return values
    arr = np.asarray(values)
    if arr.ndim > 1:
        # tensor column: fixed-size lists
        flat = arr.reshape(len(arr), -1)
        return pa.FixedSizeListArray.from_arrays(
            pa.array(flat.ravel()), flat.shape[1]
        )
    return pa.array(arr)


def block_from_batch(batch: Batch) -> Block:
    if isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, dict):
        return pa.table({k: _normalize_column(v) for k, v in batch.items()})
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
    except ImportError:
        pass
    raise TypeError(f"cannot convert {type(batch).__name__} to a block")


def block_from_rows(rows: List[Any]) -> Block:
    if rows and isinstance(rows[0], dict):
        cols: Dict[str, list] = {}
        for r in rows:
            for k, v in r.items():
                cols.setdefault(k, []).append(v)
        return pa.table({k: _normalize_column(v) for k, v in cols.items()})
    return pa.table({"item": _normalize_column(rows)})


class BlockAccessor:
    def __init__(self, block: Block):
        self.block = block

    @classmethod
    def for_block(cls, block: Block) -> "BlockAccessor":
        return cls(block)

    def num_rows(self) -> int:
        return self.block.num_rows

    def size_bytes(self) -> int:
        return self.block.nbytes

    def schema(self) -> pa.Schema:
        return self.block.schema

    def slice(self, start: int, end: int) -> Block:
        return self.block.slice(start, end - start)

    def to_arrow(self) -> pa.Table:
        return self.block

    def to_numpy(self) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        for name in self.block.column_names:
            col = self.block.column(name)
            if pa.types.is_fixed_size_list(col.type):
                combined = col.combine_chunks()
                if isinstance(combined, pa.ChunkedArray):
                    combined = combined.chunk(0)
                values = combined.values.to_numpy(zero_copy_only=False)
                out[name] = values.reshape(len(col), -1)
            else:
                out[name] = col.to_numpy(zero_copy_only=False)
        return out

    def to_pandas(self):
        return self.block.to_pandas()

    def to_batch(self, batch_format: str) -> Batch:
        if batch_format in ("numpy", "default"):
            return self.to_numpy()
        if batch_format in ("pyarrow", "arrow"):
            return self.block
        if batch_format == "pandas":
            return self.to_pandas()
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for i in range(self.block.num_rows):
            yield {name: self.block.column(name)[i].as_py() for name in self.block.column_names}


def concat_blocks(blocks: List[Block]) -> Block:
    if not blocks:
        return pa.table({})
    return pa.concat_tables(blocks)
