"""Physical operators of the streaming Data executor.

Reference counterparts (python/ray/data/_internal/execution/operators/):

- ``InputDataOp``      -> input_data_buffer.py InputDataBuffer (+ the read
                          half of plan_read_op.py: paced read-task submission)
- ``TaskPoolMapOp``    -> map_operator.py TaskPoolMapOperator
- ``ActorPoolMapOp``   -> actor_pool_map_operator.py ActorPoolMapOperator
- ``AllToAllOp``       -> all_to_all_operator.py AllToAllOperator (barrier +
                          bulk exchange: repartition/shuffle/sort/agg/zip)
- ``LimitOp``          -> limit_operator.py LimitOperator (+ upstream
                          short-circuit via the executor)
- ``OutputSplitOp``    -> output_splitter.py OutputSplitter (streaming_split)

Map-family operators preserve submission order: completions are harvested
out of order but emitted head-of-line, so ``take()`` and zip alignment see
deterministic row order while stragglers still overlap."""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Deque, Dict, Iterator, List, Optional

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.data.execution.interfaces import (
    ExecutionContext,
    PhysicalOperator,
    ReadTaskSource,
    RefBundle,
)


class _InFlight:
    __slots__ = ("ref", "submitted_at", "done", "size_bytes")

    def __init__(self, ref: ObjectRef, submitted_at: float):
        self.ref = ref
        self.submitted_at = submitted_at
        self.done = False
        self.size_bytes: Optional[int] = None


class _OrderedTaskMixin(PhysicalOperator):
    """Shared harvest machinery: poll in-flight refs, emit in order."""

    def __init__(self, name: str):
        super().__init__(name)
        self._pending: Deque[_InFlight] = deque()
        self._by_ref: Dict[ObjectRef, _InFlight] = {}

    def _track(self, ref: ObjectRef) -> None:
        t = _InFlight(ref, self.stats.on_task_submitted())
        self._pending.append(t)
        self._by_ref[ref] = t

    def active_refs(self) -> List[ObjectRef]:
        return list(self._by_ref)

    def num_active_tasks(self) -> int:
        # tracked-but-not-yet-emitted counts against the concurrency cap:
        # ordered emission means a straggling head-of-line task must pause
        # dispatches, not let completed outputs pile up behind it unbounded
        return len(self._pending)

    def _on_task_done(self, t: _InFlight, ctx: ExecutionContext) -> None:
        """Hook for subclasses (actor pools return the actor here)."""

    def process_completions(self, ctx: ExecutionContext,
                            ready: Optional[List[ObjectRef]] = None) -> bool:
        """``ready``: completed refs the EXECUTOR already discovered with its
        one wait() per tick (in cluster mode every wait is a control RPC, and
        a zero-timeout wait only sees the driver node's store — per-op
        zero-timeout polling would never observe remote completions)."""
        if ready is None:
            ready = []
            if self._by_ref:
                refs = list(self._by_ref)
                ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                        timeout=0.05)
        else:
            ready = [r for r in ready if r in self._by_ref]
        if ready:
            sizes = ctx.probe_sizes(ready)
            for ref, size in zip(ready, sizes):
                t = self._by_ref.pop(ref)
                t.done = True
                t.size_bytes = size
                self.stats.on_task_finished(t.submitted_at)
                self._on_task_done(t, ctx)
        produced = False
        # head-of-line ordered emission
        while self._pending and self._pending[0].done:
            t = self._pending.popleft()
            if not self._finished:
                self._emit(RefBundle(t.ref, size_bytes=t.size_bytes), ctx)
                produced = True
        return produced or bool(ready)


class InputDataOp(_OrderedTaskMixin):
    """Source operator. Two shapes:

    - a ``ReadTaskSource``: each thunk becomes one remote read task; the
      scheduling loop paces submission (concurrency cap + memory budget),
      so parallelism of the READ phase is an executor decision, not a
      datasource loop;
    - a driver-side ref iterator (materialized datasets, unions, nested
      executions): each dispatch pulls one ref and emits it directly.
    """

    num_cpus = 1.0

    def __init__(self, source: Any, name: Optional[str] = None):
        self._read_source: Optional[ReadTaskSource] = None
        self._ref_iter: Optional[Iterator[ObjectRef]] = None
        self._source = source
        if isinstance(source, ReadTaskSource):
            self._read_source = source
            label = f"input::read_{source.name}[{len(source)}]"
        else:
            label = name or "input"
        super().__init__(label)
        self._next_idx = 0
        self._iter_exhausted = False
        self._read_remote = None
        from ray_tpu.core.config import config

        self.concurrency_cap = config.data_default_op_concurrency
        self.inputs_complete()  # sources have no upstream

    def start(self, ctx: ExecutionContext) -> None:
        if self._read_source is not None:
            tasks = self._read_source.make_tasks

            @ray_tpu.remote(num_cpus=1,
                            name=f"data::read_{self._read_source.name}")
            def read_one(idx: int):
                return tasks[idx]()

            self._read_remote = read_one
        else:
            self._ref_iter = iter(self._source())
            self.concurrency_cap = None  # driver-side pull, not a task pool

    def can_dispatch(self) -> bool:
        if self._finished:
            return False
        if self._read_source is not None:
            return self._next_idx < len(self._read_source)
        return not self._iter_exhausted

    def dispatch(self, ctx: ExecutionContext) -> None:
        if self._read_source is not None:
            self._track(self._read_remote.remote(self._next_idx))
            self._next_idx += 1
            return
        try:
            ref = next(self._ref_iter)
        except StopIteration:
            self._iter_exhausted = True
            return
        size = ctx.probe_sizes([ref])[0]
        self._emit(RefBundle(ref, size_bytes=size), ctx)

    def completed(self) -> bool:
        if self._finished:
            return True
        if self._read_source is not None:
            return (self._next_idx >= len(self._read_source)
                    and not self._by_ref and not self._pending)
        return self._iter_exhausted


class TaskPoolMapOp(_OrderedTaskMixin):
    """One remote task per input block over the shared task pool."""

    def __init__(self, name: str, block_fn: Callable, num_cpus: float = 1.0,
                 concurrency: Optional[int] = None):
        super().__init__(name)
        self.block_fn = block_fn
        self.num_cpus = num_cpus
        from ray_tpu.core.config import config

        self.concurrency_cap = concurrency or config.data_default_op_concurrency
        self._remote = None

    def start(self, ctx: ExecutionContext) -> None:
        block_fn = self.block_fn

        @ray_tpu.remote(num_cpus=self.num_cpus, name=f"data::{self.name}")
        def apply(block):
            return block_fn(block)

        self._remote = apply

    def dispatch(self, ctx: ExecutionContext) -> None:
        bundle = self.input_queue.popleft()
        self._track(self._remote.remote(bundle.ref))


class ActorPoolMapOp(_OrderedTaskMixin):
    """Stateful transform over a fixed pool of actors (class-based
    map_batches: the callable is constructed once per actor and reused)."""

    def __init__(self, name: str, block_fn: Callable,
                 fn_constructor: Callable, concurrency: Optional[int] = None,
                 num_cpus: float = 1.0):
        super().__init__(name)
        self.block_fn = block_fn
        self.fn_constructor = fn_constructor
        self.num_cpus = num_cpus
        self.pool_size = max(1, concurrency or 2)
        self.concurrency_cap = self.pool_size
        self._actors: List[Any] = []
        self._idle: Deque[Any] = deque()
        self._actor_of: Dict[ObjectRef, Any] = {}

    def start(self, ctx: ExecutionContext) -> None:
        ctor = self.fn_constructor
        block_fn = self.block_fn

        @ray_tpu.remote(num_cpus=self.num_cpus)
        class _MapWorker:
            def __init__(self):
                self.fn = ctor()

            def apply(self, block):
                return block_fn(block, self.fn)

        self._actors = [_MapWorker.remote() for _ in range(self.pool_size)]
        self._idle = deque(self._actors)

    def can_dispatch(self) -> bool:
        return bool(self.input_queue) and bool(self._idle)

    def dispatch(self, ctx: ExecutionContext) -> None:
        bundle = self.input_queue.popleft()
        actor = self._idle.popleft()
        ref = actor.apply.remote(bundle.ref)
        self._actor_of[ref] = actor
        self._track(ref)

    def _on_task_done(self, t: _InFlight, ctx: ExecutionContext) -> None:
        actor = self._actor_of.pop(t.ref, None)
        if actor is not None:
            self._idle.append(actor)

    def shutdown(self) -> None:
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass
        self._actors = []
        self._idle.clear()


class AllToAllOp(PhysicalOperator):
    """Barrier + bulk exchange. Accumulates every input ref, then drives a
    bulk transform (the distributed map/reduce exchanges in
    ``data/executor.py``) one output block per dispatch — the scheduling
    loop stays in control, so downstream backpressure still throttles how
    fast reduce outputs materialize."""

    num_cpus = 0.0

    def __init__(self, name: str, bulk_fn: Callable[[Iterator[ObjectRef]],
                                                    Iterator[ObjectRef]]):
        super().__init__(name)
        self.bulk_fn = bulk_fn
        # no concurrency cap (the bulk generator owns its own task fan-out),
        # but exchange outputs still count against the memory budget — the
        # barrier exchange must not bypass the accounting that throttles
        # every other operator
        self.budget_participates = True
        self._collected: List[ObjectRef] = []
        self._gen: Optional[Iterator[ObjectRef]] = None
        self._gen_done = False

    def can_dispatch(self) -> bool:
        if self._finished or self._gen_done:
            return False
        # barrier: the exchange needs every input block (sort samples all
        # blocks, shuffle scatters rows everywhere); the blocks themselves
        # wait in our input queue until the first dispatch drains them
        if self._gen is None and not self._inputs_complete:
            return False
        return True

    def dispatch(self, ctx: ExecutionContext) -> None:
        if self._gen is None:
            while self.input_queue:
                self._collected.append(self.input_queue.popleft().ref)
            t0 = self.stats.on_task_submitted()
            self._gen = self.bulk_fn(iter(self._collected))
            self.stats.on_task_finished(t0)
        t0 = time.perf_counter()
        try:
            ref = next(self._gen)
        except StopIteration:
            self._gen_done = True
            return
        finally:
            self.stats.task_time_s += time.perf_counter() - t0
        size = ctx.probe_sizes([ref])[0]
        self._emit(RefBundle(ref, size_bytes=size), ctx)

    def add_input(self, bundle: RefBundle) -> None:
        super().add_input(bundle)

    def completed(self) -> bool:
        return self._finished or self._gen_done


class LimitOp(PhysicalOperator):
    """Driver-side row limit: counts rows per block, slices the boundary
    block, then short-circuits every upstream operator (the executor stops
    their dispatches and drops their queues)."""

    num_cpus = 0.0

    def __init__(self, limit: int):
        super().__init__(f"limit({limit})")
        self.limit = limit
        self.remaining = limit
        self.short_circuit = False

    def can_dispatch(self) -> bool:
        return bool(self.input_queue) and self.remaining > 0

    def dispatch(self, ctx: ExecutionContext) -> None:
        from ray_tpu.data.block import BlockAccessor

        bundle = self.input_queue.popleft()
        rows = bundle.num_rows
        block = None
        if rows is None:
            block = ray_tpu.get(bundle.ref)
            rows = block.num_rows
        if rows <= self.remaining:
            self.remaining -= rows
            bundle.num_rows = rows
            self._emit(bundle, ctx)
        else:
            if block is None:
                block = ray_tpu.get(bundle.ref)
            sliced = BlockAccessor(block).slice(0, self.remaining)
            ref = ray_tpu.put(sliced)
            self._emit(RefBundle(ref, size_bytes=sliced.nbytes,
                                 num_rows=self.remaining), ctx)
            self.remaining = 0
        if self.remaining <= 0:
            self.short_circuit = True
            self.input_queue.clear()
            self.inputs_complete()


class OutputSplitOp(PhysicalOperator):
    """Terminal fan-out for streaming_split: tags bundles with a consumer
    index round-robin (``equal=True`` balances block counts)."""

    num_cpus = 0.0

    def __init__(self, n: int, equal: bool = True):
        super().__init__(f"output_split({n})")
        self.n = n
        self.equal = equal
        self._next = 0

    def dispatch(self, ctx: ExecutionContext) -> None:
        bundle = self.input_queue.popleft()
        bundle.output_split_idx = self._next
        self._next = (self._next + 1) % self.n
        self._emit(bundle, ctx)
