"""Execution-plane interfaces for the streaming Data executor.

Reference capability: python/ray/data/_internal/execution/interfaces/
(RefBundle, PhysicalOperator, ExecutionResources). A physical operator is a
node of the compiled DAG: it receives ``RefBundle``s from upstream, launches
(or performs) work, and exposes finished bundles through a bounded output
queue. Only ObjectRefs flow between operators — block data never rides the
driver unless an operator explicitly needs it (Limit slicing, stats rows).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Deque, List, Optional

from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.data.execution.stats import OpStats


class RefBundle:
    """One block ref plus the metadata the scheduler needs (size for memory
    accounting, rows when known, output-split tag for streaming_split)."""

    __slots__ = ("ref", "size_bytes", "num_rows", "output_split_idx")

    def __init__(self, ref: ObjectRef, size_bytes: Optional[int] = None,
                 num_rows: Optional[int] = None,
                 output_split_idx: Optional[int] = None):
        self.ref = ref
        self.size_bytes = size_bytes
        self.num_rows = num_rows
        self.output_split_idx = output_split_idx

    def size_or(self, default: int) -> int:
        return self.size_bytes if self.size_bytes is not None else default


class ReadTaskSource:
    """A datasource compiled to independent read tasks (reference:
    planner/plan_read_op.py). Each thunk produces ONE block in a remote
    worker; the InputData operator owns submission pacing, so a 10k-file
    read never floods the cluster ahead of the consumer."""

    def __init__(self, make_tasks: List[Callable[[], Any]], name: str):
        self.make_tasks = make_tasks
        self.name = name

    def __len__(self) -> int:
        return len(self.make_tasks)


class PhysicalOperator:
    """Base physical operator. Subclasses implement dispatch/completion.

    Lifecycle: the executor moves bundles edge-to-edge (``add_input``),
    asks ``can_dispatch``/``dispatch`` to launch one unit of work at a time
    (the select_operator_to_run contract), polls ``process_completions``,
    and drains ``take_output``. ``mark_finished`` short-circuits the op when
    a downstream Limit is satisfied."""

    def __init__(self, name: str):
        self.name = name
        self.stats = OpStats(name)
        self.input_queue: Deque[RefBundle] = deque()
        self.output_queue: Deque[RefBundle] = deque()
        self.downstream: Optional["PhysicalOperator"] = None
        self.concurrency_cap: Optional[int] = None
        # participates in the ResourceManager's memory reservation + the
        # can_submit gate. Default: ops that launch remote tasks. Exchange
        # ops (AllToAll, shuffle reduce) opt in explicitly even when their
        # task accounting differs — their materialized outputs must not
        # bypass the budget every other operator honors.
        self.budget_participates: Optional[bool] = None
        self._inputs_complete = False
        self._finished = False  # short-circuit (Limit) or fully drained
        self._avg_out_bytes: Optional[float] = None

    # ---------------------------------------------------------------- wiring
    def start(self, ctx: "ExecutionContext") -> None:  # noqa: B027
        """One-time setup (remote fn/actor pool construction)."""

    def add_input(self, bundle: RefBundle) -> None:
        self.input_queue.append(bundle)
        self.stats.blocks_in += 1
        self.stats.bytes_in += bundle.size_or(0)
        self.stats.observe_queue(len(self.input_queue))

    def inputs_complete(self) -> None:
        self._inputs_complete = True

    def all_inputs_done(self) -> bool:
        return self._inputs_complete and not self.input_queue

    # ------------------------------------------------------------ scheduling
    def in_memory_budget(self) -> bool:
        """Resolved budget participation (``budget_participates`` wins when
        set; else: launches remote tasks <=> has a concurrency cap)."""
        if self.budget_participates is not None:
            return self.budget_participates
        return self.concurrency_cap is not None

    def can_dispatch(self) -> bool:
        """Work is available to launch right now (ignoring backpressure —
        policies and the ResourceManager gate the actual selection)."""
        return bool(self.input_queue)

    def dispatch(self, ctx: "ExecutionContext") -> None:
        raise NotImplementedError

    def active_refs(self) -> List[ObjectRef]:
        """In-flight task refs (for the executor's blocking wait)."""
        return []

    def num_active_tasks(self) -> int:
        return len(self.active_refs())

    def process_completions(self, ctx: "ExecutionContext",
                            ready: Optional[List["ObjectRef"]] = None) -> bool:
        """Harvest finished work into the output queue (non-blocking).
        ``ready``: refs the executor already observed complete this tick.
        Returns True if anything was produced."""
        return False

    def completed(self) -> bool:
        return self._finished or (
            self.all_inputs_done() and self.num_active_tasks() == 0
        )

    def mark_finished(self) -> None:
        """Downstream no longer needs outputs (Limit satisfied): drop queued
        input and stop dispatching. In-flight tasks finish in the background
        and are discarded."""
        self._finished = True
        self.input_queue.clear()
        self.output_queue.clear()

    def shutdown(self) -> None:  # noqa: B027
        """Release operator-owned resources (actor pools)."""

    # ------------------------------------------------------------- emit path
    def _emit(self, bundle: RefBundle, ctx: "ExecutionContext") -> None:
        if self._finished:
            return
        if bundle.size_bytes is not None:
            n = self.stats.blocks_out
            prev = self._avg_out_bytes if self._avg_out_bytes is not None else 0.0
            self._avg_out_bytes = (prev * n + bundle.size_bytes) / (n + 1)
        if ctx.collect_rows and bundle.num_rows is None:
            try:
                import ray_tpu

                bundle.num_rows = ray_tpu.get(bundle.ref).num_rows
            except Exception:  # noqa: BLE001 - stats must not fail the run
                pass
        self.output_queue.append(bundle)
        self.stats.blocks_out += 1
        self.stats.bytes_out += bundle.size_or(0)
        if bundle.num_rows:
            self.stats.rows_out += bundle.num_rows
        self.stats.last_output_at = time.perf_counter()

    # ------------------------------------------------------ memory accounting
    def estimated_output_bytes_per_block(self) -> int:
        if self._avg_out_bytes:
            return int(self._avg_out_bytes)
        if self.stats.blocks_in:
            return max(1, self.stats.bytes_in // self.stats.blocks_in)
        return 1 << 20  # nothing observed yet: assume 1 MiB blocks

    def internal_bytes(self) -> int:
        """Bytes this op holds outside the queues: in-flight task outputs,
        estimated from observed output sizes."""
        return self.num_active_tasks() * self.estimated_output_bytes_per_block()

    def queued_output_bytes(self) -> int:
        """Bytes this op has produced that nobody consumed yet: its own
        output queue plus what sits in the downstream input queue."""
        total = sum(b.size_or(self.estimated_output_bytes_per_block())
                    for b in self.output_queue)
        if self.downstream is not None:
            total += sum(
                b.size_or(self.estimated_output_bytes_per_block())
                for b in self.downstream.input_queue)
        return total

    def __repr__(self) -> str:
        return (f"{type(self).__name__}({self.name!r}, active="
                f"{self.num_active_tasks()}, in={len(self.input_queue)}, "
                f"out={len(self.output_queue)})")


class ExecutionContext:
    """Shared per-execution state handed to operators."""

    def __init__(self, collect_rows: bool = False):
        self.collect_rows = collect_rows
        self._runtime = None

    @property
    def runtime(self):
        if self._runtime is None:
            from ray_tpu import api as _api

            self._runtime = _api.global_worker().runtime
        return self._runtime

    def probe_sizes(self, refs: List[ObjectRef]) -> List[Optional[int]]:
        """Batched stored-size lookup (one control RPC per completion batch,
        not one per block)."""
        try:
            return self.runtime.object_sizes(refs)
        except Exception:  # noqa: BLE001 - hints only; never fail the run
            return [None] * len(refs)
