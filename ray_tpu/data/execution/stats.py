"""Per-operator execution statistics (reference: _internal/stats.py
DatasetStats / OpRuntimeMetrics). Collected at the operator boundaries the
scheduling loop already owns, so recording costs a few counter bumps per
block, not extra RPCs."""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


class OpStats:
    """Counters for one physical operator."""

    def __init__(self, name: str):
        self.name = name
        self.blocks_in = 0
        self.blocks_out = 0
        self.bytes_in = 0
        self.bytes_out = 0
        self.rows_out = 0
        self.tasks_submitted = 0
        self.tasks_finished = 0
        # sum of per-task (completion - submission) wall, driver-observed
        self.task_time_s = 0.0
        self.first_dispatch_at: Optional[float] = None
        self.last_output_at: Optional[float] = None
        self.queue_peak = 0      # input-queue occupancy high-water mark
        self.in_flight_peak = 0  # concurrent-task high-water mark
        # operator-specific counters (e.g. a shuffle's exchange bytes,
        # spill bytes, admission stalls) rendered as a supplementary line
        self.extra: Dict[str, Any] = {}

    def observe_queue(self, depth: int) -> None:
        if depth > self.queue_peak:
            self.queue_peak = depth

    def observe_in_flight(self, n: int) -> None:
        if n > self.in_flight_peak:
            self.in_flight_peak = n

    def on_task_submitted(self) -> float:
        self.tasks_submitted += 1
        if self.first_dispatch_at is None:
            self.first_dispatch_at = time.perf_counter()
        return time.perf_counter()

    def on_task_finished(self, submitted_at: float) -> None:
        self.tasks_finished += 1
        self.task_time_s += time.perf_counter() - submitted_at

    @property
    def wall_s(self) -> float:
        """Operator-active wall span: first dispatch to last output."""
        if self.first_dispatch_at is None:
            return 0.0
        end = self.last_output_at or time.perf_counter()
        return max(0.0, end - self.first_dispatch_at)

    def row(self) -> Dict[str, Any]:
        out = {
            "operator": self.name,
            "blocks_in": self.blocks_in,
            "blocks_out": self.blocks_out,
            "bytes_out": self.bytes_out,
            "rows": self.rows_out,
            "tasks": self.tasks_finished,
            "task_s": round(self.task_time_s, 4),
            "wall_s": round(self.wall_s, 4),
            "queue_peak": self.queue_peak,
            "in_flight_peak": self.in_flight_peak,
        }
        if self.extra:
            out["extra"] = dict(self.extra)
        return out


def format_stats_table(rows: List[Dict[str, Any]],
                       collect_rows: bool = True) -> str:
    header = (f"{'operator':<32}{'in':>6}{'out':>6}{'bytes_out':>12}"
              f"{'rows':>8}{'task_s':>9}{'wall_s':>9}{'queue^':>7}{'tasks^':>7}")
    lines = [header]
    for r in rows:
        lines.append(
            f"{r['operator'][:31]:<32}{r['blocks_in']:>6}{r['blocks_out']:>6}"
            f"{r['bytes_out']:>12}"
            f"{(r['rows'] if collect_rows else '-'):>8}"
            f"{r['task_s']:>9}{r['wall_s']:>9}"
            f"{r['queue_peak']:>7}{r['in_flight_peak']:>7}")
        extra = r.get("extra")
        if extra:
            detail = ", ".join(
                f"{k}={round(v, 3) if isinstance(v, float) else v}"
                for k, v in extra.items())
            lines.append(f"  └ {detail}")
    return "\n".join(lines)
