"""Pull-based scheduling loop over the physical operator DAG.

Reference capability: python/ray/data/_internal/execution/
streaming_executor.py (:48, loop :272) + streaming_executor_state.py:527
``select_operator_to_run``. Each turn of the loop:

1. harvests finished tasks into operator output queues,
2. moves bundles along the edges (bounded, byte-accounted block queues),
3. repeatedly picks ONE runnable operator — filtered through the
   backpressure policies and the ResourceManager budgets, ranked by least
   un-consumed output (drain toward the sink) — and dispatches one unit of
   work,
4. yields terminal bundles to the consumer.

The executor is a generator: while the consumer is not pulling, nothing new
dispatches, so a stalled consumer freezes the pipeline at its current
(bounded) occupancy instead of buffering the world. A slow operator's full
queues make the policies reject its upstream — backpressure propagates to
the source, which stops submitting read tasks."""

from __future__ import annotations

import time
from typing import Iterator, List, Optional

import ray_tpu
from ray_tpu.data.execution.backpressure import (
    BackpressurePolicy,
    default_policies,
)
from ray_tpu.data.execution.interfaces import (
    ExecutionContext,
    PhysicalOperator,
    RefBundle,
)
from ray_tpu.data.execution.resource_manager import ResourceManager
from ray_tpu.data.execution.stats import format_stats_table
from ray_tpu.utils.logging import get_logger

logger = get_logger("data.streaming_executor")

# per-tick dispatch bound: a safety valve against a buggy operator that
# always claims dispatchability without making progress
_MAX_DISPATCHES_PER_TICK = 256


class StreamingExecutor:
    def __init__(self, operators: List[PhysicalOperator],
                 collect_rows: bool = False,
                 resource_manager: Optional[ResourceManager] = None,
                 policies: Optional[List[BackpressurePolicy]] = None):
        if not operators:
            raise ValueError("streaming executor needs at least one operator")
        self._ops = operators
        self._index = {id(op): i for i, op in enumerate(operators)}
        for up, down in zip(operators, operators[1:]):
            up.downstream = down
        self._ctx = ExecutionContext(collect_rows=collect_rows)
        self._rm = resource_manager or ResourceManager(operators)
        self._policies = policies if policies is not None else default_policies()
        self.collect_rows = collect_rows
        # high-water mark of blocks alive anywhere in the pipeline
        # (in-flight tasks + queued): the number the backpressure tests and
        # the bench artifact watch
        self.peak_total_blocks = 0
        self._consumed = False

    # ------------------------------------------------------------- execution
    def execute(self) -> Iterator[RefBundle]:
        for op in self._ops:
            op.start(self._ctx)
        last = self._ops[-1]
        try:
            discovered = None
            while True:
                progressed = self._tick(discovered)
                discovered = None
                while last.output_queue:
                    self._consumed = True
                    yield last.output_queue.popleft()
                if self._all_done():
                    return
                if not progressed and not last.output_queue:
                    if not self._liveness_valve():
                        discovered = self._wait_for_any()
        finally:
            for op in self._ops:
                try:
                    op.shutdown()
                except Exception:  # noqa: BLE001 - teardown must not mask
                    logger.exception("operator %s shutdown failed", op.name)

    def _tick(self, discovered=None) -> bool:
        progressed = False
        # ONE wait across every operator's in-flight refs per tick.
        # ``discovered`` carries refs the blocking _wait_for_any already saw
        # complete — crucial in cluster mode, where a zero-timeout wait only
        # reports the DRIVER node's store and would never observe tasks that
        # finished on other nodes (their readiness signal is the GCS
        # location directory, consulted only by positive-timeout waits).
        ready_set = set(discovered or ())
        all_refs = [r for op in self._ops for r in op.active_refs()]
        if all_refs and not ready_set:
            ready, _ = ray_tpu.wait(all_refs, num_returns=len(all_refs),
                                    timeout=0)
            ready_set.update(ready)
        for op in self._ops:
            if op.active_refs() and op.process_completions(
                    self._ctx, ready=[r for r in op.active_refs()
                                      if r in ready_set]):
                progressed = True
        progressed |= self._move_edges()
        for _ in range(_MAX_DISPATCHES_PER_TICK):
            op = self._select_operator_to_run()
            if op is None:
                break
            before = (op.num_active_tasks(), len(op.output_queue),
                      len(op.input_queue))
            op.dispatch(self._ctx)
            op.stats.observe_in_flight(op.num_active_tasks())
            after = (op.num_active_tasks(), len(op.output_queue),
                     len(op.input_queue))
            self._move_edges()
            self._observe_occupancy()
            if after == before:
                # a dispatch that did nothing (exhausted iterator source):
                # don't spin on it this tick
                break
            progressed = True
        progressed |= self._short_circuit_limits()
        self._observe_occupancy()
        return progressed

    def _move_edges(self) -> bool:
        moved = False
        for op in self._ops:
            down = op.downstream
            if down is None:
                continue
            while op.output_queue:
                down.add_input(op.output_queue.popleft())
                moved = True
            if (op.completed() and not op.output_queue
                    and not down._inputs_complete):  # noqa: SLF001
                down.inputs_complete()
                moved = True
        return moved

    def _select_operator_to_run(self) -> Optional[PhysicalOperator]:
        candidates = []
        for op in self._ops:
            if op._finished or not op.can_dispatch():  # noqa: SLF001
                continue
            if not all(p.can_add_input(op) for p in self._policies):
                continue
            if op.in_memory_budget() and not self._rm.can_submit(op):
                continue
            candidates.append(op)
        if not candidates:
            return None
        # least un-consumed output first; ties drain toward the sink
        return min(
            candidates,
            key=lambda op: (
                len(op.output_queue)
                + (len(op.downstream.input_queue) if op.downstream else 0),
                -self._index[id(op)],
            ),
        )

    def _short_circuit_limits(self) -> bool:
        changed = False
        for i, op in enumerate(self._ops):
            if getattr(op, "short_circuit", False):
                for up in self._ops[:i]:
                    if not up._finished:  # noqa: SLF001
                        up.mark_finished()
                        changed = True
        return changed

    def _liveness_valve(self) -> bool:
        """Deadlock breaker: when every policy rejects every operator and
        NOTHING is in flight, force one dispatch on the first op with work.
        (E.g. an exchange whose output count equals the queue cap needs one
        more pull to observe exhaustion — a budget must throttle, never
        wedge the pipeline.)"""
        if any(op.active_refs() for op in self._ops):
            return False
        forced = next(
            (op for op in self._ops
             if not op._finished and op.can_dispatch()),  # noqa: SLF001
            None,
        )
        if forced is None:
            return False
        forced.dispatch(self._ctx)
        self._move_edges()
        self._observe_occupancy()
        return True

    def _observe_occupancy(self) -> None:
        total = 0
        for op in self._ops:
            total += (op.num_active_tasks() + len(op.input_queue)
                      + len(op.output_queue))
        if total > self.peak_total_blocks:
            self.peak_total_blocks = total

    def _all_done(self) -> bool:
        last = self._ops[-1]
        return last.completed() and not last.output_queue and not any(
            op.num_active_tasks() for op in self._ops
        )

    def _wait_for_any(self):
        # BLOCKING wait, not a poll: in cluster mode every wait() is a
        # control RPC, and a 100ms poll loop both spams the agents and (on
        # small hosts) starves the very workers it is waiting on. Nothing
        # new becomes dispatchable until a task completes, so parking here
        # is free; the bounded timeout is only a liveness net. Returns the
        # refs observed ready so the next tick can act on them — in cluster
        # mode this is the ONLY reliable completion signal for tasks that
        # ran on other nodes.
        refs = [r for op in self._ops for r in op.active_refs()]
        if refs:
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=5.0)
            return ready or None
        time.sleep(0.01)
        return None

    # ------------------------------------------------------------------ stats
    @property
    def stats(self):
        return [op.stats for op in self._ops]

    def stats_rows(self) -> List[dict]:
        return [op.stats.row() for op in self._ops]

    def summary(self) -> str:
        return format_stats_table(self.stats_rows(),
                                  collect_rows=self.collect_rows)

    def any_output_produced(self) -> bool:
        return any(st.blocks_out for st in self.stats)
