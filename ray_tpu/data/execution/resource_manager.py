"""Per-operator resource budgets (reference: _internal/execution/
resource_manager.py ReservationOpResourceAllocator).

The global Data budget is a fraction of the object-store capacity
(``config.data_memory_fraction``) plus the cluster CPU total. Half the
memory budget is RESERVED, split evenly across budget-participating
operators — so a fast producer can never starve a slow consumer of its
guaranteed headroom; the other half is a SHARED pool claimed first-come.
An operator with nothing in flight may always launch one task (liveness:
a budget must throttle, never deadlock)."""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.data.execution.interfaces import PhysicalOperator


class ResourceManager:
    def __init__(self, operators: List[PhysicalOperator],
                 memory_budget_bytes: Optional[int] = None,
                 cpu_total: Optional[float] = None):
        from ray_tpu.core.config import config

        self._ops = operators
        self.memory_budget = memory_budget_bytes if memory_budget_bytes \
            is not None else int(config.object_store_memory_bytes
                                 * config.data_memory_fraction)
        self.cpu_total = cpu_total if cpu_total is not None \
            else self._detect_cpu_total()
        # only ops that materialize blocks participate in the reservation;
        # pass-through ops (Limit, OutputSplit) hold no task memory. Exchange
        # ops (AllToAll, shuffle) opt in via budget_participates even though
        # their task model differs — their outputs must not bypass the
        # accounting that backpressures every other operator.
        budgeted = [op for op in operators if op.in_memory_budget()] \
            or list(operators)
        self._reserved: Dict[int, int] = {
            id(op): self.memory_budget // (2 * len(budgeted)) for op in budgeted
        }
        self._shared_total = self.memory_budget - sum(self._reserved.values())

    @staticmethod
    def _detect_cpu_total() -> float:
        try:
            from ray_tpu import api as _api

            return float(_api.cluster_resources().get("CPU", 0)) or 1.0
        except Exception:  # noqa: BLE001 - uninitialized runtime (tests)
            import os

            return float(os.cpu_count() or 1)

    # ------------------------------------------------------------- accounting
    def op_usage_bytes(self, op: PhysicalOperator) -> int:
        """An operator is charged for what it has MATERIALIZED but nobody
        consumed: in-flight task outputs (estimated) + its output queue +
        the downstream input queue it filled."""
        return op.internal_bytes() + op.queued_output_bytes()

    def global_usage_bytes(self) -> int:
        return sum(self.op_usage_bytes(op) for op in self._ops)

    def cpus_in_flight(self) -> float:
        return sum(
            op.num_active_tasks() * getattr(op, "num_cpus", 1.0)
            for op in self._ops
        )

    # -------------------------------------------------------------- decisions
    def can_submit(self, op: PhysicalOperator) -> bool:
        if op.num_active_tasks() == 0 and not op.output_queue:
            return True  # liveness valve: one task per starved op always runs
        # CPU: never queue more tasks than the cluster can run concurrently
        # (oversubscribing buys queueing, not throughput)
        if self.cpus_in_flight() + getattr(op, "num_cpus", 1.0) > self.cpu_total:
            return False
        projected = (self.op_usage_bytes(op)
                     + op.estimated_output_bytes_per_block())
        reserved = self._reserved.get(id(op), 0)
        if projected <= reserved:
            return True
        shared_used = sum(
            max(0, self.op_usage_bytes(o) - self._reserved.get(id(o), 0))
            for o in self._ops
        )
        return projected - reserved <= self._shared_total - shared_used

    def debug(self) -> Dict[str, int]:
        return {
            "memory_budget": self.memory_budget,
            "memory_used": self.global_usage_bytes(),
            "cpu_total": int(self.cpu_total),
            "cpus_in_flight": int(self.cpus_in_flight()),
        }
