"""Pluggable backpressure policies (reference: _internal/execution/
backpressure_policy/ — ConcurrencyCapBackpressurePolicy +
DownstreamCapacityBackpressurePolicy). A policy answers one question per
scheduling step: may this operator launch another task right now?

Both built-ins are on by default: the concurrency cap bounds how many tasks
one operator keeps in flight, and the downstream-capacity policy stops a
producer whose consumer is falling behind (queue depth in blocks AND bytes),
so a slow stage throttles its upstream instead of ballooning the block
queues."""

from __future__ import annotations

from typing import List

from ray_tpu.data.execution.interfaces import PhysicalOperator


class BackpressurePolicy:
    def can_add_input(self, op: PhysicalOperator) -> bool:
        raise NotImplementedError


class ConcurrencyCapBackpressurePolicy(BackpressurePolicy):
    """At most ``op.concurrency_cap`` tasks in flight per operator (ops with
    no cap — driver-side pass-throughs — are unthrottled here)."""

    def can_add_input(self, op: PhysicalOperator) -> bool:
        cap = op.concurrency_cap
        return cap is None or op.num_active_tasks() < cap


class DownstreamCapacityBackpressurePolicy(BackpressurePolicy):
    """Stop dispatching when the operator's un-consumed output — its output
    queue plus the downstream input queue — exceeds the configured block
    count or the operator's share of the memory budget."""

    def __init__(self, max_queued_blocks: int = 0,
                 max_queued_bytes: int = 0):
        from ray_tpu.core.config import config

        self.max_queued_blocks = max_queued_blocks \
            or config.data_max_queued_blocks
        self.max_queued_bytes = max_queued_bytes or int(
            config.object_store_memory_bytes * config.data_memory_fraction)

    def can_add_input(self, op: PhysicalOperator) -> bool:
        queued_blocks = len(op.output_queue)
        if op.downstream is not None:
            queued_blocks += len(op.downstream.input_queue)
        if queued_blocks >= self.max_queued_blocks:
            return False
        return op.queued_output_bytes() < self.max_queued_bytes


def default_policies() -> List[BackpressurePolicy]:
    return [ConcurrencyCapBackpressurePolicy(),
            DownstreamCapacityBackpressurePolicy()]
