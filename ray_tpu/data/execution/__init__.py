from ray_tpu.data.execution.backpressure import (
    BackpressurePolicy,
    ConcurrencyCapBackpressurePolicy,
    DownstreamCapacityBackpressurePolicy,
    default_policies,
)
from ray_tpu.data.execution.interfaces import (
    ExecutionContext,
    PhysicalOperator,
    ReadTaskSource,
    RefBundle,
)
from ray_tpu.data.execution.operators import (
    ActorPoolMapOp,
    AllToAllOp,
    InputDataOp,
    LimitOp,
    OutputSplitOp,
    TaskPoolMapOp,
)
from ray_tpu.data.execution.planner import build_physical_plan
from ray_tpu.data.execution.resource_manager import ResourceManager
from ray_tpu.data.execution.stats import OpStats, format_stats_table
from ray_tpu.data.execution.streaming_executor import StreamingExecutor

__all__ = [
    "ActorPoolMapOp",
    "AllToAllOp",
    "BackpressurePolicy",
    "ConcurrencyCapBackpressurePolicy",
    "DownstreamCapacityBackpressurePolicy",
    "ExecutionContext",
    "InputDataOp",
    "LimitOp",
    "OpStats",
    "OutputSplitOp",
    "PhysicalOperator",
    "ReadTaskSource",
    "RefBundle",
    "ResourceManager",
    "StreamingExecutor",
    "TaskPoolMapOp",
    "build_physical_plan",
    "default_policies",
    "format_stats_table",
]
