"""Logical plan -> physical operator compilation (reference:
python/ray/data/_internal/planner/planner.py: logical operators map 1:1
onto physical operators).

All-to-all stages now compile two ways:

- streaming (default): a ``ShuffleMapOp`` + ``ShuffleReduceOp`` pair
  sharing one ``ShuffleCoordinator`` — map-side partitioner tasks run as
  each upstream block lands, reduce admission is spill-aware
  (``ray_tpu/data/shuffle/``);
- barrier (``RTPU_STREAMING_SHUFFLE=0``, or stages with no ShuffleSpec —
  zip, keyless aggregate): the stage's ``execute()`` bulk exchange behind
  an ``AllToAllOp``.
"""

from __future__ import annotations

from typing import Any, List, Optional

from ray_tpu.data.execution.interfaces import PhysicalOperator, ReadTaskSource
from ray_tpu.data.execution.operators import (
    ActorPoolMapOp,
    AllToAllOp,
    InputDataOp,
    LimitOp,
    OutputSplitOp,
    TaskPoolMapOp,
)


def build_physical_plan(source: Any, stages: List[Any],
                        output_split: Optional[int] = None,
                        equal_split: bool = True) -> List[PhysicalOperator]:
    """``source`` is a ReadTaskSource or a callable returning a ref
    iterator (Dataset._source_fn); ``stages`` are the logical stages from
    ``ray_tpu.data.executor``."""
    from ray_tpu.core.config import streaming_shuffle_enabled
    from ray_tpu.data.executor import LimitStage, MapStage

    ops: List[PhysicalOperator] = [InputDataOp(source)]
    # upstream block-count estimate, threaded through the plan so a shuffle
    # can fix its reducer count BEFORE the first block arrives (streaming
    # mapping needs num_returns up front); map stages are 1:1, a limit only
    # truncates, so the hint stays a sound upper bound
    block_hint: Optional[int] = (
        len(source) if isinstance(source, ReadTaskSource) else None)
    for stage in stages:
        if isinstance(stage, MapStage):
            if stage.fn_constructor is not None:
                ops.append(ActorPoolMapOp(
                    stage.name, stage.block_fn, stage.fn_constructor,
                    concurrency=stage.concurrency, num_cpus=stage.num_cpus,
                ))
            else:
                ops.append(TaskPoolMapOp(
                    stage.name, stage.block_fn, num_cpus=stage.num_cpus,
                    concurrency=stage.concurrency,
                ))
        elif isinstance(stage, LimitStage):
            ops.append(LimitOp(stage.limit))
        else:
            spec = stage.shuffle_spec() if hasattr(stage, "shuffle_spec") \
                else None
            if spec is not None and streaming_shuffle_enabled():
                from ray_tpu.data.shuffle import (
                    ShuffleCoordinator,
                    ShuffleMapOp,
                    ShuffleReduceOp,
                )

                n_out = spec.resolve_partitions(block_hint)
                coord = ShuffleCoordinator(spec.name, n_out)
                ops.append(ShuffleMapOp(spec, coord))
                ops.append(ShuffleReduceOp(spec, coord))
                block_hint = n_out
            else:
                # zip / keyless aggregate / explicit barrier fallback: the
                # stage's execute() IS the bulk exchange
                ops.append(AllToAllOp(stage.name, stage.execute))
                block_hint = None
    if output_split is not None:
        ops.append(OutputSplitOp(output_split, equal=equal_split))
    return ops
