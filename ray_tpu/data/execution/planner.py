"""Logical plan -> physical operator compilation (reference:
python/ray/data/_internal/planner/planner.py: logical operators map 1:1
onto physical operators; all-to-all stages keep their distributed exchange
implementations as the bulk transform behind an ``AllToAllOp`` barrier)."""

from __future__ import annotations

from typing import Any, List, Optional

from ray_tpu.data.execution.interfaces import PhysicalOperator
from ray_tpu.data.execution.operators import (
    ActorPoolMapOp,
    AllToAllOp,
    InputDataOp,
    LimitOp,
    OutputSplitOp,
    TaskPoolMapOp,
)


def build_physical_plan(source: Any, stages: List[Any],
                        output_split: Optional[int] = None,
                        equal_split: bool = True) -> List[PhysicalOperator]:
    """``source`` is a ReadTaskSource or a callable returning a ref
    iterator (Dataset._source_fn); ``stages`` are the logical stages from
    ``ray_tpu.data.executor``."""
    from ray_tpu.data.executor import LimitStage, MapStage

    ops: List[PhysicalOperator] = [InputDataOp(source)]
    for stage in stages:
        if isinstance(stage, MapStage):
            if stage.fn_constructor is not None:
                ops.append(ActorPoolMapOp(
                    stage.name, stage.block_fn, stage.fn_constructor,
                    concurrency=stage.concurrency, num_cpus=stage.num_cpus,
                ))
            else:
                ops.append(TaskPoolMapOp(
                    stage.name, stage.block_fn, num_cpus=stage.num_cpus,
                    concurrency=stage.concurrency,
                ))
        elif isinstance(stage, LimitStage):
            ops.append(LimitOp(stage.limit))
        else:
            # all-to-all family (repartition/shuffle/sort/aggregate/zip):
            # the stage's execute() IS the bulk exchange
            ops.append(AllToAllOp(stage.name, stage.execute))
    if output_split is not None:
        ops.append(OutputSplitOp(output_split, equal=equal_split))
    return ops
