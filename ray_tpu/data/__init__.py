from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.dataset import DataIterator, Dataset
from ray_tpu.data.read_api import (
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,  # noqa: A004
    range_tensor,
    read_binary_files,
    read_csv,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
    read_tfrecords,
    read_webdataset,
)

__all__ = [
    "Block",
    "BlockAccessor",
    "DataIterator",
    "Dataset",
    "from_arrow",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "range_tensor",
    "read_binary_files",
    "read_csv",
    "read_images",
    "read_json",
    "read_numpy",
    "read_parquet",
    "read_text",
    "read_tfrecords",
    "read_webdataset",
]
