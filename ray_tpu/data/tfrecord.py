"""TFRecord file format + tf.train.Example wire codec, dependency-free.

Reference capability: python/ray/data/_internal/datasource/tfrecords_datasource.py
(reads TFRecord files of tf.train.Example protos). TensorFlow is not in this
image, so both layers are implemented natively:

- framing: each record is [u64 length][u32 masked-crc32c(length)]
  [payload][u32 masked-crc32c(payload)];
- payload: a tf.train.Example protobuf — a tiny fixed schema (Features =
  map<string, Feature>, Feature = oneof bytes/float/int64 list) decoded with
  a ~100-line varint wire parser instead of a TF dependency.

CRC32C here is table-driven pure Python (~MB/s): fine for record *framing*
checks and test-size files; pass ``verify_crc=False`` (the default for
reads) to skip payload CRCs on bulk pipelines.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List, Tuple, Union

# --------------------------------------------------------------------------- #
# crc32c (Castagnoli) + TFRecord masking
# --------------------------------------------------------------------------- #
_CRC_TABLE: List[int] = []


def _crc_table() -> List[int]:
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# --------------------------------------------------------------------------- #
# protobuf wire helpers (just what Example needs)
# --------------------------------------------------------------------------- #
def _write_varint(n: int, out: bytearray) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _tag(field: int, wire: int) -> int:
    return field << 3 | wire


def _write_len_delimited(field: int, payload: bytes, out: bytearray) -> None:
    _write_varint(_tag(field, 2), out)
    _write_varint(len(payload), out)
    out += payload


def _iter_fields(buf: bytes) -> Iterator[Tuple[int, int, Any]]:
    """Yields (field_number, wire_type, value); value is bytes for
    len-delimited, int for varint/fixed."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:  # varint
            val, pos = _read_varint(buf, pos)
        elif wire == 2:  # length-delimited
            length, pos = _read_varint(buf, pos)
            val = buf[pos : pos + length]
            pos += length
        elif wire == 5:  # fixed32
            val = struct.unpack_from("<I", buf, pos)[0]
            pos += 4
        elif wire == 1:  # fixed64
            val = struct.unpack_from("<Q", buf, pos)[0]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


FeatureValue = Union[List[bytes], List[float], List[int]]


def encode_example(features: Dict[str, Any]) -> bytes:
    """dict -> serialized tf.train.Example. Values may be bytes/str/int/float
    or lists thereof; numpy arrays are flattened to their list form."""
    import numpy as np

    feats = bytearray()
    for name, value in features.items():
        if isinstance(value, np.ndarray):
            value = value.ravel().tolist()
        if not isinstance(value, (list, tuple)):
            value = [value]
        inner = bytearray()  # BytesList/FloatList/Int64List
        if value and isinstance(value[0], (bytes, str)):
            for v in value:
                _write_len_delimited(
                    1, v.encode() if isinstance(v, str) else v, inner)
            kind = 1
        elif value and isinstance(value[0], float):
            packed = struct.pack(f"<{len(value)}f", *value)
            _write_len_delimited(1, packed, inner)
            kind = 2
        else:  # ints (or empty -> int64 list)
            packed = bytearray()
            for v in value:
                _write_varint(v & 0xFFFFFFFFFFFFFFFF, packed)
            _write_len_delimited(1, bytes(packed), inner)
            kind = 3
        feature = bytearray()
        _write_len_delimited(kind, bytes(inner), feature)
        entry = bytearray()  # map entry {key=1, value=2}
        _write_len_delimited(1, name.encode(), entry)
        _write_len_delimited(2, bytes(feature), entry)
        _write_len_delimited(1, bytes(entry), feats)
    out = bytearray()  # Example {features=1}
    _write_len_delimited(1, bytes(feats), out)
    return bytes(out)


def _decode_list(kind: int, buf: bytes) -> FeatureValue:
    values: List[Any] = []
    for field, wire, val in _iter_fields(buf):
        if field != 1:
            continue
        if kind == 1:  # BytesList
            values.append(val)
        elif kind == 2:  # FloatList: packed or repeated fixed32
            if wire == 2:
                values.extend(struct.unpack(f"<{len(val) // 4}f", val))
            else:
                values.append(struct.unpack("<f", struct.pack("<I", val))[0])
        else:  # Int64List: packed or repeated varint
            if wire == 2:
                pos = 0
                while pos < len(val):
                    v, pos = _read_varint(val, pos)
                    values.append(v - (1 << 64) if v >= 1 << 63 else v)
            else:
                values.append(val - (1 << 64) if val >= 1 << 63 else val)
    return values


def decode_example(payload: bytes) -> Dict[str, FeatureValue]:
    """serialized tf.train.Example -> {name: list of bytes|float|int}."""
    out: Dict[str, FeatureValue] = {}
    for field, _wire, features_buf in _iter_fields(payload):
        if field != 1:
            continue
        for f2, _w2, entry in _iter_fields(features_buf):
            if f2 != 1:
                continue
            name, feature = "", b""
            for f3, _w3, val in _iter_fields(entry):
                if f3 == 1:
                    name = val.decode()
                elif f3 == 2:
                    feature = val
            for kind, _w4, lst in _iter_fields(feature):
                out[name] = _decode_list(kind, lst)
    return out


# --------------------------------------------------------------------------- #
# Record framing
# --------------------------------------------------------------------------- #
def read_records(path: str, verify_crc: bool = False) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                return
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:])
            if verify_crc and masked_crc(header[:8]) != len_crc:
                raise ValueError(f"corrupt record length CRC in {path}")
            payload = f.read(length)
            (data_crc,) = struct.unpack("<I", f.read(4))
            if verify_crc and masked_crc(payload) != data_crc:
                raise ValueError(f"corrupt record payload CRC in {path}")
            yield payload


def write_records(path: str, payloads: Iterator[bytes]) -> int:
    """Write raw records; returns count. (Writer exists so tests and
    ``Dataset.write_tfrecords`` can produce files TF itself can read.)"""
    n = 0
    with open(path, "wb") as f:
        for payload in payloads:
            header = struct.pack("<Q", len(payload))
            f.write(header)
            f.write(struct.pack("<I", masked_crc(header)))
            f.write(payload)
            f.write(struct.pack("<I", masked_crc(payload)))
            n += 1
    return n


def write_tfrecords(path: str, examples: List[Dict[str, Any]]) -> int:
    return write_records(path, (encode_example(e) for e in examples))
