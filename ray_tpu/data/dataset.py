"""Dataset: lazy, streaming-executed distributed data.

Reference capability: python/ray/data/dataset.py (+ read_api.py,
iterator.py): lazy logical plan built by transformations, executed by the
streaming executor on iteration/consumption; per-worker shards via
streaming_split; device-prefetching batch iteration for TPU input pipelines
(the host→HBM double-buffering tier the reference leaves to torch loaders).
"""

from __future__ import annotations

import itertools
import queue as _queue
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.data.block import Batch, Block, BlockAccessor, block_from_batch, block_from_rows, concat_blocks
from ray_tpu.data.executor import (
    DEFAULT_MAX_IN_FLIGHT,
    MapStage,
    RepartitionStage,
    ShuffleStage,
    Stage,
    StreamingExecutor,
)
from ray_tpu.utils.logging import get_logger

logger = get_logger("data")


class Dataset:
    def __init__(self, source_fn: Callable[[], Iterator[ObjectRef]], stages: Optional[List[Stage]] = None):
        self._source_fn = source_fn
        self._stages: List[Stage] = stages or []

    # ------------------------------------------------------------ transforms
    def _with_stage(self, stage: Stage) -> "Dataset":
        return Dataset(self._source_fn, self._stages + [stage])

    def map_batches(
        self,
        fn: Union[Callable[[Batch], Batch], type],
        *,
        batch_format: str = "numpy",
        batch_size: Optional[int] = None,
        num_cpus: float = 1.0,
        concurrency: Optional[int] = None,
        fn_constructor_args: tuple = (),
        **_ignored,
    ) -> "Dataset":
        if isinstance(fn, type):
            cls = fn

            def ctor():
                return cls(*fn_constructor_args)

            def block_fn(block: Block, callable_obj) -> Block:
                batch = BlockAccessor(block).to_batch(batch_format)
                return block_from_batch(callable_obj(batch))

            return self._with_stage(
                MapStage(f"map_batches({cls.__name__})", block_fn,
                         num_cpus=num_cpus, fn_constructor=ctor, concurrency=concurrency)
            )

        def block_fn(block: Block) -> Block:
            batch = BlockAccessor(block).to_batch(batch_format)
            return block_from_batch(fn(batch))

        return self._with_stage(
            MapStage(f"map_batches({getattr(fn, '__name__', 'fn')})", block_fn, num_cpus=num_cpus)
        )

    def map(self, fn: Callable[[Dict], Dict], num_cpus: float = 1.0) -> "Dataset":
        def block_fn(block: Block) -> Block:
            rows = [fn(r) for r in BlockAccessor(block).iter_rows()]
            return block_from_rows(rows)

        return self._with_stage(MapStage(f"map({getattr(fn, '__name__', 'fn')})", block_fn, num_cpus=num_cpus))

    def flat_map(self, fn: Callable[[Dict], List[Dict]], num_cpus: float = 1.0) -> "Dataset":
        def block_fn(block: Block) -> Block:
            rows: List[Dict] = []
            for r in BlockAccessor(block).iter_rows():
                rows.extend(fn(r))
            return block_from_rows(rows)

        return self._with_stage(MapStage("flat_map", block_fn, num_cpus=num_cpus))

    def filter(self, fn: Callable[[Dict], bool], num_cpus: float = 1.0) -> "Dataset":
        def block_fn(block: Block) -> Block:
            import pyarrow as pa

            mask = pa.array([fn(r) for r in BlockAccessor(block).iter_rows()])
            return block.filter(mask)

        return self._with_stage(MapStage("filter", block_fn, num_cpus=num_cpus))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with_stage(RepartitionStage(num_blocks))

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        return self._with_stage(ShuffleStage(seed))

    def union(self, *others: "Dataset") -> "Dataset":
        selves = [self, *others]

        def source() -> Iterator[ObjectRef]:
            for ds in selves:
                yield from ds._execute()

        return Dataset(source)

    def limit(self, n: int) -> "Dataset":
        parent = self

        def source() -> Iterator[ObjectRef]:
            remaining = n
            for ref in parent._execute():
                if remaining <= 0:
                    return
                block = ray_tpu.get(ref)
                rows = block.num_rows
                if rows <= remaining:
                    remaining -= rows
                    yield ref
                else:
                    yield ray_tpu.put(BlockAccessor(block).slice(0, remaining))
                    remaining = 0

        return Dataset(source)

    # ----------------------------------------------------------- consumption
    def _execute(self, collect_rows: bool = False) -> Iterator[ObjectRef]:
        executor = StreamingExecutor(self._stages, collect_rows=collect_rows)
        self._last_executor = executor
        return executor.execute(self._source_fn())

    def iter_internal_refs(self) -> Iterator[ObjectRef]:
        return self._execute()

    def take(self, limit: int = 20) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for ref in self._execute():
            for row in BlockAccessor(ray_tpu.get(ref)).iter_rows():
                out.append(row)
                if len(out) >= limit:
                    return out
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return [r for ref in self._execute() for r in BlockAccessor(ray_tpu.get(ref)).iter_rows()]

    def count(self) -> int:
        return sum(ray_tpu.get(ref).num_rows for ref in self._execute())

    def schema(self):
        for ref in self._execute():
            return ray_tpu.get(ref).schema
        return None

    def materialize(self) -> "Dataset":
        refs = list(self._execute())

        def source() -> Iterator[ObjectRef]:
            return iter(refs)

        return Dataset(source)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for ref in self._execute():
            yield from BlockAccessor(ray_tpu.get(ref)).iter_rows()

    def iter_batches(
        self,
        *,
        batch_size: int = 256,
        batch_format: str = "numpy",
        prefetch_batches: int = 2,
        drop_last: bool = False,
    ) -> Iterator[Batch]:
        return _batch_iterator(self._execute(), batch_size, batch_format,
                               prefetch_batches, drop_last)

    def iter_jax_batches(
        self,
        *,
        batch_size: int = 256,
        prefetch_batches: int = 2,
        drop_last: bool = True,
        sharding=None,
        dtype=None,
    ) -> Iterator[Dict[str, Any]]:
        """Device-side prefetch: batches are transferred to HBM ahead of
        consumption (double-buffering, config.device_prefetch_depth)."""
        import jax

        from ray_tpu.core.config import config

        host_iter = self.iter_batches(
            batch_size=batch_size, batch_format="numpy",
            prefetch_batches=prefetch_batches, drop_last=drop_last,
        )

        def to_device(batch: Dict[str, np.ndarray]):
            out = {}
            for k, v in batch.items():
                arr = v if dtype is None else v.astype(dtype)
                out[k] = jax.device_put(arr, sharding) if sharding is not None else jax.device_put(arr)
            return out

        depth = max(1, config.device_prefetch_depth)
        buf: "_queue.deque" = __import__("collections").deque()
        for batch in host_iter:
            buf.append(to_device(batch))
            if len(buf) >= depth:
                yield buf.popleft()
        while buf:
            yield buf.popleft()

    def streaming_split(self, n: int, *, equal: bool = True) -> List["DataIterator"]:
        """Split into n per-consumer iterators fed round-robin from one
        execution (reference: dataset.py:1363 streaming_split used by Train's
        DataConfig for per-worker shards). Each shard is backed by a queue
        ACTOR so the iterator handle is serializable into train workers."""
        # max_concurrency>1: a consumer blocked in get() must not starve puts
        shards = [_ShardQueue.options(max_concurrency=4).remote() for _ in range(n)]
        parent = self

        def feeder() -> None:
            try:
                for i, ref in enumerate(parent._execute()):
                    # put the BLOCK (values serialize; refs are per-process
                    # futures only in local mode)
                    ray_tpu.get(shards[i % n].put.remote(ray_tpu.get(ref)))
            finally:
                for s in shards:
                    s.close.remote()

        threading.Thread(target=feeder, daemon=True, name="streaming-split").start()
        return [DataIterator(s) for s in shards]

    # ---------------------------------------------------------------- output
    def write_parquet(self, path: str) -> None:
        import os

        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        for i, ref in enumerate(self._execute()):
            pq.write_table(ray_tpu.get(ref), f"{path}/part-{i:05d}.parquet")

    def write_json(self, path: str) -> None:
        import json
        import os

        os.makedirs(path, exist_ok=True)
        for i, ref in enumerate(self._execute()):
            with open(f"{path}/part-{i:05d}.jsonl", "w") as f:
                for row in BlockAccessor(ray_tpu.get(ref)).iter_rows():
                    f.write(json.dumps(row, default=str) + "\n")

    def write_csv(self, path: str) -> None:
        import os

        import pyarrow.csv as pacsv

        os.makedirs(path, exist_ok=True)
        for i, ref in enumerate(self._execute()):
            pacsv.write_csv(ray_tpu.get(ref), f"{path}/part-{i:05d}.csv")

    def stats(self) -> str:
        """Per-stage wall-time/blocks/rows of the LAST execution (runs the
        pipeline with row collection if nothing has executed yet).
        Reference: Dataset.stats() backed by _internal/stats.py."""
        last = getattr(self, "_last_executor", None)
        # blocks_out == 0 everywhere means an execution was CREATED but never
        # consumed (stats are appended eagerly per stage) — run for real
        if last is None or not any(st.blocks_out for st in last.stats):
            for _ in self._execute(collect_rows=True):
                pass
            last = self._last_executor
        return last.summary()

    def __repr__(self) -> str:
        return f"Dataset(num_stages={len(self._stages)})"


@ray_tpu.remote
class _ShardQueue:
    """Bounded block queue between one execution and one consumer; the actor
    handle serializes into train workers (async: puts and gets interleave)."""

    def __init__(self, maxsize: int = 8):
        import asyncio

        self._q = None
        self._maxsize = maxsize

    def _queue(self):
        import asyncio

        if self._q is None:
            self._q = asyncio.Queue(maxsize=self._maxsize)
        return self._q

    async def put(self, block) -> bool:
        await self._queue().put(block)
        return True

    async def close(self) -> bool:
        await self._queue().put(None)
        return True

    async def get(self):
        return await self._queue().get()


class DataIterator:
    """Per-consumer shard handle (reference: data/iterator.py DataIterator).
    Serializable: backed by a _ShardQueue actor."""

    def __init__(self, shard_actor: Any):
        self._shard = shard_actor

    def __reduce__(self):
        return (DataIterator, (self._shard,))

    def _refs(self) -> Iterator[ObjectRef]:
        while True:
            block = ray_tpu.get(self._shard.get.remote())
            if block is None:
                return
            yield ray_tpu.put(block)

    def iter_batches(self, *, batch_size: int = 256, batch_format: str = "numpy",
                     prefetch_batches: int = 2, drop_last: bool = False) -> Iterator[Batch]:
        return _batch_iterator(self._refs(), batch_size, batch_format,
                               prefetch_batches, drop_last)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for ref in self._refs():
            yield from BlockAccessor(ray_tpu.get(ref)).iter_rows()


def _batch_iterator(refs: Iterator[ObjectRef], batch_size: int, batch_format: str,
                    prefetch_batches: int, drop_last: bool) -> Iterator[Batch]:
    """Re-chunk a stream of blocks into fixed-size batches with background
    block prefetch (reference: _internal/block_batching)."""
    out_q: "_queue.Queue" = _queue.Queue(maxsize=max(1, prefetch_batches))
    DONE = object()

    def producer() -> None:
        try:
            carry: Optional[Block] = None
            for ref in refs:
                block = ray_tpu.get(ref)
                if carry is not None:
                    block = concat_blocks([carry, block])
                    carry = None
                offset = 0
                n = block.num_rows
                while n - offset >= batch_size:
                    out_q.put(BlockAccessor(block).slice(offset, offset + batch_size))
                    offset += batch_size
                if offset < n:
                    carry = BlockAccessor(block).slice(offset, n)
            if carry is not None and carry.num_rows and not drop_last:
                out_q.put(carry)
        except BaseException as e:  # noqa: BLE001
            out_q.put(e)
            return
        finally:
            out_q.put(DONE)

    threading.Thread(target=producer, daemon=True, name="batch-prefetch").start()
    while True:
        item = out_q.get()
        if item is DONE:
            return
        if isinstance(item, BaseException):
            raise item
        yield BlockAccessor(item).to_batch(batch_format)
