"""Dataset: lazy, streaming-executed distributed data.

Reference capability: python/ray/data/dataset.py (+ read_api.py,
iterator.py): lazy logical plan built by transformations, compiled by
``ray_tpu.data.execution.planner`` into a physical operator DAG and run by
the pull-based ``execution.StreamingExecutor`` (per-op budgets,
backpressure, per-op stats — see data/execution/DESIGN.md) on
iteration/consumption; per-worker shards via streaming_split;
device-prefetching batch iteration for TPU input pipelines (the host→HBM
double-buffering tier the reference leaves to torch loaders).
"""

from __future__ import annotations

import queue as _queue
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.data.block import Batch, Block, BlockAccessor, block_from_batch, block_from_rows, concat_blocks
from ray_tpu.data.execution.planner import build_physical_plan
from ray_tpu.data.execution.streaming_executor import StreamingExecutor
from ray_tpu.data.executor import (
    AggregateStage,
    LimitStage,
    MapStage,
    RepartitionStage,
    ShuffleStage,
    SortStage,
    Stage,
    ZipStage,
)
from ray_tpu.utils.logging import get_logger

logger = get_logger("data")


class Dataset:
    def __init__(self, source_fn: Any, stages: Optional[List[Stage]] = None):
        # source_fn: callable returning an Iterator[ObjectRef], or a
        # ReadTaskSource (read_api) whose read tasks the executor paces
        self._source_fn = source_fn
        self._stages: List[Stage] = stages or []

    # ------------------------------------------------------------ transforms
    def _with_stage(self, stage: Stage) -> "Dataset":
        return Dataset(self._source_fn, self._stages + [stage])

    def map_batches(
        self,
        fn: Union[Callable[[Batch], Batch], type],
        *,
        batch_format: str = "numpy",
        batch_size: Optional[int] = None,
        num_cpus: float = 1.0,
        concurrency: Optional[int] = None,
        fn_constructor_args: tuple = (),
        **_ignored,
    ) -> "Dataset":
        if isinstance(fn, type):
            cls = fn

            def ctor():
                return cls(*fn_constructor_args)

            def block_fn(block: Block, callable_obj) -> Block:
                batch = BlockAccessor(block).to_batch(batch_format)
                return block_from_batch(callable_obj(batch))

            return self._with_stage(
                MapStage(f"map_batches({cls.__name__})", block_fn,
                         num_cpus=num_cpus, fn_constructor=ctor, concurrency=concurrency)
            )

        def block_fn(block: Block) -> Block:
            batch = BlockAccessor(block).to_batch(batch_format)
            return block_from_batch(fn(batch))

        return self._with_stage(
            MapStage(f"map_batches({getattr(fn, '__name__', 'fn')})", block_fn,
                     num_cpus=num_cpus, concurrency=concurrency)
        )

    def map(self, fn: Callable[[Dict], Dict], num_cpus: float = 1.0) -> "Dataset":
        def block_fn(block: Block) -> Block:
            rows = [fn(r) for r in BlockAccessor(block).iter_rows()]
            return block_from_rows(rows)

        return self._with_stage(MapStage(f"map({getattr(fn, '__name__', 'fn')})", block_fn, num_cpus=num_cpus))

    def flat_map(self, fn: Callable[[Dict], List[Dict]], num_cpus: float = 1.0) -> "Dataset":
        def block_fn(block: Block) -> Block:
            rows: List[Dict] = []
            for r in BlockAccessor(block).iter_rows():
                rows.extend(fn(r))
            return block_from_rows(rows)

        return self._with_stage(MapStage("flat_map", block_fn, num_cpus=num_cpus))

    def filter(self, fn: Callable[[Dict], bool], num_cpus: float = 1.0) -> "Dataset":
        def block_fn(block: Block) -> Block:
            import pyarrow as pa

            mask = pa.array([fn(r) for r in BlockAccessor(block).iter_rows()])
            return block.filter(mask)

        return self._with_stage(MapStage("filter", block_fn, num_cpus=num_cpus))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with_stage(RepartitionStage(num_blocks))

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        return self._with_stage(ShuffleStage(seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Distributed range-partition sort by a column (reference:
        dataset.py Dataset.sort -> planner/exchange/sort_task_spec.py)."""
        return self._with_stage(SortStage(key, descending))

    def groupby(self, key: Union[str, List[str]]) -> "GroupedData":
        """Group rows by key column(s) (reference: Dataset.groupby ->
        grouped_data.py). Aggregations run as a hash exchange with map-side
        combine."""
        keys = [key] if isinstance(key, str) else list(key)
        return GroupedData(self, keys)

    def aggregate(self, *aggs) -> Dict[str, Any]:
        """Global aggregation; returns {agg_name: value} (reference:
        Dataset.aggregate)."""
        out = self._with_stage(AggregateStage([], list(aggs))).take_all()
        return out[0] if out else {}

    def sum(self, on: str):
        from ray_tpu.data.aggregate import Sum

        return self.aggregate(Sum(on)).get(f"sum({on})")

    def min(self, on: str):
        from ray_tpu.data.aggregate import Min

        return self.aggregate(Min(on)).get(f"min({on})")

    def max(self, on: str):
        from ray_tpu.data.aggregate import Max

        return self.aggregate(Max(on)).get(f"max({on})")

    def mean(self, on: str):
        from ray_tpu.data.aggregate import Mean

        return self.aggregate(Mean(on)).get(f"mean({on})")

    def std(self, on: str, ddof: int = 1):
        from ray_tpu.data.aggregate import Std

        return self.aggregate(Std(on, ddof)).get(f"std({on})")

    def unique(self, column: str) -> List[Any]:
        rows = self.groupby(column).count().take_all()
        return sorted(r[column] for r in rows)

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise zip of two datasets with equal row counts (reference:
        Dataset.zip; right-side column-name collisions get a _1 suffix)."""
        return self._with_stage(ZipStage(lambda: other._execute()))

    def union(self, *others: "Dataset") -> "Dataset":
        selves = [self, *others]

        def source() -> Iterator[ObjectRef]:
            for ds in selves:
                yield from ds._execute()

        return Dataset(source)

    def limit(self, n: int) -> "Dataset":
        """First n rows; compiles to a LimitOp that short-circuits upstream
        operators (reads stop submitting once the limit is satisfied)."""
        return self._with_stage(LimitStage(n))

    # ----------------------------------------------------------- consumption
    def _build_executor(self, collect_rows: bool = False,
                        output_split: Optional[int] = None,
                        equal_split: bool = True) -> StreamingExecutor:
        ops = build_physical_plan(self._source_fn, self._stages,
                                  output_split=output_split,
                                  equal_split=equal_split)
        executor = StreamingExecutor(ops, collect_rows=collect_rows)
        self._last_executor = executor
        return executor

    def _execute(self, collect_rows: bool = False) -> Iterator[ObjectRef]:
        executor = self._build_executor(collect_rows=collect_rows)
        return (bundle.ref for bundle in executor.execute())

    def iter_internal_refs(self) -> Iterator[ObjectRef]:
        return self._execute()

    def take(self, limit: int = 20) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for ref in self._execute():
            for row in BlockAccessor(ray_tpu.get(ref)).iter_rows():
                out.append(row)
                if len(out) >= limit:
                    return out
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return [r for ref in self._execute() for r in BlockAccessor(ray_tpu.get(ref)).iter_rows()]

    def count(self) -> int:
        return sum(ray_tpu.get(ref).num_rows for ref in self._execute())

    def schema(self):
        for ref in self._execute():
            return ray_tpu.get(ref).schema
        return None

    def materialize(self) -> "Dataset":
        refs = list(self._execute())

        def source() -> Iterator[ObjectRef]:
            return iter(refs)

        return Dataset(source)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for ref in self._execute():
            yield from BlockAccessor(ray_tpu.get(ref)).iter_rows()

    def iter_batches(
        self,
        *,
        batch_size: int = 256,
        batch_format: str = "numpy",
        prefetch_batches: int = 2,
        drop_last: bool = False,
    ) -> Iterator[Batch]:
        return _batch_iterator(self._execute(), batch_size, batch_format,
                               prefetch_batches, drop_last)

    def iter_jax_batches(
        self,
        *,
        batch_size: int = 256,
        prefetch_batches: int = 2,
        drop_last: bool = True,
        sharding=None,
        dtype=None,
    ) -> Iterator[Dict[str, Any]]:
        """Device-side prefetch: batches are transferred to HBM ahead of
        consumption (double-buffering, config.device_prefetch_depth)."""
        import jax

        from ray_tpu.core.config import config

        host_iter = self.iter_batches(
            batch_size=batch_size, batch_format="numpy",
            prefetch_batches=prefetch_batches, drop_last=drop_last,
        )

        def to_device(batch: Dict[str, np.ndarray]):
            out = {}
            for k, v in batch.items():
                arr = v if dtype is None else v.astype(dtype)
                out[k] = jax.device_put(arr, sharding) if sharding is not None else jax.device_put(arr)
            return out

        depth = max(1, config.device_prefetch_depth)
        buf: "_queue.deque" = __import__("collections").deque()
        for batch in host_iter:
            buf.append(to_device(batch))
            if len(buf) >= depth:
                yield buf.popleft()
        while buf:
            yield buf.popleft()

    def streaming_split(self, n: int, *, equal: bool = True) -> List["DataIterator"]:
        """Split into n per-consumer iterators fed round-robin from one
        execution (reference: dataset.py:1363 streaming_split used by Train's
        DataConfig for per-worker shards). Each shard is backed by a queue
        ACTOR so the iterator handle is serializable into train workers."""
        # max_concurrency>1: a consumer blocked in get() must not starve puts
        shards = [_ShardQueue.options(max_concurrency=4).remote() for _ in range(n)]
        parent = self

        def feeder() -> None:
            try:
                # terminal OutputSplitOp tags each bundle with its consumer
                executor = parent._build_executor(output_split=n,
                                                  equal_split=equal)
                for bundle in executor.execute():
                    # put the BLOCK (values serialize; refs are per-process
                    # futures only in local mode)
                    idx = bundle.output_split_idx or 0
                    ray_tpu.get(shards[idx].put.remote(ray_tpu.get(bundle.ref)))
            finally:
                for s in shards:
                    s.close.remote()

        threading.Thread(target=feeder, daemon=True, name="streaming-split").start()
        return [DataIterator(s) for s in shards]

    # ---------------------------------------------------------------- output
    def write_parquet(self, path: str) -> None:
        import os

        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        for i, ref in enumerate(self._execute()):
            pq.write_table(ray_tpu.get(ref), f"{path}/part-{i:05d}.parquet")

    def write_json(self, path: str) -> None:
        import json
        import os

        os.makedirs(path, exist_ok=True)
        for i, ref in enumerate(self._execute()):
            with open(f"{path}/part-{i:05d}.jsonl", "w") as f:
                for row in BlockAccessor(ray_tpu.get(ref)).iter_rows():
                    f.write(json.dumps(row, default=str) + "\n")

    def write_csv(self, path: str) -> None:
        import os

        import pyarrow.csv as pacsv

        os.makedirs(path, exist_ok=True)
        for i, ref in enumerate(self._execute()):
            pacsv.write_csv(ray_tpu.get(ref), f"{path}/part-{i:05d}.csv")

    def stats(self) -> str:
        """Per-operator blocks/bytes/time/queue metrics of the LAST
        execution (runs the pipeline with row collection if nothing has
        executed yet). Reference: Dataset.stats() backed by
        _internal/stats.py."""
        last = getattr(self, "_last_executor", None)
        # no output anywhere means an execution was CREATED but never
        # consumed — run for real, collecting row counts
        if last is None or not last.any_output_produced():
            for _ in self._execute(collect_rows=True):
                pass
            last = self._last_executor
        return last.summary()

    def stats_rows(self) -> List[Dict[str, Any]]:
        """Structured per-operator stats of the last execution (the rows
        behind ``stats()``; empty if nothing has executed)."""
        last = getattr(self, "_last_executor", None)
        return last.stats_rows() if last is not None else []

    def __repr__(self) -> str:
        return f"Dataset(num_stages={len(self._stages)})"


class GroupedData:
    """Result of Dataset.groupby (reference: data/grouped_data.py)."""

    def __init__(self, ds: Dataset, keys: List[str]):
        self._ds = ds
        self._keys = keys

    def aggregate(self, *aggs) -> Dataset:
        return self._ds._with_stage(AggregateStage(self._keys, list(aggs)))

    def count(self) -> Dataset:
        from ray_tpu.data.aggregate import Count

        return self.aggregate(Count())

    def sum(self, on: str) -> Dataset:
        from ray_tpu.data.aggregate import Sum

        return self.aggregate(Sum(on))

    def min(self, on: str) -> Dataset:
        from ray_tpu.data.aggregate import Min

        return self.aggregate(Min(on))

    def max(self, on: str) -> Dataset:
        from ray_tpu.data.aggregate import Max

        return self.aggregate(Max(on))

    def mean(self, on: str) -> Dataset:
        from ray_tpu.data.aggregate import Mean

        return self.aggregate(Mean(on))

    def std(self, on: str, ddof: int = 1) -> Dataset:
        from ray_tpu.data.aggregate import Std

        return self.aggregate(Std(on, ddof))

    def map_groups(self, fn: Callable[[Dict[str, np.ndarray]], Any]) -> Dataset:
        """Apply fn to each whole group (rows of one key, as a numpy batch);
        fn returns a batch/dict of rows (reference: GroupedData.map_groups).
        Implemented as sort-by-key then per-block group apply — the sort
        exchange guarantees one group never spans two blocks."""
        keys = self._keys
        sorted_ds = self._ds.sort(keys[0])

        def block_fn(block: Block) -> Block:
            import numpy as np

            from ray_tpu.data.block import BlockAccessor, block_from_batch, concat_blocks

            if block.num_rows == 0:
                return block
            acc = BlockAccessor(block)
            batch = acc.to_numpy()
            kcol = batch[keys[0]]
            # group boundaries within the sorted block
            change = np.nonzero(kcol[1:] != kcol[:-1])[0] + 1
            starts = np.concatenate([[0], change])
            ends = np.concatenate([change, [len(kcol)]])
            outs = []
            for s, e in zip(starts, ends):
                sub = {k: v[s:e] for k, v in batch.items()}
                res = fn(sub)
                outs.append(block_from_batch(res))
            return concat_blocks(outs)

        return sorted_ds._with_stage(MapStage("map_groups", block_fn))


@ray_tpu.remote
class _ShardQueue:
    """Bounded block queue between one execution and one consumer; the actor
    handle serializes into train workers (async: puts and gets interleave)."""

    def __init__(self, maxsize: int = 8):
        import asyncio

        self._q = None
        self._maxsize = maxsize

    def _queue(self):
        import asyncio

        if self._q is None:
            self._q = asyncio.Queue(maxsize=self._maxsize)
        return self._q

    async def put(self, block) -> bool:
        await self._queue().put(block)
        return True

    async def close(self) -> bool:
        await self._queue().put(None)
        return True

    async def get(self):
        return await self._queue().get()


class DataIterator:
    """Per-consumer shard handle (reference: data/iterator.py DataIterator).
    Serializable: backed by a _ShardQueue actor."""

    def __init__(self, shard_actor: Any):
        self._shard = shard_actor

    def __reduce__(self):
        return (DataIterator, (self._shard,))

    def _refs(self) -> Iterator[ObjectRef]:
        while True:
            block = ray_tpu.get(self._shard.get.remote())
            if block is None:
                return
            yield ray_tpu.put(block)

    def iter_batches(self, *, batch_size: int = 256, batch_format: str = "numpy",
                     prefetch_batches: int = 2, drop_last: bool = False) -> Iterator[Batch]:
        return _batch_iterator(self._refs(), batch_size, batch_format,
                               prefetch_batches, drop_last)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for ref in self._refs():
            yield from BlockAccessor(ray_tpu.get(ref)).iter_rows()

    def iter_jax_batches(self, *, batch_size: int = 256,
                         prefetch_batches: int = 2, drop_last: bool = True,
                         sharding=None, dtype=None) -> Iterator[Dict[str, Any]]:
        """Device-side prefetch on a streaming_split shard — the per-train-
        worker half of the data->train path (reference: DataIterator.
        iter_torch_batches used by Train via DataConfig)."""
        import jax

        from ray_tpu.core.config import config

        host_iter = self.iter_batches(
            batch_size=batch_size, batch_format="numpy",
            prefetch_batches=prefetch_batches, drop_last=drop_last,
        )

        def to_device(batch):
            out = {}
            for k, v in batch.items():
                arr = v if dtype is None else v.astype(dtype)
                out[k] = (jax.device_put(arr, sharding)
                          if sharding is not None else jax.device_put(arr))
            return out

        depth = max(1, config.device_prefetch_depth)
        buf: "_queue.deque" = __import__("collections").deque()
        for batch in host_iter:
            buf.append(to_device(batch))
            if len(buf) >= depth:
                yield buf.popleft()
        while buf:
            yield buf.popleft()


def _batch_iterator(refs: Iterator[ObjectRef], batch_size: int, batch_format: str,
                    prefetch_batches: int, drop_last: bool) -> Iterator[Batch]:
    """Re-chunk a stream of blocks into fixed-size batches with background
    block prefetch (reference: _internal/block_batching)."""
    out_q: "_queue.Queue" = _queue.Queue(maxsize=max(1, prefetch_batches))
    DONE = object()

    def producer() -> None:
        try:
            carry: Optional[Block] = None
            for ref in refs:
                block = ray_tpu.get(ref)
                if carry is not None:
                    block = concat_blocks([carry, block])
                    carry = None
                offset = 0
                n = block.num_rows
                while n - offset >= batch_size:
                    out_q.put(BlockAccessor(block).slice(offset, offset + batch_size))
                    offset += batch_size
                if offset < n:
                    carry = BlockAccessor(block).slice(offset, n)
            if carry is not None and carry.num_rows and not drop_last:
                out_q.put(carry)
        except BaseException as e:  # noqa: BLE001
            out_q.put(e)
            return
        finally:
            out_q.put(DONE)

    threading.Thread(target=producer, daemon=True, name="batch-prefetch").start()
    while True:
        item = out_q.get()
        if item is DONE:
            return
        if isinstance(item, BaseException):
            raise item
        yield BlockAccessor(item).to_batch(batch_format)
