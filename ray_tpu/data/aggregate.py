"""Aggregation specs for Dataset.groupby / Dataset.aggregate.

Reference capability: python/ray/data/aggregate.py (AggregateFn family:
Count/Sum/Min/Max/Mean/Std) executed by the hash-shuffle aggregate planner
(python/ray/data/_internal/planner/exchange/). Redesign: each aggregate is a
(map-side partial columns, reduce-side merge, finalize) triple evaluated with
pyarrow's native group_by kernels — the combine runs vectorized inside map
tasks, so only tiny partial tables cross the exchange.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

Block = pa.Table


class AggregateFn:
    """One aggregation over a column (or over all rows for Count).

    - ``partial_columns(block)``: named intermediate columns added map-side
    - ``partial_aggs``: pyarrow group_by specs that combine intermediates
      within one partition-map output
    - ``merge_aggs``: specs that merge partials across map outputs
    - ``finalize(table)``: turn merged partials into the final column
    """

    name = "agg"

    def partial_columns(self, block: Block) -> dict:
        return {}

    def partial_aggs(self) -> List[tuple]:
        raise NotImplementedError

    def merge_aggs(self) -> List[tuple]:
        raise NotImplementedError

    def finalize(self, table: pa.Table) -> pa.Array:
        raise NotImplementedError

    def drop_columns(self) -> List[str]:
        """Partial columns to drop from the final table."""
        return []


class Count(AggregateFn):
    def __init__(self):
        self.name = "count()"
        self._c = "__cnt"

    def partial_columns(self, block: Block) -> dict:
        return {self._c: pa.array(np.ones(block.num_rows, dtype=np.int64))}

    def partial_aggs(self) -> List[tuple]:
        return [(self._c, "sum")]

    def merge_aggs(self) -> List[tuple]:
        return [(f"{self._c}_sum", "sum")]

    def finalize(self, table: pa.Table) -> pa.Array:
        return table.column(f"{self._c}_sum_sum").combine_chunks()

    def drop_columns(self) -> List[str]:
        return [f"{self._c}_sum_sum"]


class _SimpleAgg(AggregateFn):
    """sum/min/max: the same kernel at every level."""

    kernel = ""

    def __init__(self, on: str):
        self.on = on
        self.name = f"{self.kernel}({on})"

    def partial_columns(self, block: Block) -> dict:
        return {self.on: block.column(self.on)}

    def partial_aggs(self) -> List[tuple]:
        return [(self.on, self.kernel)]

    def merge_aggs(self) -> List[tuple]:
        return [(f"{self.on}_{self.kernel}", self.kernel)]

    def finalize(self, table: pa.Table) -> pa.Array:
        return table.column(f"{self.on}_{self.kernel}_{self.kernel}").combine_chunks()

    def drop_columns(self) -> List[str]:
        return [f"{self.on}_{self.kernel}_{self.kernel}"]


class Sum(_SimpleAgg):
    kernel = "sum"


class Min(_SimpleAgg):
    kernel = "min"


class Max(_SimpleAgg):
    kernel = "max"


class Mean(AggregateFn):
    def __init__(self, on: str):
        self.on = on
        self.name = f"mean({on})"
        self._s = f"__mean_s_{on}"
        self._c = f"__mean_c_{on}"

    def partial_columns(self, block: Block) -> dict:
        col = block.column(self.on)
        return {
            self._s: col,
            self._c: pc.cast(pc.is_valid(col), pa.int64()),
        }

    def partial_aggs(self) -> List[tuple]:
        return [(self._s, "sum"), (self._c, "sum")]

    def merge_aggs(self) -> List[tuple]:
        return [(f"{self._s}_sum", "sum"), (f"{self._c}_sum", "sum")]

    def finalize(self, table: pa.Table) -> pa.Array:
        s = table.column(f"{self._s}_sum_sum").to_numpy(zero_copy_only=False)
        c = table.column(f"{self._c}_sum_sum").to_numpy(zero_copy_only=False)
        with np.errstate(invalid="ignore", divide="ignore"):
            return pa.array(s / np.maximum(c, 1))

    def drop_columns(self) -> List[str]:
        return [f"{self._s}_sum_sum", f"{self._c}_sum_sum"]


class Std(AggregateFn):
    """Distributed std via (count, sum, sum-of-squares) partials."""

    def __init__(self, on: str, ddof: int = 1):
        self.on = on
        self.ddof = ddof
        self.name = f"std({on})"
        self._s = f"__std_s_{on}"
        self._q = f"__std_q_{on}"
        self._c = f"__std_c_{on}"

    def partial_columns(self, block: Block) -> dict:
        col = block.column(self.on)
        return {
            self._s: col,
            self._q: pc.multiply(col, col),
            self._c: pc.cast(pc.is_valid(col), pa.int64()),
        }

    def partial_aggs(self) -> List[tuple]:
        return [(self._s, "sum"), (self._q, "sum"), (self._c, "sum")]

    def merge_aggs(self) -> List[tuple]:
        return [(f"{self._s}_sum", "sum"), (f"{self._q}_sum", "sum"),
                (f"{self._c}_sum", "sum")]

    def finalize(self, table: pa.Table) -> pa.Array:
        s = table.column(f"{self._s}_sum_sum").to_numpy(zero_copy_only=False).astype(np.float64)
        q = table.column(f"{self._q}_sum_sum").to_numpy(zero_copy_only=False).astype(np.float64)
        c = table.column(f"{self._c}_sum_sum").to_numpy(zero_copy_only=False).astype(np.float64)
        denom = np.maximum(c - self.ddof, 1)
        var = np.maximum((q - s * s / np.maximum(c, 1)) / denom, 0.0)
        return pa.array(np.sqrt(var))

    def drop_columns(self) -> List[str]:
        return [f"{self._s}_sum_sum", f"{self._q}_sum_sum", f"{self._c}_sum_sum"]


def make_partial(block: Block, keys: List[str], aggs: List[AggregateFn]) -> pa.Table:
    """Map-side combine: per-group partials for one input block."""
    cols = {k: block.column(k) for k in keys}
    for agg in aggs:
        cols.update(agg.partial_columns(block))
    specs: List[tuple] = []
    for agg in aggs:
        specs.extend(agg.partial_aggs())
    tbl = pa.table(cols) if cols else block
    if keys:
        return tbl.group_by(keys).aggregate(specs)
    return _global_agg(tbl, specs)


def _global_agg(tbl: pa.Table, specs: List[tuple]) -> pa.Table:
    out = {}
    for col, kernel in specs:
        fn = getattr(pc, kernel)
        out[f"{col}_{kernel}"] = pa.array([fn(tbl.column(col)).as_py()])
    return pa.table(out)


def merge_partials(partials: List[pa.Table], keys: List[str],
                   aggs: List[AggregateFn]) -> pa.Table:
    """Reduce-side merge + finalize for one output partition."""
    non_empty = [p for p in partials if p.num_rows]
    combined = pa.concat_tables(non_empty) if non_empty else partials[0]
    specs: List[tuple] = []
    for agg in aggs:
        specs.extend(agg.merge_aggs())
    if keys:
        merged = combined.group_by(keys).aggregate(specs)
    else:
        merged = _global_agg(combined, specs)
    out_cols: dict = {k: merged.column(k) for k in keys}
    for agg in aggs:
        out_cols[agg.name] = agg.finalize(merged)
    return pa.table(out_cols)
