"""Proactive object broadcast (reference: push_manager.h pushes; the
ray.experimental broadcast-ish utilities). ``broadcast(ref)`` replicates a
sealed object to every alive node (or an explicit node list) via the
agents' binomial push tree — each node uploads at most twice, so an N-node
broadcast completes in ~log2(N) rounds instead of N serial pulls from the
one seeded copy."""

from __future__ import annotations

from typing import List, Optional

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef


def broadcast(ref: ObjectRef, node_ids: Optional[List[str]] = None,
              timeout: float = 600.0) -> int:
    """Replicate ``ref`` to ``node_ids`` (default: every alive node).
    Returns the number of nodes newly holding a copy. Local runtime: no-op
    (single store)."""
    from ray_tpu.core.worker import global_worker

    runtime = global_worker().runtime
    agent = getattr(runtime, "agent", None)
    if agent is None:
        return 0  # in-process runtime: one store, nothing to push
    # make sure the object is local to OUR agent (the tree root)
    ray_tpu.wait([ref], num_returns=1, timeout=timeout)
    if node_ids is None:
        node_ids = [n["NodeID"] for n in runtime.nodes() if n.get("Alive", True)]
    targets = [n for n in node_ids if n != runtime.node_hex]
    if not targets:
        return 0
    agent.call("ensure_local", object_id=ref.id.hex(), timeout_s=timeout,
               timeout=timeout + 5)
    out = agent.call("push_object", object_id=ref.id.hex(), targets=targets,
                     timeout=timeout)
    failed = out.get("failed") or {}
    if failed:
        from ray_tpu.utils.logging import get_logger

        get_logger("broadcast").warning(
            "broadcast of %s missed %d node(s): %s",
            ref.id.hex()[:16], len(failed),
            {k[:8]: v for k, v in failed.items()})
    return int(out.get("pushed", 0))
