"""Mutable-object channels: zero-RPC shared-memory pipes between processes.

Reference capability: python/ray/experimental/channel/shared_memory_channel.py
+ src/ray/core_worker/experimental_mutable_object_manager.h:48 (versioned
WriteAcquire/ReadAcquire over mutable plasma buffers) — the data plane of
compiled DAGs. Redesign: a channel is one shm file holding a 128-byte
control block (C++11 atomics driven by ray_tpu/_native/channel.cc — the
seqlock protocol Python cannot express) plus a payload region. A writer
publishes versioned values; up to 8 readers consume them with per-reader
ack counters, giving the reference's depth-1 lossless queue: write N+1
blocks until every reader acked N.

A pure-Python fallback (struct-packed control words, polling) keeps the
API alive without the native toolchain; aligned 8-byte stores are atomic
on every platform jax runs on, so the fallback is safe if slower.

Channels are NODE-LOCAL (same shm namespace). Cross-node pipelines go
through ``RemoteChannelRelay`` (a tiny actor that forwards versions over
the existing RPC plane) — the analogue of the reference raylet's
HandlePushMutableObject cross-node push.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import struct
import time
import uuid
from dataclasses import dataclass
from typing import Any, List, Optional

from ray_tpu.core import serialization
from ray_tpu.utils.logging import get_logger

logger = get_logger("channel")

_HDR = 128
# control-block layout (must match channel.cc): seq@0, len@8, acks[8]@16,
# closed@80 — all u64 little-endian
_OFF_SEQ, _OFF_LEN, _OFF_ACKS, _OFF_CLOSED = 0, 8, 16, 80


class ChannelError(RuntimeError):
    pass


class ChannelClosed(ChannelError):
    pass


class ChannelTimeout(ChannelError, TimeoutError):
    pass


@dataclass
class ChannelHandle:
    """Serializable address of a channel (pass to actors as a task arg)."""

    path: str
    capacity: int
    num_readers: int
    node_id: str = ""


def _native_lib():
    try:
        from ray_tpu import _native

        return _native.lib() if _native.available() else None
    except Exception:  # noqa: BLE001
        return None


class _PyOps:
    """Fallback seqlock ops over the mapped control block (struct-based)."""

    @staticmethod
    def _get(mm, off):
        return struct.unpack_from("<Q", mm, off)[0]

    @staticmethod
    def _set(mm, off, v):
        struct.pack_into("<Q", mm, off, v)

    @classmethod
    def init(cls, mm):
        mm[:_HDR] = b"\x00" * _HDR

    @classmethod
    def write_acquire(cls, mm, wait_readers, timeout_ms):
        deadline = time.monotonic() + timeout_ms / 1000.0
        seq = cls._get(mm, _OFF_SEQ)
        current = seq // 2
        if wait_readers > 0 and current > 0:
            while True:
                if all(cls._get(mm, _OFF_ACKS + 8 * r) >= current
                       for r in range(min(wait_readers, 8))):
                    break
                if cls._get(mm, _OFF_CLOSED):
                    return -2
                if time.monotonic() > deadline:
                    return -1
                time.sleep(0.00005)
        if cls._get(mm, _OFF_CLOSED):
            return -2
        cls._set(mm, _OFF_SEQ, seq + 1)
        return current + 1

    @classmethod
    def write_release(cls, mm, length):
        cls._set(mm, _OFF_LEN, length)
        cls._set(mm, _OFF_SEQ, cls._get(mm, _OFF_SEQ) + 1)

    @classmethod
    def read_acquire(cls, mm, last_version, timeout_ms):
        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            seq = cls._get(mm, _OFF_SEQ)
            if seq % 2 == 0 and seq // 2 > last_version:
                return seq // 2, cls._get(mm, _OFF_LEN)
            if cls._get(mm, _OFF_CLOSED):
                return -2, 0
            if time.monotonic() > deadline:
                return -1, 0
            time.sleep(0.00005)

    @classmethod
    def read_validate(cls, mm, version):
        seq = cls._get(mm, _OFF_SEQ)
        return seq % 2 == 0 and seq // 2 == version

    @classmethod
    def read_ack(cls, mm, slot, version):
        cls._set(mm, _OFF_ACKS + 8 * slot, version)

    @classmethod
    def close(cls, mm):
        cls._set(mm, _OFF_CLOSED, 1)

    @classmethod
    def is_closed(cls, mm):
        return bool(cls._get(mm, _OFF_CLOSED))


class Channel:
    """Single-writer, N-reader versioned shm channel.

    Create on the writer side with ``Channel.create(...)``; ship
    ``chan.handle`` to readers; each reader opens ``Channel.open(handle,
    reader_slot=i)``.
    """

    def __init__(self, handle: ChannelHandle, create: bool,
                 reader_slot: Optional[int] = None):
        self.handle = handle
        self.reader_slot = reader_slot
        self._last_read = 0
        flags = os.O_RDWR | (os.O_CREAT | os.O_EXCL if create else 0)
        fd = os.open(handle.path, flags, 0o600)
        try:
            if create:
                os.ftruncate(fd, _HDR + handle.capacity)
            self._mm = mmap.mmap(fd, _HDR + handle.capacity)
        finally:
            os.close(fd)
        self._lib = _native_lib()
        if self._lib is not None:
            self._cbuf = ctypes.c_char.from_buffer(self._mm)
            self._base = ctypes.addressof(self._cbuf)
        if create:
            if self._lib is not None:
                self._lib.rtpu_chan_init(self._base)
            else:
                _PyOps.init(self._mm)

    # ------------------------------------------------------------- factory
    @classmethod
    def create(cls, capacity: int = 8 << 20, num_readers: int = 1,
               name: Optional[str] = None) -> "Channel":
        if not 1 <= num_readers <= 8:
            raise ValueError("num_readers must be in [1, 8]")
        path = os.path.join(
            "/dev/shm", name or f"rtpu-chan-{uuid.uuid4().hex[:16]}")
        node_id = ""
        try:
            from ray_tpu.core.worker import global_worker

            w = global_worker()
            node_id = getattr(getattr(w, "runtime", None), "node_hex", "") or ""
        except Exception:  # noqa: BLE001 - outside a runtime
            pass
        h = ChannelHandle(path=path, capacity=capacity,
                          num_readers=num_readers, node_id=node_id)
        return cls(h, create=True)

    @classmethod
    def open(cls, handle: ChannelHandle, reader_slot: int = 0) -> "Channel":
        if not os.path.exists(handle.path):
            raise ChannelError(
                f"channel {handle.path} not on this node"
                + (f" (created on node {handle.node_id[:8]}; use "
                   f"RemoteChannelRelay for cross-node pipelines)"
                   if handle.node_id else "")
            )
        return cls(handle, create=False, reader_slot=reader_slot)

    # -------------------------------------------------------------- writer
    def write(self, value: Any, timeout_s: float = 30.0) -> int:
        """Publish a new version (blocks until all readers acked the
        previous one — depth-1 lossless queue). Returns the version."""
        payload, refs = serialization.pack(value)
        if refs:
            raise ChannelError(
                "ObjectRefs cannot ride a mutable channel (no ownership "
                "transfer); pass plain data or use task args"
            )
        return self.write_bytes(bytes(payload), timeout_s)

    def write_bytes(self, payload: bytes, timeout_s: float = 30.0) -> int:
        if len(payload) > self.handle.capacity:
            raise ChannelError(
                f"payload {len(payload)}B exceeds channel capacity "
                f"{self.handle.capacity}B"
            )
        if self._lib is not None:
            v = self._lib.rtpu_chan_write_acquire(
                self._base, self.handle.num_readers, int(timeout_s * 1000))
        else:
            v = _PyOps.write_acquire(self._mm, self.handle.num_readers,
                                     int(timeout_s * 1000))
        if v == -2:
            raise ChannelClosed("channel closed")
        if v == -1:
            raise ChannelTimeout(
                f"write_acquire: readers did not consume within {timeout_s}s")
        self._mm[_HDR:_HDR + len(payload)] = payload
        if self._lib is not None:
            self._lib.rtpu_chan_write_release(self._base, len(payload))
        else:
            _PyOps.write_release(self._mm, len(payload))
        return int(v)

    # -------------------------------------------------------------- reader
    def read(self, timeout_s: float = 30.0) -> Any:
        version, data = self.read_bytes(timeout_s)
        return serialization.unpack(data, zero_copy=False)

    def read_bytes(self, timeout_s: float = 30.0) -> tuple:
        """Block for the next version after the last one this reader saw.
        Returns (version, bytes). Raises ChannelClosed at end-of-stream."""
        deadline = time.monotonic() + timeout_s
        while True:
            remaining_ms = max(1, int((deadline - time.monotonic()) * 1000))
            if self._lib is not None:
                ln = ctypes.c_uint64()
                v = self._lib.rtpu_chan_read_acquire(
                    self._base, self._last_read, ctypes.byref(ln), remaining_ms)
                length = ln.value
            else:
                v, length = _PyOps.read_acquire(self._mm, self._last_read,
                                                remaining_ms)
            if v == -2:
                raise ChannelClosed("channel closed by writer")
            if v == -1:
                raise ChannelTimeout(f"no new version within {timeout_s}s")
            data = bytes(self._mm[_HDR:_HDR + length])
            ok = (self._lib.rtpu_chan_read_validate(self._base, v)
                  if self._lib is not None
                  else _PyOps.read_validate(self._mm, v))
            if not ok:
                continue  # torn read: writer raced us; retry
            self._last_read = int(v)
            if self.reader_slot is not None:
                if self._lib is not None:
                    self._lib.rtpu_chan_read_ack(self._base, self.reader_slot, v)
                else:
                    _PyOps.read_ack(self._mm, self.reader_slot, int(v))
            return int(v), data

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        """Writer hang-up: readers drain and then see ChannelClosed."""
        try:
            if self._lib is not None:
                self._lib.rtpu_chan_close(self._base)
            else:
                _PyOps.close(self._mm)
        except (ValueError, OSError):
            pass

    def is_closed(self) -> bool:
        if self._lib is not None:
            return bool(self._lib.rtpu_chan_is_closed(self._base))
        return _PyOps.is_closed(self._mm)

    def destroy(self) -> None:
        """Close + release the mapping + unlink the file (creator side)."""
        self.close()
        try:
            if self._lib is not None:
                del self._cbuf  # release the buffer export so mmap can close
            self._mm.close()
        except (BufferError, ValueError):
            pass
        try:
            os.unlink(self.handle.path)
        except OSError:
            pass

    def __reduce__(self):
        raise TypeError(
            "pass chan.handle (ChannelHandle) to other processes, then "
            "Channel.open(handle, reader_slot=...)"
        )
