"""ray_tpu: a TPU-native distributed AI framework.

Capability-equivalent of Ray 2.39 (+ pluggable external scheduling) rebuilt
idiomatically for TPU: a task/actor/object core runtime for host-side
orchestration, with in-program parallelism (DP/FSDP/TP/PP/SP/EP/CP) expressed
as JAX/XLA constructs — pjit shardings over device meshes, XLA collectives
over ICI/DCN, Pallas kernels for the hot ops — instead of NCCL process groups.

Public surface mirrors the reference's `ray.*` top level
(reference: python/ray/__init__.py).
"""

from ray_tpu._version import __version__
from ray_tpu.api import (
    available_resources,
    cancel,
    cluster_resources,
    free,
    get,
    get_actor,
    get_runtime_context,
    init,
    is_initialized,
    kill,
    kv_del,
    kv_get,
    kv_keys,
    kv_put,
    list_named_actors,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)
from ray_tpu.core.actor import ActorClass, ActorHandle, method
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.remote_function import RemoteFunction
from ray_tpu.core.streaming import ObjectRefGenerator
from ray_tpu import exceptions
from ray_tpu.profiling import profile

__all__ = [
    "profile",
    "__version__",
    "ActorClass",
    "ActorHandle",
    "ObjectRef",
    "ObjectRefGenerator",
    "RemoteFunction",
    "available_resources",
    "cancel",
    "cluster_resources",
    "exceptions",
    "free",
    "get",
    "get_actor",
    "get_runtime_context",
    "init",
    "is_initialized",
    "kill",
    "kv_del",
    "kv_get",
    "kv_keys",
    "kv_put",
    "list_named_actors",
    "method",
    "nodes",
    "put",
    "remote",
    "shutdown",
    "wait",
]
