"""Deployment definitions and applications.

Reference capability: python/ray/serve/deployment.py (@serve.deployment
decorator, Deployment.options / .bind) and serve/_private/deployment_state.py
(target state records). A Deployment is a declarative spec; binding it with
constructor args yields an Application that serve.run() materializes through
the controller.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, Optional, Tuple


@dataclass(frozen=True)
class AutoscalingConfig:
    """Queue-depth autoscaling (reference: serve/config.py AutoscalingConfig +
    serve/_private/autoscaling_state.py:262 decision logic)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 2.0
    downscale_delay_s: float = 10.0
    metrics_interval_s: float = 0.5


@dataclass(frozen=True)
class Deployment:
    func_or_class: Any
    name: str
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    autoscaling_config: Optional[AutoscalingConfig] = None
    user_config: Optional[Dict[str, Any]] = None
    health_check_period_s: float = 2.0
    # stream=True: HTTP responses are sent chunked as the callable's
    # generator yields (reference: serve/_private/proxy.py:542 streaming
    # send_request_to_replica); python handles use .options(stream=True)
    stream: bool = False

    def options(self, **kwargs) -> "Deployment":
        if "autoscaling_config" in kwargs and isinstance(kwargs["autoscaling_config"], dict):
            kwargs["autoscaling_config"] = AutoscalingConfig(**kwargs["autoscaling_config"])
        return replace(self, **kwargs)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(deployment=self, init_args=args, init_kwargs=kwargs)

    @property
    def target_replicas(self) -> int:
        if self.autoscaling_config is not None:
            return self.autoscaling_config.min_replicas
        return self.num_replicas


@dataclass(frozen=True)
class Application:
    deployment: Deployment
    init_args: Tuple = ()
    init_kwargs: Dict[str, Any] = field(default_factory=dict)


def deployment(
    _func_or_class: Optional[Callable] = None,
    *,
    name: Optional[str] = None,
    num_replicas: int = 1,
    max_ongoing_requests: int = 8,
    ray_actor_options: Optional[Dict[str, Any]] = None,
    autoscaling_config: Optional[Any] = None,
    user_config: Optional[Dict[str, Any]] = None,
    stream: bool = False,
):
    """@serve.deployment decorator (reference: serve/api.py:deployment)."""

    if isinstance(autoscaling_config, dict):
        autoscaling_config = AutoscalingConfig(**autoscaling_config)

    def wrap(target):
        return Deployment(
            func_or_class=target,
            name=name or getattr(target, "__name__", "deployment"),
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            ray_actor_options=dict(ray_actor_options or {}),
            autoscaling_config=autoscaling_config,
            user_config=user_config,
            stream=stream,
        )

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap
