"""serve public API: run/shutdown/status/get_handle.

Reference capability: serve/api.py (serve.run:565, serve.start,
serve.shutdown, serve.status) — here the controller + proxy are named actors
in the "serve" namespace so every driver/worker in the cluster reaches the
same instance.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import cloudpickle

import ray_tpu
from ray_tpu.serve.controller import ServeController
from ray_tpu.serve.deployment import Application, Deployment
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.proxy import ProxyActor

_CONTROLLER_NAME = "SERVE_CONTROLLER"
_PROXY_NAME = "SERVE_PROXY"
_NAMESPACE = "serve"

_state: Dict[str, Any] = {"controller": None, "proxy": None}


def start(http_host: str = "127.0.0.1", http_port: int = 8000,
          http: bool = True):
    """Idempotently start the serve instance (controller + proxy actors)."""
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    controller = _state.get("controller")
    if controller is None:
        try:
            controller = ray_tpu.get_actor(_CONTROLLER_NAME, namespace=_NAMESPACE)
        except ValueError:
            controller = (
                ray_tpu.remote(ServeController)
                .options(name=_CONTROLLER_NAME, namespace=_NAMESPACE,
                         max_concurrency=32)
                .remote()
            )
        _state["controller"] = controller
    if http and _state.get("proxy") is None:
        try:
            proxy = ray_tpu.get_actor(_PROXY_NAME, namespace=_NAMESPACE)
        except ValueError:
            proxy = (
                ray_tpu.remote(ProxyActor)
                .options(name=_PROXY_NAME, namespace=_NAMESPACE, max_concurrency=8)
                .remote(controller, http_host, http_port)
            )
        _state["proxy"] = proxy
    return controller


def run(app: Application, name: Optional[str] = None, *,
        http: bool = True, http_port: int = 8000,
        wait_for_ready: bool = True, timeout: float = 120.0) -> DeploymentHandle:
    """Deploy an application; returns its handle (reference: serve.run)."""
    if isinstance(app, Deployment):
        app = app.bind()
    controller = start(http_port=http_port, http=http)
    app_name = name or app.deployment.name
    ray_tpu.get(
        controller.deploy.remote(
            app_name,
            cloudpickle.dumps(app.deployment),
            cloudpickle.dumps((app.init_args, app.init_kwargs)),
        ),
        timeout=60,
    )
    if wait_for_ready:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if ray_tpu.get(controller.wait_ready.remote(app_name), timeout=60):
                break
            time.sleep(0.2)
        else:
            raise TimeoutError(f"app '{app_name}' not ready after {timeout}s")
    return DeploymentHandle(controller, app_name)


def get_app_handle(name: str) -> DeploymentHandle:
    controller = start(http=False)
    return DeploymentHandle(controller, name)


def get_deployment_handle(deployment_name: str, app_name: Optional[str] = None) -> DeploymentHandle:
    return get_app_handle(app_name or deployment_name)


def status() -> Dict[str, Any]:
    controller = _state.get("controller")
    if controller is None:
        return {}
    return ray_tpu.get(controller.status.remote(), timeout=30)


def http_address() -> Optional[str]:
    proxy = _state.get("proxy")
    if proxy is None:
        return None
    return ray_tpu.get(proxy.address.remote(), timeout=30)


def delete(name: str) -> None:
    controller = _state.get("controller")
    if controller is not None:
        ray_tpu.get(controller.delete_app.remote(name), timeout=30)


def shutdown() -> None:
    controller = _state.pop("controller", None)
    proxy = _state.pop("proxy", None)
    if controller is not None:
        try:
            ray_tpu.get(controller.shutdown.remote(), timeout=30)
            ray_tpu.kill(controller)
        except Exception:  # noqa: BLE001
            pass
    if proxy is not None:
        try:
            ray_tpu.kill(proxy)
        except Exception:  # noqa: BLE001
            pass
    from ray_tpu.serve import handle as _handle

    _handle._routers.clear()
