"""serve public API: run/shutdown/status/get_handle.

Reference capability: serve/api.py (serve.run:565, serve.start,
serve.shutdown, serve.status) — here the controller + proxy are named actors
in the "serve" namespace so every driver/worker in the cluster reaches the
same instance.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import cloudpickle

import ray_tpu
from ray_tpu.serve.controller import ServeController
from ray_tpu.serve.deployment import Application, Deployment
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.proxy import ProxyActor

_CONTROLLER_NAME = "SERVE_CONTROLLER"
_PROXY_NAME = "SERVE_PROXY"
_NAMESPACE = "serve"

_state: Dict[str, Any] = {"controller": None, "proxy": None}


def start(http_host: str = "127.0.0.1", http_port: int = 8000,
          http: bool = True, proxy_location: str = "head"):
    """Idempotently start the serve instance (controller + proxy actors).

    ``proxy_location``: "head" (one proxy on the starting node) or
    "every_node" — one HTTP proxy pinned to each alive node (reference:
    _private/proxy_state.py per-node ProxyStateManager). With every_node,
    pass http_port=0 for ephemeral ports (required on one-box test
    clusters where every "node" shares the same host)."""
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    controller = _state.get("controller")
    if controller is None:
        try:
            controller = ray_tpu.get_actor(_CONTROLLER_NAME, namespace=_NAMESPACE)
        except ValueError:
            controller = (
                ray_tpu.remote(ServeController)
                .options(name=_CONTROLLER_NAME, namespace=_NAMESPACE,
                         # long-poll listeners each hold a call slot for up
                         # to 30 s; size well above expected router count
                         max_concurrency=128)
                .remote()
            )
        _state["controller"] = controller
    if http and not _state.get("proxies"):
        proxies = []
        if proxy_location == "every_node":
            from ray_tpu.core.resources import NodeAffinitySchedulingStrategy
            from ray_tpu.util import state as _st

            for n in _st.list_nodes():
                if not n.get("Alive"):
                    continue
                name = f"{_PROXY_NAME}:{n['NodeID'][:12]}"
                try:
                    p = ray_tpu.get_actor(name, namespace=_NAMESPACE)
                except ValueError:
                    p = (
                        ray_tpu.remote(ProxyActor)
                        .options(
                            name=name, namespace=_NAMESPACE, max_concurrency=8,
                            scheduling_strategy=NodeAffinitySchedulingStrategy(
                                n["NodeID"]),
                        )
                        .remote(controller, http_host, http_port)
                    )
                proxies.append(p)
        else:
            try:
                p = ray_tpu.get_actor(_PROXY_NAME, namespace=_NAMESPACE)
            except ValueError:
                p = (
                    ray_tpu.remote(ProxyActor)
                    .options(name=_PROXY_NAME, namespace=_NAMESPACE,
                             max_concurrency=8)
                    .remote(controller, http_host, http_port)
                )
            proxies.append(p)
        _state["proxy"] = proxies[0] if proxies else None
        _state["proxies"] = proxies
    return controller


def run(app: Application, name: Optional[str] = None, *,
        http: bool = True, http_port: int = 8000,
        proxy_location: str = "head",
        wait_for_ready: bool = True, timeout: float = 120.0) -> DeploymentHandle:
    """Deploy an application; returns its handle (reference: serve.run)."""
    if isinstance(app, Deployment):
        app = app.bind()
    controller = start(http_port=http_port, http=http,
                       proxy_location=proxy_location)
    app_name = name or app.deployment.name
    ray_tpu.get(
        controller.deploy.remote(
            app_name,
            cloudpickle.dumps(app.deployment),
            cloudpickle.dumps((app.init_args, app.init_kwargs)),
        ),
        timeout=60,
    )
    if wait_for_ready:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if ray_tpu.get(controller.wait_ready.remote(app_name), timeout=60):
                break
            time.sleep(0.2)
        else:
            raise TimeoutError(f"app '{app_name}' not ready after {timeout}s")
    return DeploymentHandle(controller, app_name)


def get_app_handle(name: str) -> DeploymentHandle:
    controller = start(http=False)
    return DeploymentHandle(controller, name)


def get_deployment_handle(deployment_name: str, app_name: Optional[str] = None) -> DeploymentHandle:
    return get_app_handle(app_name or deployment_name)


def status() -> Dict[str, Any]:
    controller = _state.get("controller")
    if controller is None:
        return {}
    return ray_tpu.get(controller.status.remote(), timeout=30)


def http_address() -> Optional[str]:
    proxy = _state.get("proxy")
    if proxy is None:
        return None
    return ray_tpu.get(proxy.address.remote(), timeout=30)


def http_addresses() -> list:
    """Every proxy's address (one per node with proxy_location="every_node")."""
    return [ray_tpu.get(p.address.remote(), timeout=30)
            for p in _state.get("proxies") or []]


def delete(name: str) -> None:
    controller = _state.get("controller")
    if controller is not None:
        ray_tpu.get(controller.delete_app.remote(name), timeout=30)


def shutdown() -> None:
    controller = _state.pop("controller", None)
    _state.pop("proxy", None)
    proxies = _state.pop("proxies", None) or []
    if controller is not None:
        try:
            ray_tpu.get(controller.shutdown.remote(), timeout=30)
            ray_tpu.kill(controller)
        except Exception:  # noqa: BLE001
            pass
    for proxy in proxies:
        try:
            ray_tpu.get(proxy.stop.remote(), timeout=10)  # release the port
        except Exception:  # noqa: BLE001
            pass
        try:
            ray_tpu.kill(proxy)
        except Exception:  # noqa: BLE001
            pass
    from ray_tpu.serve import handle as _handle

    for r in _handle._routers.values():
        r.stop()
    _handle._routers.clear()
