"""HTTP proxy: the ingress data plane.

Reference capability: serve/_private/proxy.py (ProxyActor:446, HTTP entry
:542 — route-prefix matching, request forwarding to replicas via the
replica scheduler, draining). Here: a minimal asyncio HTTP/1.1 server run by
a proxy actor (stdlib only — no starlette in the image); bodies are decoded
by content-type (json -> dict, text -> str, else bytes) and handed to the
deployment's __call__ through the pow-2 router.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Dict, Optional, Tuple

from ray_tpu.utils.logging import get_logger

logger = get_logger("serve.proxy")

_STREAM_DONE = object()
_STREAM_ERR = object()


def _encode_stream_item(item: Any) -> bytes:
    if isinstance(item, bytes):
        return item
    if isinstance(item, str):
        return item.encode()
    try:
        return json.dumps(item).encode() + b"\n"  # ndjson record per item
    except TypeError:
        return (str(item) + "\n").encode()


class ProxyActor:
    """One per serve instance (head node). Routes /app_name/... -> app.

    Two ingress planes on one event loop:
    - HTTP/1.1 (curl-able, json/ndjson) — the reference's uvicorn analogue;
    - native msgpack-RPC (``rpc_address()``) with push-channel streaming —
      the reference's gRPC ingress analogue (serve/_private/grpc_util.py)
      re-based on this framework's own wire protocol; clients use
      serve.rpc_ingress.ServeRpcClient.
    """

    def __init__(self, controller, host: str = "127.0.0.1", port: int = 8000):
        self._controller = controller
        self._host = host
        self._port = port
        self._rpc = None
        self._rpc_addr: Optional[str] = None
        self._routes: Dict[str, Any] = {}  # app -> Router (lazy)
        self._stream_flags: Dict[str, Tuple[bool, float]] = {}  # app -> (stream, ts)
        self._ready = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._http_server: Optional[asyncio.AbstractServer] = None
        self._stopping = False
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="serve-http-proxy")
        self._thread.start()
        self._ready.wait(timeout=30)

    def address(self) -> str:
        return f"http://{self._host}:{self._port}"

    def rpc_address(self) -> Optional[str]:
        """host:port of the msgpack-RPC ingress listener."""
        return self._rpc_addr

    def check_health(self) -> bool:
        return self._ready.is_set()

    def stop(self) -> bool:
        """Close both listeners and stop the server loop. Needed explicitly:
        in the local runtime actors are THREADS, so killing the actor alone
        would leave the HTTP port bound for the life of the process."""
        self._stopping = True
        loop = self._loop
        if loop is None or not loop.is_running():
            return True

        async def _close() -> None:
            # close the SOCKETS, not just the loop: a stopped loop keeps its
            # transports (and the bound ports) alive in this process
            if self._http_server is not None:
                self._http_server.close()
            if self._rpc is not None:
                try:
                    await self._rpc.stop()
                except Exception:  # noqa: BLE001
                    pass
            loop.stop()

        asyncio.run_coroutine_threadsafe(_close(), loop)
        self._thread.join(timeout=5.0)
        return True

    # ------------------------------------------------------------- http core
    def _serve(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def start():
            from ray_tpu.core.rpc import RpcServer

            server = await asyncio.start_server(self._on_conn, self._host, self._port)
            self._http_server = server
            self._port = server.sockets[0].getsockname()[1]
            # RPC ingress rides the same loop; chaos-exempt (data plane)
            self._rpc = RpcServer(self._host, 0, chaos=False)
            self._rpc.register("serve_call", self._serve_call)
            self._rpc.register("serve_stream", self._serve_stream)
            host, rpc_port = await self._rpc.start()
            self._rpc_addr = f"{host}:{rpc_port}"
            self._ready.set()
            async with server:
                await server.serve_forever()

        try:
            self._loop.run_until_complete(start())
        except RuntimeError:
            if not self._stopping:  # deliberate stop() is not a death
                logger.exception("proxy server died")
        except Exception:  # noqa: BLE001
            logger.exception("proxy server died")

    # -------------------------------------------------------- rpc ingress
    async def _serve_call(self, app: str, payload: Any = None,
                          app_method: str = "__call__") -> Any:
        """Unary RPC ingress: payload -> deployment -> msgpack-able result."""
        loop = asyncio.get_event_loop()
        router = await loop.run_in_executor(None, self._router_for, app)
        if router is None:
            raise KeyError(f"no app '{app}'")
        call_args = (payload,) if payload is not None else ()
        return await loop.run_in_executor(
            None, lambda: router.call(app_method, call_args, {}))

    async def _serve_stream(self, app: str, channel: str,
                            payload: Any = None,
                            app_method: str = "__call__") -> bool:
        """Streaming RPC ingress: the CLIENT subscribes to ``channel`` first,
        then calls this; items are pushed as {"item": x}, terminated by
        {"end": true} or {"error": msg}. (The reference's gRPC server-streaming
        analogue over the native push-pubsub plane.)"""
        loop = asyncio.get_event_loop()
        router = await loop.run_in_executor(None, self._router_for, app)
        if router is None:
            raise KeyError(f"no app '{app}'")
        call_args = (payload,) if payload is not None else ()

        def publish(data: Dict[str, Any], timeout: float = 30.0) -> None:
            asyncio.run_coroutine_threadsafe(
                self._rpc.publish(channel, data), loop
            ).result(timeout)

        def pull() -> None:
            try:
                stream = router.call_streaming(app_method, call_args, {})
                try:
                    for item in stream:
                        publish({"item": item})
                    publish({"end": True})
                finally:
                    stream.close()
            except BaseException as e:  # noqa: BLE001 - surfaced in-band
                try:
                    publish({"error": f"{type(e).__name__}: {e}"})
                except Exception:  # noqa: BLE001
                    pass

        threading.Thread(target=pull, daemon=True,
                         name="proxy-rpc-stream").start()
        return True

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:
                    break
                method, path, headers, body = req
                status, payload, ctype = await self._handle(method, path, headers, body)
                keep = headers.get("connection", "").lower() != "close"
                if status == b"STREAM":
                    # payload is an async item queue: chunked transfer so the
                    # client sees items the moment the replica yields them
                    # (reference: proxy.py:542 streaming response path)
                    await self._write_chunked(writer, payload, ctype, keep)
                    if not keep:
                        break
                    continue
                writer.write(
                    b"HTTP/1.1 " + status + b"\r\n"
                    b"Content-Type: " + ctype + b"\r\n"
                    b"Content-Length: " + str(len(payload)).encode() + b"\r\n"
                    + (b"Connection: keep-alive\r\n" if keep else b"Connection: close\r\n")
                    + b"\r\n" + payload
                )
                await writer.drain()
                if not keep:
                    break
        except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError):
            pass
        except Exception:  # noqa: BLE001
            logger.exception("proxy connection error")
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _read_request(self, reader) -> Optional[Tuple[str, str, Dict[str, str], bytes]]:
        line = await reader.readline()
        if not line:
            return None
        parts = line.decode("latin1").strip().split(" ")
        if len(parts) < 2:
            return None
        method, path = parts[0], parts[1]
        headers: Dict[str, str] = {}
        while True:
            h = await reader.readline()
            h = h.decode("latin1").strip()
            if not h:
                break
            if ":" in h:
                k, v = h.split(":", 1)
                headers[k.strip().lower()] = v.strip()
        length = int(headers.get("content-length", 0) or 0)
        body = await reader.readexactly(length) if length else b""
        return method, path, headers, body

    async def _handle(self, method: str, path: str, headers: Dict[str, str],
                      body: bytes) -> Tuple[bytes, bytes, bytes]:
        loop = asyncio.get_event_loop()
        path = path.split("?", 1)[0]
        if path in ("/-/healthz", "/-/routes"):
            if path == "/-/healthz":
                return b"200 OK", b"ok", b"text/plain"
            import ray_tpu

            # controller calls block: keep them off the event-loop thread
            apps = await loop.run_in_executor(
                None,
                lambda: ray_tpu.get(self._controller.list_apps.remote(), timeout=10),
            )
            return b"200 OK", json.dumps({f"/{a}": a for a in apps}).encode(), b"application/json"
        segs = [s for s in path.split("/") if s]
        if not segs:
            return b"404 Not Found", b"no application in path", b"text/plain"
        app = segs[0]
        router = await loop.run_in_executor(None, self._router_for, app)
        if router is None:
            return b"404 Not Found", f"no app '{app}'".encode(), b"text/plain"
        # decode body by content type
        ctype = headers.get("content-type", "")
        arg: Any
        if "json" in ctype and body:
            try:
                arg = json.loads(body)
            except json.JSONDecodeError:
                return b"400 Bad Request", b"invalid json", b"text/plain"
        elif body:
            arg = body.decode() if "text" in ctype else body
        else:
            arg = None
        call_args = (arg,) if arg is not None else ()
        # controller round-trip inside: keep it off the event-loop thread
        app_streams = await loop.run_in_executor(None, self._app_streams, app)
        if app_streams:
            # hand the connection an asyncio item queue fed by a dedicated
            # puller thread (one per stream — the writer itself never parks a
            # shared executor thread between tokens). The writer owns a
            # `closed` event: on client disconnect the puller stops and
            # closes the value stream — running the router's and replica's
            # finally blocks so ongoing-request accounting and the producer's
            # backpressure gate are released, never leaked. A semaphore
            # bounds unconsumed items so a slow client can't buffer a whole
            # LLM response in proxy memory.
            q: "asyncio.Queue" = asyncio.Queue()
            window = threading.Semaphore(64)
            closed = threading.Event()

            def put(item) -> None:
                loop.call_soon_threadsafe(q.put_nowait, item)

            def pull() -> None:
                stream = router.call_streaming("__call__", call_args, {})
                try:
                    for item in stream:
                        while not window.acquire(timeout=0.5):
                            if closed.is_set():
                                return
                        if closed.is_set():
                            return
                        put(item)
                    put(_STREAM_DONE)
                except BaseException as e:  # noqa: BLE001
                    try:
                        put((_STREAM_ERR, e))
                    except Exception:  # noqa: BLE001
                        pass  # proxy loop already gone
                finally:
                    stream.close()

            threading.Thread(target=pull, daemon=True, name="proxy-stream-pull").start()
            return b"STREAM", (q, window, closed), b"application/x-ndjson"
        try:
            result = await loop.run_in_executor(
                # Router.call is actor-handle dispatch, not the RPC plane
                # rtpulint: disable=rpc-drift
                None, lambda: router.call("__call__", call_args, {})
            )
        except Exception as e:  # noqa: BLE001 - surface as 500
            return b"500 Internal Server Error", str(e).encode(), b"text/plain"
        if isinstance(result, bytes):
            return b"200 OK", result, b"application/octet-stream"
        if isinstance(result, str):
            return b"200 OK", result.encode(), b"text/plain"
        try:
            return b"200 OK", json.dumps(result).encode(), b"application/json"
        except TypeError:
            return b"200 OK", str(result).encode(), b"text/plain"

    def _router_for(self, app: str):
        import ray_tpu
        from ray_tpu.serve.router import Router

        r = self._routes.get(app)
        if r is None:
            apps = ray_tpu.get(self._controller.list_apps.remote(), timeout=10)
            if app not in apps:
                return None
            r = Router(self._controller, app)
            self._routes[app] = r
        return r

    def _app_streams(self, app: str) -> bool:
        import time as _time

        cached = self._stream_flags.get(app)
        now = _time.monotonic()
        if cached is not None and now - cached[1] < 2.0:
            return cached[0]
        import ray_tpu

        try:
            meta = ray_tpu.get(self._controller.get_app_meta.remote(app), timeout=10)
        except Exception:  # noqa: BLE001
            return cached[0] if cached else False
        streams = bool(meta and meta.get("stream"))
        # short TTL: a redeploy that flips `stream` takes effect within 2 s
        self._stream_flags[app] = (streams, now)
        return streams

    async def _write_chunked(self, writer: asyncio.StreamWriter, payload,
                             ctype: bytes, keep: bool) -> None:
        """Chunked-transfer response: one HTTP chunk per stream item, flushed
        immediately — tokens reach the client before generation finishes.
        On client disconnect the puller is stopped and its stream closed so
        no thread or replica ongoing-slot leaks."""
        q, window, closed = payload
        try:
            writer.write(
                b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: " + ctype + b"\r\n"
                b"Transfer-Encoding: chunked\r\n"
                + (b"Connection: keep-alive\r\n" if keep else b"Connection: close\r\n")
                + b"\r\n"
            )
            await writer.drain()
            while True:
                item = await q.get()
                if item is _STREAM_DONE:
                    break
                window.release()
                if isinstance(item, tuple) and len(item) == 2 and item[0] is _STREAM_ERR:
                    # mid-stream failure: terminate the chunk stream with an
                    # in-band error record (headers are already sent)
                    data = json.dumps({"error": str(item[1])}).encode() + b"\n"
                    writer.write(hex(len(data))[2:].encode() + b"\r\n" + data + b"\r\n")
                    break
                data = _encode_stream_item(item)
                writer.write(hex(len(data))[2:].encode() + b"\r\n" + data + b"\r\n")
                await writer.drain()
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        finally:
            closed.set()  # puller sees it within its 0.5s acquire window
