"""Replica actor: hosts one copy of a deployment's callable.

Reference capability: serve/_private/replica.py (Replica.__init__:518,
handle_request:533 — user-code execution with ongoing-request accounting,
health checks, graceful shutdown). Runs as a max_concurrency actor; each
request is one actor task. Queue-length accounting backs both the pow-2
router (probe path) and autoscaling (controller scrapes stats).
"""

from __future__ import annotations

import asyncio
import inspect
import threading
import time
from typing import Any, Dict, Optional

from ray_tpu import exceptions as exc


class ReplicaOverloadedError(exc.RayTpuError):
    """Rejected: the replica is at max_ongoing_requests (the router should
    retry on another replica — reference: back-pressure in replica_scheduler)."""


class Replica:
    """Generic replica wrapper. Instantiated as an actor by the controller:
    ``Replica.options(max_concurrency=...).remote(serialized_deployment, ...)``.
    """

    def __init__(self, deployment_def: bytes, init_args: tuple, init_kwargs: dict,
                 replica_id: str = ""):
        import cloudpickle

        dep = cloudpickle.loads(deployment_def)
        self._deployment = dep
        self._replica_id = replica_id
        self._max_ongoing = int(dep.max_ongoing_requests)
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        self._started_at = time.time()
        target = dep.func_or_class
        self._is_function = not inspect.isclass(target)
        if self._is_function:
            # function deployment: the function IS __call__
            self._callable = target
        else:
            self._callable = target(*init_args, **init_kwargs)
        if dep.user_config is not None:
            reconfigure = getattr(self._callable, "reconfigure", None)
            if reconfigure is not None:
                reconfigure(dep.user_config)

    # ------------------------------------------------------------- requests
    def handle_request(self, method: str, args: tuple, kwargs: dict,
                       multiplexed_model_id: str = "") -> Any:
        from ray_tpu.serve.multiplex import (
            _reset_request_model_id, _set_request_model_id,
        )

        with self._lock:
            if self._ongoing >= self._max_ongoing:
                raise ReplicaOverloadedError(
                    f"replica {self._replica_id} at max_ongoing_requests="
                    f"{self._max_ongoing}"
                )
            self._ongoing += 1
            self._total += 1
        mux_token = _set_request_model_id(multiplexed_model_id)
        try:
            if self._is_function:
                if method != "__call__":
                    raise AttributeError(
                        f"function deployment '{self._deployment.name}' only "
                        f"supports __call__, not '{method}'"
                    )
                fn = self._callable
            else:
                fn = getattr(self._callable, method, None)
                if fn is None:
                    raise AttributeError(
                        f"deployment '{self._deployment.name}' has no method '{method}'"
                    )
            result = fn(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = _run_coro(result)
            return result
        finally:
            _reset_request_model_id(mux_token)
            with self._lock:
                self._ongoing -= 1

    def handle_request_streaming(self, method: str, args: tuple, kwargs: dict,
                                 multiplexed_model_id: str = ""):
        """Streaming variant: a generator method, invoked by routers with
        ``num_returns="streaming"`` so each yielded item is sealed and
        consumable before the request finishes (reference:
        serve/_private/proxy.py:542 streaming send_request_to_replica +
        replica.py:533 handle_request_streaming). Non-generator results
        stream as a single item."""
        from ray_tpu.serve.multiplex import (
            _reset_request_model_id, _set_request_model_id,
        )

        with self._lock:
            if self._ongoing >= self._max_ongoing:
                raise ReplicaOverloadedError(
                    f"replica {self._replica_id} at max_ongoing_requests="
                    f"{self._max_ongoing}"
                )
            self._ongoing += 1
            self._total += 1
        mux_token = _set_request_model_id(multiplexed_model_id)
        try:
            if self._is_function:
                fn = self._callable
            else:
                fn = getattr(self._callable, method, None)
                if fn is None:
                    raise AttributeError(
                        f"deployment '{self._deployment.name}' has no method '{method}'"
                    )
            result = fn(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = _run_coro(result)
            if inspect.isgenerator(result):
                yield from result
            elif inspect.isasyncgen(result):
                from ray_tpu.core.streaming import iter_async_gen

                yield from iter_async_gen(result)
            else:
                yield result
        finally:
            _reset_request_model_id(mux_token)
            with self._lock:
                self._ongoing -= 1

    # ---------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "replica_id": self._replica_id,
                "ongoing": self._ongoing,
                "total": self._total,
                "max_ongoing": self._max_ongoing,
                "uptime_s": time.time() - self._started_at,
            }

    def check_health(self) -> bool:
        user_check = getattr(self._callable, "check_health", None)
        if user_check is not None:
            user_check()
        return True

    def reconfigure(self, user_config: Dict[str, Any]) -> bool:
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)
        return True

    def prepare_for_shutdown(self) -> bool:
        """Run user cleanup before the controller kills the worker
        (reference: replica graceful shutdown calls the callable's
        __del__)."""
        fn = getattr(self._callable, "__del__", None)
        if fn is not None:
            try:
                fn()
            except Exception:  # noqa: BLE001 - cleanup must not block kill
                pass
        return True


def _run_coro(coro):
    """Execute a coroutine returned by user code (replica methods run on
    executor threads, so a fresh loop per call is the simple correct thing)."""
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()
