"""Continuous-batched LLM serving engine (the TPU-native Serve flagship).

Reference capability: the reference serves LLMs by orchestrating external
GPU engines (ray.serve.llm -> vLLM); here the engine IS the framework:

- a slotted KV cache in HBM (models/decode.py) — one slot per in-flight
  request, no paging tables needed with a static XLA buffer;
- CONTINUOUS batching: new requests are prefilled into free slots while
  other slots keep decoding — no batch barrier (Orca-style iteration-level
  scheduling);
- prefill is bucketed (prompt padded to the next bucket) so each bucket
  compiles once; decode is one compiled multi-step program (T tokens per
  host round trip — hides dispatch latency, critical over tunneled TPUs);
- per-request metrics: TTFT (first token latency) and decode tok/s, scraped
  by bench_serve.py for the BASELINE req/s + p50 TTFT headline.

``LLMDeployment`` wraps the engine as a serve deployment; requests are
dicts {"tokens": [...], "max_tokens": N} -> {"tokens": [...], "ttft_s": ...}.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.utils.logging import get_logger

logger = get_logger("serve.llm")


@dataclass
class GenRequest:
    tokens: List[int]
    max_tokens: int
    eos_token: Optional[int]
    future: Future
    submitted_at: float = field(default_factory=time.perf_counter)
    ttft_s: Optional[float] = None
    out_tokens: List[int] = field(default_factory=list)
    slot: int = -1
    pending_first: Any = None  # device scalar: first sampled token, unfetched
    # streaming: tokens pushed here as decoded (None sentinel = done)
    stream_q: Optional["queue.Queue"] = None
    streamed: int = 0
    cancelled: bool = False


class LLMEngine:
    """Continuous-batching loop around models/decode.py (dense slots) or
    models/paged_decode.py (paged KV cache).

    Paged mode (default): HBM is committed per REQUEST
    (ceil((prompt+max_tokens)/page_size) pages from a shared pool), not
    per-slot*max_seq — so ``num_slots`` can far exceed what a dense cache
    would fit, and short requests stop paying for max_seq rows. Decode
    attention runs the TPU Pallas paged_attention kernel when head_dim
    tiles the lane register file (128), else a gather fallback."""

    def __init__(self, config, params=None, *, num_slots: int = 8,
                 max_seq_len: Optional[int] = None, decode_chunk: int = 8,
                 temperature: float = 0.0, prefill_buckets: Optional[List[int]] = None,
                 paged: bool = True, page_size: int = 64,
                 total_pages: Optional[int] = None):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.decode import (
            init_kv_cache,
            make_decode_fn,
            make_prefill_fn,
        )
        from ray_tpu.models.llama import llama_init

        self.config = config
        self.num_slots = num_slots
        self.max_seq = max_seq_len or config.max_seq_len
        self.decode_chunk = decode_chunk
        self.params = params if params is not None else llama_init(
            config, jax.random.key(0)
        )
        self.paged = paged
        if paged:
            from ray_tpu.models.paged_decode import (
                PageAllocator,
                init_paged_cache,
                make_paged_decode_fn,
                make_paged_prefill_fn,
            )

            self.page_size = page_size
            self.pages_per_slot = -(-self.max_seq // page_size)
            # default pool: dense-equivalent capacity (+1 trash page) — same
            # worst-case guarantees as the slotted cache. The paging WIN is
            # opting into a smaller pool (or more slots at the same pool):
            # HBM then tracks real demand instead of slots * max_seq
            self.total_pages = total_pages or (
                1 + num_slots * self.pages_per_slot)
            self.allocator = PageAllocator(self.total_pages)
            self.cache = init_paged_cache(config, self.total_pages, page_size)
            self._table = jnp.zeros((num_slots, self.pages_per_slot), jnp.int32)
            self._slot_pages: List[Optional[List[int]]] = [None] * num_slots
            self._prefill = make_paged_prefill_fn(config, page_size)
            self._decode = make_paged_decode_fn(config, decode_chunk,
                                                page_size, temperature)
        else:
            self.cache = init_kv_cache(config, num_slots, self.max_seq)
            self._prefill = make_prefill_fn(config)
            self._decode = make_decode_fn(config, decode_chunk, temperature)
        self.prefill_buckets = sorted({
            min(b, self.max_seq) for b in (prefill_buckets or [128, 512, 2048])
        })
        if paged:
            # buckets must be page multiples so prompt K/V scatter is a
            # clean reshape-scatter
            self.prefill_buckets = sorted({
                -(-b // page_size) * page_size for b in self.prefill_buckets
            })
        self._key = jax.random.key(0)
        # device-side batch state
        self._tokens = jnp.zeros((num_slots,), jnp.int32)
        self._positions = jnp.zeros((num_slots,), jnp.int32)
        self._active = jnp.zeros((num_slots,), bool)
        # host-side state
        self._slots: List[Optional[GenRequest]] = [None] * num_slots
        self._pending: "queue.Queue[GenRequest]" = queue.Queue()
        from collections import deque

        # head-of-line holding area for requests the page pool couldn't fit
        self._admit_backlog: "deque[GenRequest]" = deque()
        self._shutdown = False
        self._jnp = jnp
        self._jax = jax
        self._steps = 0
        self._tokens_out = 0
        self._started = time.perf_counter()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-engine")
        self._thread.start()

    # ----------------------------------------------------------------- API
    def generate(self, tokens: List[int], max_tokens: int = 64,
                 eos_token: Optional[int] = None,
                 timeout: Optional[float] = None) -> Dict[str, Any]:
        """Blocking generate (replica-thread entry). Returns
        {"tokens", "ttft_s", "latency_s"}."""
        if len(tokens) + max_tokens > self.max_seq:
            raise ValueError(
                f"prompt {len(tokens)} + max_tokens {max_tokens} exceeds "
                f"max_seq_len {self.max_seq}"
            )
        req = GenRequest(tokens=list(tokens), max_tokens=max_tokens,
                         eos_token=eos_token, future=Future())
        self._pending.put(req)
        result = req.future.result(timeout=timeout)
        return result

    def generate_stream(self, tokens: List[int], max_tokens: int = 64,
                        eos_token: Optional[int] = None,
                        timeout: Optional[float] = None):
        """Streaming generate: yields {"token": t} the moment each token is
        decoded, then a final {"done": True, "ttft_s", "latency_s",
        "num_tokens"} record. Abandoning the generator cancels the request
        (its slot retires at the next decode step)."""
        if len(tokens) + max_tokens > self.max_seq:
            raise ValueError(
                f"prompt {len(tokens)} + max_tokens {max_tokens} exceeds "
                f"max_seq_len {self.max_seq}"
            )
        req = GenRequest(tokens=list(tokens), max_tokens=max_tokens,
                         eos_token=eos_token, future=Future())
        req.stream_q = queue.Queue()
        self._pending.put(req)
        try:
            while True:
                tok = req.stream_q.get(timeout=timeout)
                if tok is None:
                    break
                yield {"token": tok}
            result = req.future.result(timeout=5.0)
            yield {"done": True, "ttft_s": result["ttft_s"],
                   "latency_s": result["latency_s"],
                   "num_tokens": len(result["tokens"])}
        finally:
            req.cancelled = True  # no-op if already finished

    def stats(self) -> Dict[str, Any]:
        return {
            "slots": self.num_slots,
            "active": sum(r is not None for r in self._slots),
            "queued": self._pending.qsize() + len(self._admit_backlog),
            "decode_steps": self._steps,
            "tokens_generated": self._tokens_out,
            "uptime_s": time.perf_counter() - self._started,
        }

    def stop(self) -> None:
        self._shutdown = True
        # join: a daemon thread still inside a jax dispatch at interpreter
        # shutdown aborts the process (pthread "exception not rethrown")
        self._thread.join(timeout=10)

    # ---------------------------------------------------------------- loop
    def _bucket_for(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        # longer than the largest configured bucket: round up to a 128
        # multiple (one extra compile) rather than silently truncating the
        # prompt — max_seq admission already guaranteed it fits
        bucket = min(self.max_seq, -(-n // 128) * 128)
        if self.paged:
            bucket = -(-bucket // self.page_size) * self.page_size
            bucket = min(bucket, self.pages_per_slot * self.page_size)
        return bucket

    def _admit(self) -> None:
        """Prefill waiting requests into free slots WITHOUT a host sync: the
        first sampled token stays on device and is fetched together with the
        next decode chunk (one round trip per loop iteration — dispatch
        latency over tunneled TPUs would otherwise serialize admissions)."""
        if self.paged:
            self._admit_paged_batched()
            return
        jnp = self._jnp
        while True:
            try:
                free = self._slots.index(None)
            except ValueError:
                return
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                return
            n = len(req.tokens)
            bucket = self._bucket_for(n)
            assert bucket >= n, (bucket, n)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :n] = req.tokens
            logits, self.cache = self._prefill(
                self.params, self.cache, jnp.asarray(padded),
                jnp.int32(free), jnp.int32(min(n, bucket)),
            )
            first = jnp.argmax(logits).astype(jnp.int32)  # device scalar
            req.pending_first = first
            req.slot = free
            self._slots[free] = req
            self._tokens = self._tokens.at[free].set(first)
            self._positions = self._positions.at[free].set(n)
            self._active = self._active.at[free].set(True)

    def _admit_paged_batched(self) -> None:
        """Pull every admissible request, group by prefill bucket, and run
        ONE batched prefill program per group. Every group pads to a FIXED
        batch size (min(8, num_slots)): prefill cost is dominated by the
        per-program dispatch (measured ~130ms flat on tunneled v5e vs
        ~45ms/row of compute), so padding is nearly free while keeping ONE
        compile per bucket."""
        jnp = self._jnp
        free_slots = [i for i, r in enumerate(self._slots) if r is None]
        admitted: List[tuple] = []  # (req, slot, pages, bucket)
        while free_slots:
            if self._admit_backlog:
                req = self._admit_backlog.popleft()
            else:
                try:
                    req = self._pending.get_nowait()
                except queue.Empty:
                    break
            n = len(req.tokens)
            bucket = self._bucket_for(n)
            need = max(bucket // self.page_size,
                       -(-(n + req.max_tokens) // self.page_size))
            if need > self.allocator.total - 1:
                req.future.set_exception(ValueError(
                    f"request needs {need} KV pages but the pool has "
                    f"{self.allocator.total - 1}; raise total_pages or "
                    "lower max_tokens"))
                if req.stream_q is not None:
                    req.stream_q.put(None)
                continue
            pages = self.allocator.alloc(need)
            if pages is None:
                # pool exhausted: hold at the HEAD of the line (not the back
                # of the FIFO) so a big request can't be starved forever by
                # later-arriving small ones grabbing every freed page
                self._admit_backlog.appendleft(req)
                break
            admitted.append((req, free_slots.pop(0), pages, bucket))
        if not admitted:
            return
        by_bucket: Dict[int, List[tuple]] = {}
        for item in admitted:
            by_bucket.setdefault(item[3], []).append(item)
        size = min(8, self.num_slots)
        for bucket, group in by_bucket.items():
            for i in range(0, len(group), size):
                self._prefill_group(group[i:i + size], bucket, size)

    def _prefill_group(self, chunk: List[tuple], bucket: int, size: int) -> None:
        """One batched prefill program for `chunk` (padded to `size` rows;
        pad rows write to the trash page and are discarded)."""
        jnp = self._jnp
        n_pages = bucket // self.page_size
        tokens = np.zeros((size, bucket), np.int32)
        page_arr = np.zeros((size, n_pages), np.int32)  # pad rows -> trash
        lengths = np.ones((size,), np.int32)
        for row, (req, slot, pages, _b) in enumerate(chunk):
            n = len(req.tokens)
            tokens[row, :n] = req.tokens
            page_arr[row] = pages[:n_pages]
            lengths[row] = min(n, bucket)
        logits, self.cache = self._prefill(
            self.params, self.cache, jnp.asarray(tokens),
            jnp.asarray(page_arr), jnp.asarray(lengths),
        )
        firsts = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [size]
        for row, (req, slot, pages, _b) in enumerate(chunk):
            n = len(req.tokens)
            self._slot_pages[slot] = pages
            trow = np.zeros((self.pages_per_slot,), np.int32)
            trow[: len(pages)] = pages
            self._table = self._table.at[slot].set(jnp.asarray(trow))
            first = firsts[row]  # device scalar
            req.pending_first = first
            req.slot = slot
            self._slots[slot] = req
            self._tokens = self._tokens.at[slot].set(first)
            self._positions = self._positions.at[slot].set(n)
            self._active = self._active.at[slot].set(True)

    def _push_stream(self, req: GenRequest) -> None:
        """Forward newly-decoded tokens to a streaming consumer."""
        if req.stream_q is None:
            return
        while req.streamed < len(req.out_tokens):
            req.stream_q.put(req.out_tokens[req.streamed])
            req.streamed += 1

    def _finished(self, req: GenRequest) -> bool:
        if req.cancelled:
            return True
        if len(req.out_tokens) >= req.max_tokens:
            return True
        if req.eos_token is not None and req.out_tokens and \
                req.out_tokens[-1] == req.eos_token:
            return True
        if req.slot >= 0 and len(req.tokens) + len(req.out_tokens) >= self.max_seq:
            return True
        return False

    def _retire(self, slot: int) -> None:
        req = self._slots[slot]
        self._slots[slot] = None
        self._active = self._active.at[slot].set(False)
        if self.paged and self._slot_pages[slot] is not None:
            self.allocator.release(self._slot_pages[slot])
            self._slot_pages[slot] = None
            # table row back to the trash page so the retired slot's frozen
            # decode writes can't touch recycled pages
            self._table = self._table.at[slot].set(0)
        if req is None:
            return
        if req.eos_token is not None and req.eos_token in req.out_tokens:
            req.out_tokens = req.out_tokens[: req.out_tokens.index(req.eos_token) + 1]
        self._tokens_out += len(req.out_tokens)
        self._push_stream(req)
        if req.stream_q is not None:
            req.stream_q.put(None)  # end-of-stream sentinel
        req.future.set_result({
            "tokens": req.out_tokens,
            "ttft_s": req.ttft_s,
            "latency_s": time.perf_counter() - req.submitted_at,
        })

    def _loop(self) -> None:
        jax = self._jax
        while not self._shutdown:
            try:
                self._admit()
                if not any(r is not None for r in self._slots):
                    time.sleep(0.01)  # idle: poll for work (_admit drains FIFO)
                    continue
                self._key, sub = jax.random.split(self._key)
                if self.paged:
                    sampled, last, self._positions, self.cache = self._decode(
                        self.params, self.cache, self._tokens,
                        self._positions, self._active, self._table, sub,
                    )
                else:
                    sampled, last, self._positions, self.cache = self._decode(
                        self.params, self.cache, self._tokens,
                        self._positions, self._active, sub,
                    )
                self._tokens = last
                self._steps += self.decode_chunk
                # ONE host sync per chunk: chunk tokens + any pending first
                # tokens from this round's prefills
                firsts = {slot: req.pending_first
                          for slot, req in enumerate(self._slots)
                          if req is not None and req.pending_first is not None}
                host_tokens, host_firsts = jax.device_get((sampled, firsts))
                now = time.perf_counter()
                for slot, first in host_firsts.items():
                    req = self._slots[slot]
                    if req is None:
                        continue
                    req.pending_first = None
                    req.ttft_s = now - req.submitted_at
                    req.out_tokens.append(int(first))
                    self._push_stream(req)  # first token streams immediately
                for slot, req in enumerate(self._slots):
                    if req is None:
                        continue
                    if self._finished(req):
                        self._retire(slot)
                        continue
                    for t in host_tokens[slot]:
                        req.out_tokens.append(int(t))
                        if self._finished(req):
                            break
                    self._push_stream(req)
                    if self._finished(req):
                        self._retire(slot)
            except Exception:  # noqa: BLE001 - engine loop must survive
                logger.exception("llm engine loop error")
                time.sleep(0.5)


class LLMDeployment:
    """Serve deployment wrapping LLMEngine. Construct via serve.deployment:

        app = serve.deployment(LLMDeployment, name="llm").bind(model="tiny")
        handle = serve.run(app)
        handle.generate.remote({"tokens": [...], "max_tokens": 32}).result()
    """

    def __init__(self, model: str = "tiny", num_slots: int = 8,
                 decode_chunk: int = 8, max_seq_len: Optional[int] = None,
                 temperature: float = 0.0, params=None):
        from ray_tpu.models.llama import LlamaConfig

        factories = {
            "tiny": LlamaConfig.tiny,
            "llama_1b": LlamaConfig.llama_1b,
            "llama3_8b": LlamaConfig.llama3_8b,
        }
        if model not in factories:
            raise ValueError(f"unknown model '{model}'; options: {sorted(factories)}")
        config = factories[model]()
        self.engine = LLMEngine(
            config, params, num_slots=num_slots, decode_chunk=decode_chunk,
            max_seq_len=max_seq_len, temperature=temperature,
        )

    def __call__(self, request: Dict[str, Any]):
        if request.get("stream"):
            return self.generate_stream(request)
        return self.generate(request)

    def generate(self, request: Dict[str, Any]) -> Dict[str, Any]:
        return self.engine.generate(
            tokens=request["tokens"],
            max_tokens=int(request.get("max_tokens", 64)),
            eos_token=request.get("eos_token"),
            timeout=request.get("timeout"),
        )

    def generate_stream(self, request: Dict[str, Any]):
        """Token-streaming generate: yields {"token": t} per decoded token
        then a final {"done": True, ...} record. Route via a stream=True
        deployment (HTTP chunks) or handle.options(stream=True)."""
        return self.engine.generate_stream(
            tokens=request["tokens"],
            max_tokens=int(request.get("max_tokens", 64)),
            eos_token=request.get("eos_token"),
            timeout=request.get("timeout"),
        )

    def engine_stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def __del__(self):
        try:
            self.engine.stop()
        except Exception:  # noqa: BLE001
            pass
