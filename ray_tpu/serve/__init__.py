"""ray_tpu.serve — model/application serving over the actor runtime.

Reference capability: python/ray/serve (controller, proxy, replicas, pow-2
routing, dynamic batching, autoscaling) re-designed TPU-first: the flagship
deployment is a continuous-batched LLM decode engine (serve.llm) with a
slotted KV cache resident in HBM and one compiled step per decode tick.
"""

from ray_tpu.serve.api import (
    delete,
    get_app_handle,
    get_deployment_handle,
    http_address,
    http_addresses,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.deployment import Application, AutoscalingConfig, Deployment, deployment
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.rpc_ingress import ServeRpcClient
from ray_tpu.serve import schema

__all__ = [
    "ServeRpcClient",
    "get_multiplexed_model_id",
    "multiplexed",
    "schema",
    "Application",
    "AutoscalingConfig",
    "Deployment",
    "DeploymentHandle",
    "DeploymentResponse",
    "batch",
    "delete",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "http_address",
    "http_addresses",
    "run",
    "shutdown",
    "start",
    "status",
]
