"""Serve controller: the control plane actor.

Reference capability: serve/_private/controller.py (ServeController:84, the
reconciliation control loop run_control_loop:370) + autoscaling_state.py:262
(queue-depth scaling decisions) + deployment_state.py (target vs running
replica reconciliation). One named actor per serve instance:

- holds the declarative target state {app name -> deployment spec + args}
- reconciles: starts/stops Replica actors to match target counts
- health-checks replicas, replacing dead ones
- autoscales deployments with an AutoscalingConfig on mean ongoing requests
  per replica (scrapes replica stats each tick)
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.utils.logging import get_logger

logger = get_logger("serve.controller")

CONTROL_LOOP_PERIOD_S = 0.5


class ServeController:
    def __init__(self):
        # app -> record
        self._apps: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.RLock()
        self._shutdown = False
        # versioned config bus (reference: serve/long_poll.py LongPollHost):
        # every replica-set change bumps the version and wakes blocked
        # listen_for_change calls — routers get pushed updates instead of
        # polling + probing every replica
        self._version = 1
        self._version_cv = threading.Condition()
        self._loop_thread = threading.Thread(
            target=self._control_loop, daemon=True, name="serve-control-loop"
        )
        self._loop_thread.start()

    def _bump_version(self) -> None:
        with self._version_cv:
            self._version += 1
            self._version_cv.notify_all()

    def listen_for_change(self, app_name: str, known_version: int,
                          timeout_s: float = 30.0) -> Dict[str, Any]:
        """Long-poll: returns as soon as the config version exceeds
        known_version (or at timeout with the current state). Payload is the
        app's live replica set — everything a router needs."""
        with self._version_cv:
            self._version_cv.wait_for(
                lambda: self._version > known_version or self._shutdown,
                timeout=timeout_s,
            )
        with self._lock:
            rec = self._apps.get(app_name)
            return {
                "version": self._version,
                "exists": rec is not None,
                "replicas": list(rec["replicas"]) if rec else [],
            }

    # ------------------------------------------------------------ target API
    def deploy(self, app_name: str, deployment_def: bytes, init_args: bytes) -> bool:
        """Set/replace an application's target state. Replicas are created by
        the control loop (deploy returns once the target is recorded; callers
        poll wait_ready)."""
        dep = cloudpickle.loads(deployment_def)
        stale: List[Any] = []
        with self._lock:
            old = self._apps.get(app_name)
            # code/ctor-args change = a new VERSION: existing replicas run
            # the old code and must be rolled, not reconfigured (reference:
            # deployment_state.py version-change rolling update). num_replicas
            # and user_config changes keep replicas in place.
            code_changed = old is not None and (
                old["init_args"] != init_args
                or old["deployment_def"] != deployment_def
            )
            self._apps[app_name] = {
                "deployment_def": deployment_def,
                "deployment": dep,
                "init_args": init_args,
                "target": dep.target_replicas,
                "replicas": [] if code_changed else (old["replicas"] if old else []),
                "next_replica_idx": old["next_replica_idx"] if old else 0,
                "last_scale_up": 0.0,
                "last_scale_down": 0.0,
                "ongoing_history": [],
            }
            if code_changed:
                stale = list(old["replicas"])
            elif old is not None:
                for r in old["replicas"]:
                    if dep.user_config is not None:
                        try:
                            r.reconfigure.remote(dep.user_config)
                        except Exception:  # noqa: BLE001
                            pass
        # stale replicas left the routing set with the version bump below;
        # drain off-thread so their in-flight requests finish first
        for r in stale:
            threading.Thread(target=self._drain_then_stop, args=(r,),
                             daemon=True, name="serve-drain").start()
        self._bump_version()
        return True

    def delete_app(self, app_name: str) -> bool:
        with self._lock:
            rec = self._apps.pop(app_name, None)
        self._bump_version()
        if rec:
            for r in rec["replicas"]:
                self._stop_replica(r)
        return True

    def get_replicas(self, app_name: str) -> List[Any]:
        with self._lock:
            rec = self._apps.get(app_name)
            return list(rec["replicas"]) if rec else []

    def list_apps(self) -> List[str]:
        with self._lock:
            return list(self._apps)

    def get_app_meta(self, app_name: str) -> Optional[Dict[str, Any]]:
        """Routing-relevant deployment metadata (proxy reads ``stream`` to
        pick buffered vs chunked responses)."""
        with self._lock:
            rec = self._apps.get(app_name)
            if rec is None:
                return None
            dep = rec["deployment"]
            return {
                "name": dep.name,
                "stream": bool(getattr(dep, "stream", False)),
                "max_ongoing_requests": dep.max_ongoing_requests,
            }

    def status(self) -> Dict[str, Any]:
        out = {}
        with self._lock:
            apps = {name: (rec["target"], list(rec["replicas"]))
                    for name, rec in self._apps.items()}
        for name, (target, replicas) in apps.items():
            stats = []
            for r in replicas:
                try:
                    stats.append(ray_tpu.get(r.stats.remote(), timeout=2))
                except Exception:  # noqa: BLE001
                    stats.append({"ongoing": -1})
            out[name] = {
                "target_replicas": target,
                "running_replicas": len(replicas),
                "replica_stats": stats,
            }
        return out

    def wait_ready(self, app_name: str) -> bool:
        """True once at least one replica is alive and answering."""
        with self._lock:
            rec = self._apps.get(app_name)
            replicas = list(rec["replicas"]) if rec else []
        for r in replicas:
            try:
                ray_tpu.get(r.check_health.remote(), timeout=30)
                return True
            except Exception:  # noqa: BLE001
                continue
        return False

    def shutdown(self) -> bool:
        self._shutdown = True
        with self._lock:
            apps = list(self._apps.values())
            self._apps.clear()
        for rec in apps:
            for r in rec["replicas"]:
                self._stop_replica(r)
        return True

    # ---------------------------------------------------------- control loop
    def _control_loop(self) -> None:
        while not self._shutdown:
            time.sleep(CONTROL_LOOP_PERIOD_S)
            try:
                self._reconcile_once()
            except Exception:  # noqa: BLE001 - the loop must never die
                logger.exception("serve control loop error")

    def _reconcile_once(self) -> None:
        self._poll_declarative()
        with self._lock:
            apps = list(self._apps.items())
        for name, rec in apps:
            self._health_check(name, rec)
            self._autoscale(name, rec)
            self._scale_to_target(name, rec)

    def _poll_declarative(self) -> None:
        """Config-bus half of `serve deploy` REST (serve/schema.py): the
        dashboard validates + enqueues configs/rollback flags in GCS KV; the
        controller (a full worker process) applies them here — so the REST
        plane needs no actor plumbing (reference: serve REST -> controller
        deploy flow, schema.py + application_state.py)."""
        import json as _json

        from ray_tpu.serve import schema as _schema

        try:
            raw = ray_tpu.kv_get(_schema.PENDING_KEY)
            if raw:
                ray_tpu.kv_del(_schema.PENDING_KEY)
                _schema.apply_config(_json.loads(raw))
            if ray_tpu.kv_get(_schema.ROLLBACK_KEY):
                ray_tpu.kv_del(_schema.ROLLBACK_KEY)
                _schema.rollback()
        except Exception:  # noqa: BLE001 - the loop must never die
            logger.exception("declarative config apply failed")

    def _health_check(self, name: str, rec: Dict[str, Any]) -> None:
        dead = []
        for r in list(rec["replicas"]):
            try:
                ray_tpu.get(r.check_health.remote(), timeout=10)
            except Exception:  # noqa: BLE001
                dead.append(r)
        if dead:
            with self._lock:
                for r in dead:
                    if r in rec["replicas"]:
                        rec["replicas"].remove(r)
            self._bump_version()
            logger.warning("serve app %s: %d replica(s) failed health check",
                           name, len(dead))

    def _autoscale(self, name: str, rec: Dict[str, Any]) -> None:
        cfg = rec["deployment"].autoscaling_config
        if cfg is None or not rec["replicas"]:
            return
        total_ongoing = 0
        live = 0
        for r in rec["replicas"]:
            try:
                s = ray_tpu.get(r.stats.remote(), timeout=2)
                total_ongoing += s["ongoing"]
                live += 1
            except Exception:  # noqa: BLE001
                continue
        if live == 0:
            return
        desired = max(1, math.ceil(total_ongoing / max(cfg.target_ongoing_requests, 1e-9)))
        desired = min(max(desired, cfg.min_replicas), cfg.max_replicas)
        now = time.monotonic()
        with self._lock:
            current = rec["target"]
            if desired > current and now - rec["last_scale_up"] >= cfg.upscale_delay_s:
                rec["target"] = desired
                rec["last_scale_up"] = now
                logger.info("autoscale %s: %d -> %d (ongoing=%d)",
                            name, current, desired, total_ongoing)
            elif desired < current and now - rec["last_scale_down"] >= cfg.downscale_delay_s:
                rec["target"] = max(desired, current - 1)  # scale down gently
                rec["last_scale_down"] = now
                logger.info("autoscale %s: %d -> %d (ongoing=%d)",
                            name, current, rec["target"], total_ongoing)

    def _scale_to_target(self, name: str, rec: Dict[str, Any]) -> None:
        with self._lock:
            target = rec["target"]
            current = len(rec["replicas"])
        changed = False
        for _ in range(current, target):
            replica = self._start_replica(name, rec)
            if replica is None:
                break
            with self._lock:
                rec["replicas"].append(replica)
            changed = True
        if current > target:
            with self._lock:
                victims = rec["replicas"][target:]
                rec["replicas"] = rec["replicas"][:target]
            changed = True
            # victims left the replica set (and the push below tells every
            # router) BEFORE they stop: drain in the background so no
            # in-flight request is lost (reference: proxy_state.py draining)
            for r in victims:
                threading.Thread(
                    target=self._drain_then_stop, args=(r,),
                    daemon=True, name="serve-drain",
                ).start()
        if changed:
            self._bump_version()

    def _drain_then_stop(self, replica, drain_timeout_s: float = 30.0) -> None:
        deadline = time.monotonic() + drain_timeout_s
        while time.monotonic() < deadline:
            try:
                if ray_tpu.get(replica.stats.remote(), timeout=5)["ongoing"] <= 0:
                    break
            except Exception:  # noqa: BLE001 - already dead: nothing to drain
                break
            time.sleep(0.1)
        self._stop_replica(replica)

    def _start_replica(self, name: str, rec: Dict[str, Any]):
        from ray_tpu.serve.replica import Replica

        dep = rec["deployment"]
        with self._lock:
            idx = rec["next_replica_idx"]
            rec["next_replica_idx"] += 1
        replica_id = f"{name}#{idx}"
        init_args, init_kwargs = cloudpickle.loads(rec["init_args"])
        actor_opts = dict(dep.ray_actor_options)
        actor_opts.setdefault("max_concurrency", max(dep.max_ongoing_requests * 2, 8))
        actor_opts.setdefault("max_restarts", 0)
        try:
            cls = ray_tpu.remote(Replica)
            return cls.options(**actor_opts).remote(
                rec["deployment_def"], init_args, init_kwargs, replica_id
            )
        except Exception:  # noqa: BLE001
            logger.exception("failed to start replica %s", replica_id)
            return None

    def _stop_replica(self, replica) -> None:
        try:
            # wait for user cleanup BEFORE killing (a fire-and-forget would
            # race the kill and never run)
            ray_tpu.get(replica.prepare_for_shutdown.remote(), timeout=15)
        except Exception:  # noqa: BLE001
            pass
        try:
            ray_tpu.kill(replica)
        except Exception:  # noqa: BLE001
            pass
