"""Declarative serve config: schema validation + apply/rollback.

Reference capability: serve/schema.py (ServeDeploySchema — YAML app configs
validated then reconciled by the controller) + the `serve deploy` CLI/REST
flow. Config shape:

```yaml
applications:
  - name: adder                 # unique app name (required)
    import_path: mymod:app      # "<module>:<attr>" -> Application |
                                #   Deployment | zero-arg builder (required)
    num_replicas: 2             # optional overrides applied via .options()
    max_concurrent_requests: 8
    user_config: {...}          # passed to the deployment ctor IF the
                                #   import path yields a bare Deployment
```

Apply paths:
- CLI `serve deploy app.yaml` -> a driver process calls ``apply_config``
  directly (starts the serve instance when absent);
- REST PUT /api/serve/applications -> dashboard validates and enqueues the
  config in GCS KV; the RUNNING controller's reconcile loop picks it up
  (``ServeController._poll_declarative``) — the long-poll config-bus
  pattern, so the dashboard process needs no actor plumbing.

The previous config is retained under ``PREV_KEY`` for one-step rollback
(`serve rollback` / POST /api/serve/rollback).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

CONFIG_KEY = "serve:declarative:current"
PREV_KEY = "serve:declarative:prev"
PENDING_KEY = "serve:declarative:pending"
ROLLBACK_KEY = "serve:declarative:rollback"
STATUS_KEY = "serve:declarative:status"

_APP_FIELDS = {
    "name", "import_path", "num_replicas", "max_concurrent_requests",
    "user_config", "autoscaling", "route_prefix",
}


def validate_config(cfg: Any) -> Dict[str, Any]:
    """Normalize + validate; raises ValueError with a field-path message."""
    if not isinstance(cfg, dict):
        raise ValueError("config root must be a mapping")
    unknown = set(cfg) - {"applications"}
    if unknown:
        raise ValueError(f"unknown top-level fields: {sorted(unknown)}")
    apps = cfg.get("applications")
    if not isinstance(apps, list) or not apps:
        raise ValueError("'applications' must be a non-empty list")
    seen = set()
    out: List[Dict[str, Any]] = []
    for i, app in enumerate(apps):
        where = f"applications[{i}]"
        if not isinstance(app, dict):
            raise ValueError(f"{where} must be a mapping")
        unknown = set(app) - _APP_FIELDS
        if unknown:
            raise ValueError(f"{where}: unknown fields {sorted(unknown)}")
        name = app.get("name")
        if not isinstance(name, str) or not name:
            raise ValueError(f"{where}.name: required non-empty string")
        if name in seen:
            raise ValueError(f"{where}.name: duplicate app name '{name}'")
        seen.add(name)
        ip = app.get("import_path")
        if not isinstance(ip, str) or ":" not in ip:
            raise ValueError(
                f"{where}.import_path: required '<module>:<attr>' string")
        for field, typ in (("num_replicas", int),
                           ("max_concurrent_requests", int)):
            if field in app and (not isinstance(app[field], typ)
                                 or app[field] <= 0):
                raise ValueError(f"{where}.{field}: positive {typ.__name__}")
        if "user_config" in app and not isinstance(app["user_config"], dict):
            raise ValueError(f"{where}.user_config: mapping")
        out.append(dict(app))
    return {"applications": out}


def load_yaml(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        return validate_config(yaml.safe_load(f))


def _import_app(import_path: str):
    import importlib

    module_name, _, attr = import_path.partition(":")
    obj = importlib.import_module(module_name)
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def _build_application(app_cfg: Dict[str, Any]):
    from ray_tpu.serve.deployment import Application, Deployment

    obj = _import_app(app_cfg["import_path"])
    overrides = {k: app_cfg[k] for k in
                 ("num_replicas", "max_concurrent_requests", "autoscaling")
                 if k in app_cfg}
    if isinstance(obj, Application):
        if overrides:
            dep = obj.deployment.options(**overrides)
            obj = Application(deployment=dep, init_args=obj.init_args,
                              init_kwargs=obj.init_kwargs)
        return obj
    if isinstance(obj, Deployment):
        if overrides:
            obj = obj.options(**overrides)
        user_cfg = app_cfg.get("user_config") or {}
        return obj.bind(**user_cfg)
    if callable(obj):  # zero-arg builder
        return _coerce_built(obj(), overrides, app_cfg)
    raise TypeError(
        f"{app_cfg['import_path']} resolved to {type(obj).__name__}; "
        "expected Application, Deployment, or builder callable")


def _coerce_built(obj, overrides, app_cfg):
    from ray_tpu.serve.deployment import Application, Deployment

    if isinstance(obj, Deployment):
        obj = obj.options(**overrides) if overrides else obj
        return obj.bind(**(app_cfg.get("user_config") or {}))
    if isinstance(obj, Application):
        if overrides:
            dep = obj.deployment.options(**overrides)
            obj = Application(deployment=dep, init_args=obj.init_args,
                              init_kwargs=obj.init_kwargs)
        return obj
    raise TypeError(f"builder returned {type(obj).__name__}")


def apply_config(cfg: Dict[str, Any], *, record: bool = True,
                 wait_for_ready: bool = False) -> Dict[str, Any]:
    """Reconcile the serve instance to ``cfg``: deploy every listed app,
    delete declaratively-owned apps that disappeared. Returns a status dict.
    Runs in any process with an initialized ray_tpu runtime.

    Ingress is NOT reconfigured when a serve instance already exists: a
    declarative app deploy must never spawn an HTTP proxy the operator
    disabled (or fight over a port) — only a COLD start via the CLI brings
    up the default HTTP ingress."""
    import ray_tpu
    from ray_tpu import serve

    cfg = validate_config(cfg)
    try:
        ray_tpu.get_actor("SERVE_CONTROLLER", namespace="serve")
        http = False  # running instance: leave its ingress configuration be
    except ValueError:
        http = True  # cold start (CLI serve deploy): default ingress
    prev_raw = ray_tpu.kv_get(CONFIG_KEY)
    prev = json.loads(prev_raw) if prev_raw else None
    deployed, errors = [], {}
    for app_cfg in cfg["applications"]:
        try:
            application = _build_application(app_cfg)
            serve.run(application, name=app_cfg["name"], http=http,
                      wait_for_ready=wait_for_ready)
            deployed.append(app_cfg["name"])
        except Exception as e:  # noqa: BLE001 - per-app isolation
            errors[app_cfg["name"]] = f"{type(e).__name__}: {e}"
    # remove apps the previous declarative config owned but this one dropped
    wanted = {a["name"] for a in cfg["applications"]}
    if prev:
        for app_cfg in prev.get("applications", []):
            if app_cfg["name"] not in wanted:
                try:
                    serve.delete(app_cfg["name"])
                except Exception:  # noqa: BLE001
                    pass
    if record:
        if prev_raw:
            ray_tpu.kv_put(PREV_KEY, prev_raw)
        ray_tpu.kv_put(CONFIG_KEY, json.dumps(cfg).encode())
    status = {"deployed": deployed, "errors": errors}
    ray_tpu.kv_put(STATUS_KEY, json.dumps(status).encode())
    return status


def rollback() -> Dict[str, Any]:
    """Re-apply the previous declarative config (one-step undo)."""
    import ray_tpu

    prev_raw = ray_tpu.kv_get(PREV_KEY)
    if not prev_raw:
        raise ValueError("no previous declarative config to roll back to")
    cur = ray_tpu.kv_get(CONFIG_KEY)
    cfg = json.loads(prev_raw)
    status = apply_config(cfg, record=False)
    # swap: current <- prev, prev <- what was current
    ray_tpu.kv_put(CONFIG_KEY, prev_raw)
    if cur:
        ray_tpu.kv_put(PREV_KEY, cur)
    return status


def current_config() -> Optional[Dict[str, Any]]:
    import ray_tpu

    raw = ray_tpu.kv_get(CONFIG_KEY)
    return json.loads(raw) if raw else None
