"""Native-RPC serve ingress client.

Reference capability: serve's gRPC ingress client surface
(serve/_private/grpc_util.py + generated stubs) — here a thin client for
the proxy's msgpack-RPC listener (ProxyActor.rpc_address()):

    client = ServeRpcClient(proxy_rpc_address)
    out = client.call("myapp", {"x": 1})          # unary
    for tok in client.stream("chat", "prompt"):    # server streaming
        ...

Payloads/results must be msgpack-able (None/bool/int/float/str/bytes/list/
dict) — the same cross-language type universe as the C++ client; richer
types belong on the Python handle API.
"""

from __future__ import annotations

import queue
import uuid
from typing import Any, Iterator, Optional

from ray_tpu.core.rpc import SyncRpcClient


class ServeRpcClient:
    def __init__(self, address: str):
        self._client = SyncRpcClient(address)

    def call(self, app: str, payload: Any = None, *,
             method: str = "__call__", timeout: Optional[float] = 60.0) -> Any:
        return self._client.call("serve_call", app=app, payload=payload,
                                 app_method=method, timeout=timeout)

    def stream(self, app: str, payload: Any = None, *,
               method: str = "__call__",
               item_timeout: float = 60.0) -> Iterator[Any]:
        """Server-streaming call: yields items as the replica produces them.
        Subscribe-then-call ordering guarantees no item is missed."""
        channel = f"serve-stream:{uuid.uuid4().hex}"
        q: "queue.Queue" = queue.Queue()
        self._client.subscribe(channel, q.put)
        try:
            self._client.call("serve_stream", app=app, channel=channel,
                              payload=payload, app_method=method, timeout=60.0)
            while True:
                try:
                    msg = q.get(timeout=item_timeout)
                except queue.Empty:
                    raise TimeoutError(
                        f"stream from app '{app}' produced no item in "
                        f"{item_timeout}s") from None
                if not isinstance(msg, dict):
                    continue
                if msg.get("end"):
                    return
                if "error" in msg:
                    raise RuntimeError(f"stream failed: {msg['error']}")
                yield msg.get("item")
        finally:
            # per-call channel: drop it on both ends or a long-lived client
            # accumulates one dead subscription per stream() call
            self._client.unsubscribe(channel)

    def close(self) -> None:
        self._client.close()
