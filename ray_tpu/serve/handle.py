"""DeploymentHandle: python-level calls into a serve application.

Reference capability: serve/handle.py (DeploymentHandle.remote returning a
DeploymentResponse backed by the replica scheduler). Handles share one Router
per (process, app): pow-2 routing with queue-length estimates.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve.router import Router

_routers: Dict[str, Router] = {}
_routers_lock = threading.Lock()


def _router_for(controller, app_name: str) -> Router:
    with _routers_lock:
        r = _routers.get(app_name)
        if r is None:
            r = Router(controller, app_name)
            _routers[app_name] = r
        return r


class DeploymentResponse:
    """Future-like result of handle.remote(). ``.result()`` resolves (with
    overload retry via the router); ``.ref`` exposes the underlying ObjectRef
    for composition with ray_tpu.get/wait."""

    def __init__(self, router: Router, ref, replica):
        self._router = router
        self._ref = ref
        self._replica = replica
        self._done = False

    @property
    def ref(self):
        return self._ref

    def result(self, timeout: Optional[float] = None) -> Any:
        from ray_tpu.serve.replica import ReplicaOverloadedError

        try:
            value = ray_tpu.get(self._ref, timeout=timeout)
            return value
        except ReplicaOverloadedError:
            # raced an overloaded replica: fall back to the router's
            # retrying call path
            return self._router.call(
                self._method, self._args, self._kwargs, timeout=timeout,
                multiplexed_model_id=getattr(self, "_multiplexed_model_id", ""))
        finally:
            if not self._done:
                self._done = True
                self._router._note(self._replica, -1)


class DeploymentHandle:
    def __init__(self, controller, app_name: str, method: str = "__call__",
                 stream: bool = False, multiplexed_model_id: str = ""):
        self._controller = controller
        self._app = app_name
        self._method = method
        self._stream = stream
        self._multiplexed_model_id = multiplexed_model_id

    def options(self, method_name: Optional[str] = None,
                stream: Optional[bool] = None,
                multiplexed_model_id: Optional[str] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self._controller, self._app, method_name or self._method,
            stream=self._stream if stream is None else stream,
            multiplexed_model_id=(self._multiplexed_model_id
                                  if multiplexed_model_id is None
                                  else multiplexed_model_id),
        )

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self._controller, self._app, name,
                                stream=self._stream,
                                multiplexed_model_id=self._multiplexed_model_id)

    def remote(self, *args, **kwargs):
        router = _router_for(self._controller, self._app)
        if self._stream:
            # generator of VALUES, yielded as the replica produces them
            # (reference: handle.options(stream=True) -> DeploymentResponseGenerator)
            return router.call_streaming(
                self._method, args, kwargs,
                multiplexed_model_id=self._multiplexed_model_id)
        ref, replica = router.route(
            self._method, args, kwargs,
            multiplexed_model_id=self._multiplexed_model_id)
        resp = DeploymentResponse(router, ref, replica)
        resp._method = self._method
        resp._args = args
        resp._kwargs = kwargs
        resp._multiplexed_model_id = self._multiplexed_model_id
        return resp
