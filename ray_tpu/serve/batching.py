"""Dynamic request batching.

Reference capability: python/ray/serve/batching.py (@serve.batch — queue
individual calls, flush as a single list-call when max_batch_size is reached
or batch_wait_timeout_s elapses). Thread-based: replica methods execute on
executor threads, so the flusher is a daemon thread and callers block on
per-item futures.
"""

from __future__ import annotations

import functools
import queue
import threading
from concurrent.futures import Future
from typing import Any, Callable, List, Optional


_init_lock = threading.Lock()  # guards lazy _BatchQueue creation everywhere


class _BatchQueue:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._wait = batch_wait_timeout_s
        self._q: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"batch-{getattr(fn, '__name__', 'fn')}")
        self._thread.start()

    def submit(self, item: Any) -> Any:
        fut: Future = Future()
        self._q.put((item, fut))
        return fut.result()

    def _loop(self) -> None:
        while True:
            item, fut = self._q.get()
            batch = [(item, fut)]
            # fill up to max_batch_size, waiting at most batch_wait_timeout_s
            # from the FIRST item (reference semantics)
            import time

            deadline = time.monotonic() + self._wait
            while len(batch) < self._max:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._q.get(timeout=remaining))
                except queue.Empty:
                    break
            items = [b[0] for b in batch]
            try:
                results = self._fn(items)
                if not isinstance(results, (list, tuple)) or len(results) != len(items):
                    raise TypeError(
                        f"@serve.batch function must return a list of "
                        f"{len(items)} results, got {type(results).__name__}"
                    )
                for (_, f), r in zip(batch, results):
                    f.set_result(r)
            except BaseException as e:  # noqa: BLE001 - propagate to every caller
                for _, f in batch:
                    if not f.done():
                        f.set_exception(e)


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: the wrapped function receives a LIST of requests and must
    return a list of results of the same length. Individual callers invoke it
    with a single request and get their single result."""

    def wrap(fn):
        state_attr = f"__batch_queue_{fn.__name__}__"

        @functools.wraps(fn)
        def method_wrapper(self, request):
            bq = getattr(self, state_attr, None)
            if bq is None:
                # resolve the guard lock via import at CALL time: wrappers are
                # cloudpickled by value with deployments, and any directly
                # referenced lock (closure or global) would be pickled along
                from ray_tpu.serve import batching as _batching

                with _batching._init_lock:
                    bq = getattr(self, state_attr, None)
                    if bq is None:
                        bq = _batching._BatchQueue(
                            functools.partial(fn, self),
                            max_batch_size, batch_wait_timeout_s,
                        )
                        setattr(self, state_attr, bq)
            return bq.submit(request)

        @functools.wraps(fn)
        def func_wrapper(request):
            bq = getattr(func_wrapper, state_attr, None)
            if bq is None:
                from ray_tpu.serve import batching as _batching

                with _batching._init_lock:
                    bq = getattr(func_wrapper, state_attr, None)
                    if bq is None:
                        bq = _batching._BatchQueue(
                            fn, max_batch_size, batch_wait_timeout_s
                        )
                        setattr(func_wrapper, state_attr, bq)
            return bq.submit(request)

        import inspect

        params = list(inspect.signature(fn).parameters)
        if params and params[0] == "self":
            return method_wrapper
        return func_wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
