"""Power-of-two-choices replica router.

Reference capability: serve/_private/replica_scheduler/pow_2_scheduler.py
(PowerOfTwoChoicesReplicaScheduler:52, select via queue-length probing
:352). Per-process router: keeps a cached replica set (refreshed from the
controller), picks two random replicas, routes to the one with the shorter
cached queue, and retries on overload/death with the stale replica evicted.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.utils.logging import get_logger

logger = get_logger("serve.router")


class Router:
    """Routers subscribe to the controller's versioned config bus
    (reference: serve/long_poll.py LongPollClient): a daemon thread blocks
    in listen_for_change and applies pushed replica-set updates — config
    changes propagate in one RPC latency, with no periodic probing of every
    replica (the old 2 s poll + O(replicas) stats storm)."""

    def __init__(self, controller, app_name: str):
        self._controller = controller
        self._app = app_name
        self._replicas: List[Any] = []
        self._queue_len: Dict[Any, int] = {}  # cached estimates per handle
        self._version = 0
        # sticky multiplex routing: model id -> last replica that served it
        # (locality without control traffic; reference tracks exact
        # model->replica maps over long-poll)
        self._model_affinity: Dict[str, Any] = {}
        self._synced = threading.Event()
        self._stopped = False
        self._lock = threading.Lock()
        self._listener = threading.Thread(
            target=self._listen_loop, daemon=True, name=f"router-poll-{app_name}"
        )
        self._listener.start()

    # ---------------------------------------------------------- replica set
    def stop(self) -> None:
        """Stop the long-poll listener (serve.shutdown path)."""
        self._stopped = True

    def _apply(self, update: Dict[str, Any]) -> None:
        with self._lock:
            self._version = update["version"]
            new = list(update["replicas"])
            # keep queue estimates for survivors; new replicas start at 0
            self._queue_len = {r: self._queue_len.get(r, 0) for r in new}
            self._replicas = new
            # purge pins to replicas no longer in the set (scale-down would
            # otherwise leak dead handles in the affinity map forever)
            live = set(map(id, new))
            for mid in [m for m, r in self._model_affinity.items()
                        if id(r) not in live]:
                del self._model_affinity[mid]
        self._synced.set()

    def _listen_loop(self) -> None:
        backoff = 0.1
        while not self._stopped:
            try:
                update = ray_tpu.get(
                    self._controller.listen_for_change.remote(
                        self._app, self._version, timeout_s=30.0
                    ),
                    timeout=45,
                )
                self._apply(update)
                backoff = 0.1
            except Exception:  # noqa: BLE001 - controller restarting/busy
                if self._stopped:
                    return
                time.sleep(backoff)
                backoff = min(backoff * 2, 2.0)

    def _refresh(self, force: bool = False) -> None:
        """Wait for the first pushed config; after an eviction (``force``)
        wait briefly for a fresh push, but don't stall the retry loop — the
        local eviction already removed the dead replica."""
        if force:
            self._synced.clear()
            self._synced.wait(timeout=0.5)
            self._synced.set()  # never wedge future non-force waits
            return
        self._synced.wait(timeout=10.0)

    def _pick(self, model_id: str = "") -> Any:
        """Pow-2: two random candidates, lower cached queue length wins.
        A multiplexed model id prefers its sticky replica while healthy
        (model stays loaded there), falling back to pow-2 + re-pin."""
        with self._lock:
            replicas = list(self._replicas)
            sticky = self._model_affinity.get(model_id) if model_id else None
        if not replicas:
            raise exc.RayTpuError("no replicas available")
        if sticky is not None and sticky in replicas:
            return sticky
        if len(replicas) == 1:
            choice = replicas[0]
        else:
            a, b = random.sample(replicas, 2)
            with self._lock:
                qa = self._queue_len.get(a, 0)
                qb = self._queue_len.get(b, 0)
            choice = a if qa <= qb else b
        if model_id:
            with self._lock:
                self._model_affinity[model_id] = choice
        return choice

    def _note(self, replica, delta: int) -> None:
        with self._lock:
            if replica in self._queue_len:
                self._queue_len[replica] = max(0, self._queue_len.get(replica, 0) + delta)

    def _unpin(self, model_id: str, replica) -> None:
        """Overloaded sticky replica: drop the pin so the retry re-picks by
        pow-2 (and re-pins wherever it lands)."""
        with self._lock:
            if self._model_affinity.get(model_id) is replica:
                del self._model_affinity[model_id]

    def _evict(self, replica) -> None:
        with self._lock:
            if replica in self._replicas:
                self._replicas.remove(replica)
            self._queue_len.pop(replica, None)
            for mid in [m for m, r in self._model_affinity.items()
                        if r is replica]:
                del self._model_affinity[mid]

    # -------------------------------------------------------------- routing
    def route(self, method: str, args: tuple, kwargs: dict,
              max_attempts: int = 10, multiplexed_model_id: str = "") -> Tuple[Any, Any]:
        """Submit to a chosen replica; returns (result ObjectRef, replica)."""
        self._refresh()
        last: Optional[Exception] = None
        for _ in range(max_attempts):
            try:
                replica = self._pick(multiplexed_model_id)
            except exc.RayTpuError as e:
                last = e
                time.sleep(0.2)
                self._refresh(force=True)
                continue
            self._note(replica, +1)
            ref = replica.handle_request.remote(
                method, args, kwargs,
                multiplexed_model_id=multiplexed_model_id)
            return ref, replica
        raise exc.RayTpuError(f"no route for {self._app}.{method}: {last}")

    def route_streaming(self, method: str, args: tuple, kwargs: dict,
                        max_attempts: int = 10, multiplexed_model_id: str = ""):
        """Submit a streaming request; returns (ObjectRefGenerator, replica).
        Items become available as the replica's generator yields."""
        self._refresh()
        last: Optional[Exception] = None
        for _ in range(max_attempts):
            try:
                replica = self._pick(multiplexed_model_id)
            except exc.RayTpuError as e:
                last = e
                time.sleep(0.2)
                self._refresh(force=True)
                continue
            self._note(replica, +1)
            gen = replica.handle_request_streaming.options(
                num_returns="streaming"
            ).remote(method, args, kwargs,
                     multiplexed_model_id=multiplexed_model_id)
            return gen, replica
        raise exc.RayTpuError(f"no route for {self._app}.{method}: {last}")

    def call_streaming(self, method: str, args: tuple, kwargs: dict,
                       multiplexed_model_id: str = ""):
        """Route AND stream VALUES, retrying overload/replica-death on other
        replicas while no item has been delivered yet (after the first item
        the stream is already partially consumed; mid-stream failures
        propagate)."""
        from ray_tpu.serve.replica import ReplicaOverloadedError

        attempts = 0
        while True:
            gen, replica = self.route_streaming(
                method, args, kwargs,
                multiplexed_model_id=multiplexed_model_id)
            it = iter(gen)
            try:
                try:
                    first_ref = next(it)
                except StopIteration:
                    return
                try:
                    first = ray_tpu.get(first_ref)
                except Exception as e:  # noqa: BLE001
                    retryable = (
                        isinstance(e, ReplicaOverloadedError)
                        or "ReplicaOverloadedError" in type(e).__name__
                        or isinstance(e, (exc.ActorDiedError, exc.ActorUnavailableError))
                    )
                    if retryable:
                        if isinstance(e, (exc.ActorDiedError, exc.ActorUnavailableError)):
                            self._evict(replica)
                            self._refresh(force=True)
                        elif multiplexed_model_id:
                            self._unpin(multiplexed_model_id, replica)
                        attempts += 1
                        if attempts > 20:
                            raise
                        time.sleep(min(0.05 * attempts, 0.5))
                        continue
                    raise
                yield first
                for ref in it:
                    yield ray_tpu.get(ref)
                return
            finally:
                self._note(replica, -1)

    def call(self, method: str, args: tuple, kwargs: dict, timeout: Optional[float] = None,
             multiplexed_model_id: str = ""):
        """Route AND resolve, retrying overloads on other replicas
        (the synchronous fast path used by the proxy)."""
        from ray_tpu.serve.replica import ReplicaOverloadedError

        deadline = None if timeout is None else time.monotonic() + timeout
        attempts = 0
        while True:
            ref, replica = self.route(
                method, args, kwargs,
                multiplexed_model_id=multiplexed_model_id)
            try:
                remaining = None if deadline is None else max(0.1, deadline - time.monotonic())
                result = ray_tpu.get(ref, timeout=remaining)
                self._note(replica, -1)
                return result
            except Exception as e:  # noqa: BLE001
                self._note(replica, -1)
                if isinstance(e, ReplicaOverloadedError) or "ReplicaOverloadedError" in str(type(e).__name__):
                    if multiplexed_model_id:
                        self._unpin(multiplexed_model_id, replica)
                    attempts += 1
                    if attempts > 20:
                        raise
                    time.sleep(min(0.05 * attempts, 0.5))
                    continue
                if isinstance(e, (exc.ActorDiedError, exc.ActorUnavailableError)):
                    self._evict(replica)
                    self._refresh(force=True)
                    attempts += 1
                    if attempts > 5:
                        raise
                    continue
                raise
