"""Power-of-two-choices replica router.

Reference capability: serve/_private/replica_scheduler/pow_2_scheduler.py
(PowerOfTwoChoicesReplicaScheduler:52, select via queue-length probing
:352). Per-process router: keeps a cached replica set (refreshed from the
controller), picks two random replicas, routes to the one with the shorter
cached queue, and retries on overload/death with the stale replica evicted.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.utils.logging import get_logger

logger = get_logger("serve.router")

REFRESH_PERIOD_S = 2.0


class Router:
    def __init__(self, controller, app_name: str):
        self._controller = controller
        self._app = app_name
        self._replicas: List[Any] = []
        self._queue_len: Dict[Any, int] = {}  # cached estimates per handle
        self._last_refresh = 0.0
        self._lock = threading.Lock()

    # ---------------------------------------------------------- replica set
    def _refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_refresh < REFRESH_PERIOD_S and self._replicas:
                return
            self._last_refresh = now
        try:
            replicas = ray_tpu.get(
                self._controller.get_replicas.remote(self._app), timeout=10
            )
        except Exception:  # noqa: BLE001 - controller briefly unavailable
            logger.warning("router: replica refresh failed for %s", self._app)
            return
        # probe live queue lengths (corrects drift from fire-and-forget
        # handle submissions whose completion the router never observes)
        probes = [(r, r.stats.remote()) for r in replicas]
        fresh: Dict[Any, int] = {}
        for r, ref in probes:
            try:
                fresh[r] = int(ray_tpu.get(ref, timeout=2)["ongoing"])
            except Exception:  # noqa: BLE001 - dead/slow replica: keep stale
                fresh[r] = self._queue_len.get(r, 0)
        with self._lock:
            self._replicas = list(replicas)
            self._queue_len = fresh

    def _pick(self) -> Any:
        """Pow-2: two random candidates, lower cached queue length wins."""
        with self._lock:
            replicas = list(self._replicas)
        if not replicas:
            raise exc.RayTpuError("no replicas available")
        if len(replicas) == 1:
            return replicas[0]
        a, b = random.sample(replicas, 2)
        with self._lock:
            qa = self._queue_len.get(a, 0)
            qb = self._queue_len.get(b, 0)
        return a if qa <= qb else b

    def _note(self, replica, delta: int) -> None:
        with self._lock:
            if replica in self._queue_len:
                self._queue_len[replica] = max(0, self._queue_len.get(replica, 0) + delta)

    def _evict(self, replica) -> None:
        with self._lock:
            if replica in self._replicas:
                self._replicas.remove(replica)
            self._queue_len.pop(replica, None)

    # -------------------------------------------------------------- routing
    def route(self, method: str, args: tuple, kwargs: dict,
              max_attempts: int = 10) -> Tuple[Any, Any]:
        """Submit to a chosen replica; returns (result ObjectRef, replica)."""
        self._refresh()
        last: Optional[Exception] = None
        for _ in range(max_attempts):
            try:
                replica = self._pick()
            except exc.RayTpuError as e:
                last = e
                time.sleep(0.2)
                self._refresh(force=True)
                continue
            self._note(replica, +1)
            ref = replica.handle_request.remote(method, args, kwargs)
            return ref, replica
        raise exc.RayTpuError(f"no route for {self._app}.{method}: {last}")

    def route_streaming(self, method: str, args: tuple, kwargs: dict,
                        max_attempts: int = 10):
        """Submit a streaming request; returns (ObjectRefGenerator, replica).
        Items become available as the replica's generator yields."""
        self._refresh()
        last: Optional[Exception] = None
        for _ in range(max_attempts):
            try:
                replica = self._pick()
            except exc.RayTpuError as e:
                last = e
                time.sleep(0.2)
                self._refresh(force=True)
                continue
            self._note(replica, +1)
            gen = replica.handle_request_streaming.options(
                num_returns="streaming"
            ).remote(method, args, kwargs)
            return gen, replica
        raise exc.RayTpuError(f"no route for {self._app}.{method}: {last}")

    def call_streaming(self, method: str, args: tuple, kwargs: dict):
        """Route AND stream VALUES, retrying overload/replica-death on other
        replicas while no item has been delivered yet (after the first item
        the stream is already partially consumed; mid-stream failures
        propagate)."""
        from ray_tpu.serve.replica import ReplicaOverloadedError

        attempts = 0
        while True:
            gen, replica = self.route_streaming(method, args, kwargs)
            it = iter(gen)
            try:
                try:
                    first_ref = next(it)
                except StopIteration:
                    return
                try:
                    first = ray_tpu.get(first_ref)
                except Exception as e:  # noqa: BLE001
                    retryable = (
                        isinstance(e, ReplicaOverloadedError)
                        or "ReplicaOverloadedError" in type(e).__name__
                        or isinstance(e, (exc.ActorDiedError, exc.ActorUnavailableError))
                    )
                    if retryable:
                        if isinstance(e, (exc.ActorDiedError, exc.ActorUnavailableError)):
                            self._evict(replica)
                            self._refresh(force=True)
                        attempts += 1
                        if attempts > 20:
                            raise
                        time.sleep(min(0.05 * attempts, 0.5))
                        continue
                    raise
                yield first
                for ref in it:
                    yield ray_tpu.get(ref)
                return
            finally:
                self._note(replica, -1)

    def call(self, method: str, args: tuple, kwargs: dict, timeout: Optional[float] = None):
        """Route AND resolve, retrying overloads on other replicas
        (the synchronous fast path used by the proxy)."""
        from ray_tpu.serve.replica import ReplicaOverloadedError

        deadline = None if timeout is None else time.monotonic() + timeout
        attempts = 0
        while True:
            ref, replica = self.route(method, args, kwargs)
            try:
                remaining = None if deadline is None else max(0.1, deadline - time.monotonic())
                result = ray_tpu.get(ref, timeout=remaining)
                self._note(replica, -1)
                return result
            except Exception as e:  # noqa: BLE001
                self._note(replica, -1)
                if isinstance(e, ReplicaOverloadedError) or "ReplicaOverloadedError" in str(type(e).__name__):
                    attempts += 1
                    if attempts > 20:
                        raise
                    time.sleep(min(0.05 * attempts, 0.5))
                    continue
                if isinstance(e, (exc.ActorDiedError, exc.ActorUnavailableError)):
                    self._evict(replica)
                    self._refresh(force=True)
                    attempts += 1
                    if attempts > 5:
                        raise
                    continue
                raise
