"""Model multiplexing: many models per deployment, LRU-cached per replica.

Reference capability: python/ray/serve/multiplex.py (@serve.multiplexed —
a per-replica _ModelMultiplexWrapper with an async LRU of loaded models,
get_multiplexed_model_id() from request context, and multiplex-aware
routing in the replica scheduler). Redesign:

- ``@multiplexed(max_num_models_per_replica=N)`` wraps a model LOADER
  (method or free function taking model_id). Each replica instance keeps
  its own LRU; eviction calls the model's ``__del__``/``unload()`` if
  present.
- ``get_multiplexed_model_id()`` reads the request's model id (propagated
  by the router/replica around each call).
- Routing is STICKY: the router remembers which replica last served each
  model id and prefers it while healthy (locality without extra control
  traffic); overload/death falls back to pow-2 and re-pins. The reference
  propagates exact model->replica maps over long-poll — a roadmap upgrade
  on the same seam.
"""

from __future__ import annotations

import contextvars
import functools
import inspect
from collections import OrderedDict
from typing import Any, Callable, Optional

_model_id_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "rtpu_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """The model id of the CURRENT request (empty if the caller didn't set
    one via handle.options(multiplexed_model_id=...))."""
    return _model_id_ctx.get()


def _set_request_model_id(model_id: str):
    return _model_id_ctx.set(model_id or "")


def _reset_request_model_id(token) -> None:
    _model_id_ctx.reset(token)


class _ModelCache:
    """Thread-safe LRU with per-model load deduplication: replicas execute
    requests on concurrent threads, so N simultaneous misses for one model
    id must produce ONE loader call (the reference serializes loads for the
    same reason — a large model loaded N times concurrently blows memory)."""

    def __init__(self, capacity: int):
        import threading

        self.capacity = capacity
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._lock = threading.Lock()
        self._loading: dict = {}  # model_id -> threading.Event

    _MISS = object()

    def try_get(self, model_id: str):
        """Cached model, or (_MISS, claim_event): claim_event is None when
        THIS caller claimed the load, else the in-flight loader's event."""
        import threading

        with self._lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id], None
            ev = self._loading.get(model_id)
            if ev is None:
                self._loading[model_id] = threading.Event()
                return self._MISS, None  # claimed: caller must load+store
            return self._MISS, ev  # someone else is loading: wait on ev

    def finish(self, model_id: str, model=_MISS) -> None:
        """Release the claim; store the model if the load succeeded."""
        evicted = []
        with self._lock:
            ev = self._loading.pop(model_id, None)
            if model is not self._MISS:
                self._models[model_id] = model
                self._models.move_to_end(model_id)
                while len(self._models) > self.capacity:
                    evicted.append(self._models.popitem(last=False)[1])
        if ev is not None:
            ev.set()
        for old in evicted:
            unload = getattr(old, "unload", None)
            if callable(unload):
                try:
                    unload()
                except Exception:  # noqa: BLE001 - best-effort eviction
                    pass

    def get_or_load(self, model_id: str, load: Callable[[], Any]):
        while True:
            model, ev = self.try_get(model_id)
            if model is not self._MISS:
                return model
            if ev is not None:
                ev.wait(timeout=600.0)
                continue  # loader finished (or failed): re-check
            try:
                model = load()
            except BaseException:
                self.finish(model_id)  # release claim; waiters re-try
                raise
            self.finish(model_id, model)
            return model

    def ids(self):
        with self._lock:
            return list(self._models)


def multiplexed(max_num_models_per_replica: int = 3) -> Callable:
    """Decorator for a model loader (reference: serve.multiplexed). The
    wrapped loader is called only on cache misses; hits return the
    replica-resident model instantly.

        @serve.deployment
        class ModelServer:
            @multiplexed(max_num_models_per_replica=4)
            async def get_model(self, model_id: str):
                return load_weights(model_id)

            async def __call__(self, request):
                model = await self.get_model(get_multiplexed_model_id())
                ...
    """
    if max_num_models_per_replica < 1:
        raise ValueError("max_num_models_per_replica must be >= 1")

    def deco(loader: Callable) -> Callable:
        cache_attr = f"__rtpu_mux_cache_{loader.__name__}"
        is_async = inspect.iscoroutinefunction(loader)
        takes_self = "self" in inspect.signature(loader).parameters
        # NOTE: a FREE-FUNCTION loader's cache hangs off the decorator, so
        # in the in-process local runtime multiple replicas of the same
        # deployment SHARE it (capacity is per process, not per replica).
        # Cluster replicas are separate processes, where the distinction
        # vanishes. Method loaders (the documented form) are always
        # per-instance.

        def split(args, kwargs):
            if takes_self:
                owner = args[0]
                rest = args[1:]
            else:
                owner = deco
                rest = args
            if rest:
                model_id = rest[0]
            elif "model_id" in kwargs:
                model_id = kwargs["model_id"]
            else:
                raise TypeError(
                    f"{loader.__name__}() needs a model_id (positional or "
                    "model_id= keyword)")
            return owner, str(model_id)

        def cache_of(owner) -> _ModelCache:
            cache = getattr(owner, cache_attr, None)
            if cache is None:
                cache = _ModelCache(max_num_models_per_replica)
                setattr(owner, cache_attr, cache)
            return cache

        if is_async:
            @functools.wraps(loader)
            async def async_wrapper(*args, **kwargs):
                import asyncio

                owner, model_id = split(args, kwargs)
                cache = cache_of(owner)
                while True:
                    model, ev = cache.try_get(model_id)
                    if model is not _ModelCache._MISS:
                        return model
                    if ev is not None:
                        # another thread/coroutine is loading: wait without
                        # blocking this event loop
                        await asyncio.get_event_loop().run_in_executor(
                            None, ev.wait, 600.0)
                        continue
                    try:
                        model = await loader(*args, **kwargs)
                    except BaseException:
                        cache.finish(model_id)
                        raise
                    cache.finish(model_id, model)
                    return model

            async_wrapper.__rtpu_multiplexed__ = True  # type: ignore[attr-defined]
            return async_wrapper

        @functools.wraps(loader)
        def wrapper(*args, **kwargs):
            owner, model_id = split(args, kwargs)
            return cache_of(owner).get_or_load(
                model_id, lambda: loader(*args, **kwargs))

        wrapper.__rtpu_multiplexed__ = True  # type: ignore[attr-defined]
        return wrapper

    return deco
