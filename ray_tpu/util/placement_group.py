"""Placement groups: gang reservation of resource bundles.

Reference capability: python/ray/util/placement_group.py (+ GCS 2-phase
bundle reservation, src/ray/gcs/gcs_server/gcs_placement_group_*). Strategies:

- PACK / STRICT_PACK: co-locate bundles (STRICT_PACK = one node; on TPU this
  maps to "same ICI domain/slice" so collectives never cross DCN).
- SPREAD / STRICT_SPREAD: distribute across nodes.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu.core.ids import PlacementGroupID
from ray_tpu.core.worker import require_worker

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]], strategy: str):
        self.id = pg_id
        self._bundles = bundles
        self._strategy = strategy

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return list(self._bundles)

    @property
    def bundle_count(self) -> int:
        return len(self._bundles)

    @property
    def strategy(self) -> str:
        return self._strategy

    def ready(self, timeout: Optional[float] = None) -> bool:
        """Block until the group is reserved (reference: pg.ready() returns an
        ObjectRef; here it blocks directly — await-style use goes through
        wait_until_ready)."""
        w = require_worker()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if w.runtime.placement_group_ready(self.id, timeout):
                return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.01)

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        return self.ready(timeout=timeout_seconds)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self._bundles, self._strategy))

    def __repr__(self) -> str:
        return f"PlacementGroup(id={self.id.hex()[:16]}, {len(self._bundles)} bundles, {self._strategy})"


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}")
    if not bundles:
        raise ValueError("placement group requires at least one bundle")
    for b in bundles:
        if not b or any(v < 0 for v in b.values()):
            raise ValueError(f"invalid bundle: {b}")
    w = require_worker()
    pg_id = w.runtime.create_placement_group(bundles, strategy, name)
    return PlacementGroup(pg_id, bundles, strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    require_worker().runtime.remove_placement_group(pg.id)


def placement_group_table() -> Dict[str, Dict]:
    w = require_worker()
    table = getattr(w.runtime, "placement_group_table", None)
    return table() if table else {}


def get_current_placement_group() -> Optional[PlacementGroup]:
    # Set for tasks/actors scheduled with capture_child_tasks; local runtime
    # does not propagate it yet.
    return None
