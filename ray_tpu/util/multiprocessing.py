"""multiprocessing.Pool API over the task runtime.

Reference capability: python/ray/util/multiprocessing/pool.py — a drop-in
``Pool`` whose workers are cluster actors, so ``pool.map`` scales past one
machine and survives worker crashes (tasks retry). Supported surface:
apply/apply_async/map/map_async/imap/imap_unordered/starmap + context
manager; initializer/initargs run once per worker actor.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional, Sequence

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool


@ray_tpu.remote
class _PoolWorker:
    def __init__(self, initializer: Optional[Callable] = None,
                 initargs: tuple = ()):
        if initializer is not None:
            initializer(*initargs)

    def run(self, fn: Callable, args: tuple, kwargs: dict):
        return fn(*args, **(kwargs or {}))

    def run_chunk(self, fn: Callable, chunk: List[tuple], star: bool):
        if star:
            return [fn(*args) for args in chunk]
        return [fn(args) for args in chunk]


class AsyncResult:
    def __init__(self, refs: List[Any], unchunk: bool):
        self._refs = refs
        self._unchunk = unchunk

    def get(self, timeout: Optional[float] = None):
        out = ray_tpu.get(self._refs, timeout=timeout)
        if self._unchunk:
            return list(itertools.chain.from_iterable(out))
        return out[0] if len(out) == 1 else out

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        done, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                               timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0.001)
            return True
        except Exception:  # noqa: BLE001
            return False


class Pool:
    """Actor-backed process pool (reference: ray.util.multiprocessing.Pool)."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = ()):
        if processes is None:
            try:
                processes = max(2, int(ray_tpu.cluster_resources().get("CPU", 2)))
            except Exception:  # noqa: BLE001
                processes = 2
        processes = max(1, processes)
        self._workers = [
            _PoolWorker.remote(initializer, initargs) for _ in range(processes)
        ]
        self._pool = ActorPool(self._workers)
        self._closed = False
        self._rr = itertools.count()  # round-robin cursor for apply_async

    # ------------------------------------------------------------- lifecycle
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass

    def join(self) -> None:
        assert self._closed, "close() the pool before join()"

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()

    # ------------------------------------------------------------------ api
    def _check(self) -> None:
        if self._closed:
            raise ValueError("Pool not running")

    def apply(self, fn: Callable, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: Optional[dict] = None) -> AsyncResult:
        self._check()
        w = self._workers[next(self._rr) % len(self._workers)]
        return AsyncResult([w.run.remote(fn, args, kwds or {})], unchunk=False)

    def _chunks(self, iterable: Iterable, chunksize: Optional[int]) -> List[List]:
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (len(self._workers) * 4) or 1)
        return [items[i:i + chunksize] for i in range(0, len(items), chunksize)]

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        self._check()
        chunks = self._chunks(iterable, chunksize)
        refs = [
            self._workers[i % len(self._workers)].run_chunk.remote(
                fn, chunk, False)
            for i, chunk in enumerate(chunks)
        ]
        return AsyncResult(refs, unchunk=True)

    def starmap(self, fn: Callable, iterable: Iterable[Sequence],
                chunksize: Optional[int] = None) -> List[Any]:
        self._check()
        chunks = self._chunks(iterable, chunksize)
        refs = [
            self._workers[i % len(self._workers)].run_chunk.remote(
                fn, chunk, True)
            for i, chunk in enumerate(chunks)
        ]
        return AsyncResult(refs, unchunk=True).get()

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: int = 1):
        """Ordered lazy iterator (reference: pool.imap)."""
        self._check()
        for v in self._pool.map(
                lambda a, item: a.run.remote(fn, (item,), {}), list(iterable)):
            yield v

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: int = 1):
        self._check()
        for v in self._pool.map_unordered(
                lambda a, item: a.run.remote(fn, (item,), {}), list(iterable)):
            yield v
