"""Cluster state API: list/inspect nodes, actors, objects, tasks, jobs, logs.

Reference capability: python/ray/util/state/api.py (list_nodes/actors/
objects/tasks, get_log:1168) — there backed by the dashboard's state head;
here the client aggregates straight from the GCS + node agents (no separate
observability service to run).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ray_tpu.core.rpc import SyncRpcClient
from ray_tpu.core.worker import require_worker


def _gcs() -> SyncRpcClient:
    w = require_worker()
    gcs = getattr(w.runtime, "gcs", None)
    if gcs is None:
        raise RuntimeError(
            "the state API requires a cluster runtime "
            "(ray_tpu.init(address=...)); the in-process backend has no GCS"
        )
    return gcs


def _agents() -> List[Dict[str, Any]]:
    return [n for n in _gcs().call("get_nodes") if n["Alive"]]


def list_nodes() -> List[Dict[str, Any]]:
    return _gcs().call("get_nodes")


def list_actors() -> List[Dict[str, Any]]:
    return _gcs().call("list_actors")


def list_objects(limit: int = 1000) -> List[Dict[str, Any]]:
    return _gcs().call("list_objects", limit=limit)


def list_placement_groups() -> Dict[str, Dict[str, Any]]:
    return _gcs().call("placement_group_table")


def list_tasks() -> List[Dict[str, Any]]:
    """Per-task lifecycle states aggregated from every node agent."""
    out: List[Dict[str, Any]] = []
    for node in _agents():
        client = SyncRpcClient(node["NodeManagerAddress"])
        try:
            for task_id, state in client.call("task_states").items():
                out.append({"task_id": task_id, "state": state,
                            "node_id": node["NodeID"]})
        except Exception:  # noqa: BLE001 - a dying node must not break listing
            continue
        finally:
            client.close()
    return out


def list_jobs() -> List[Dict[str, Any]]:
    from ray_tpu.job.sdk import list_jobs_from_gcs

    return list_jobs_from_gcs(_gcs())


def cluster_summary() -> Dict[str, Any]:
    gcs = _gcs()
    return {
        "debug": gcs.call("debug_state"),
        "nodes": len([n for n in gcs.call("get_nodes") if n["Alive"]]),
        "resources_total": gcs.call("cluster_resources"),
        "resources_available": gcs.call("available_resources"),
    }


def _agent_for(node_id: Optional[str]) -> Optional[str]:
    for n in _agents():
        if node_id is None or n["NodeID"] == node_id:
            return n["NodeManagerAddress"]
    return None


def get_log(filename: str, node_id: Optional[str] = None,
            tail_bytes: int = 65536) -> bytes:
    addr = _agent_for(node_id)
    if addr is None:
        raise ValueError(f"no alive node {node_id}")
    client = SyncRpcClient(addr)
    try:
        return client.call("get_log", name=filename, tail_bytes=tail_bytes)
    finally:
        client.close()


def list_logs(node_id: Optional[str] = None) -> List[str]:
    addr = _agent_for(node_id)
    if addr is None:
        raise ValueError(f"no alive node {node_id}")
    client = SyncRpcClient(addr)
    try:
        return client.call("list_logs")
    finally:
        client.close()
