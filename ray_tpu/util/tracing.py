"""Distributed tracing: spans with cross-process trace propagation.

Reference capability: python/ray/util/tracing/tracing_helper.py:34-165
(`ray.init(_tracing_startup_hook=...)` injecting OpenTelemetry wrappers
around remote calls). Redesign without an opentelemetry dependency (not in
the image): the framework emits plain span dicts

    {"trace_id", "span_id", "parent_id", "name", "start_s", "end_s",
     "attrs": {...}}

to a pluggable EXPORTER — the OpenTelemetry hook point: pass an exporter
that forwards to your otel SDK (span dicts map 1:1 onto otel spans), or use
the default JSONL file exporter.

Propagation: ``enable_tracing()`` patches task submission to stamp the
current trace context into each task's spec (``__trace_ctx__`` in
runtime_env); workers (always listening — near-zero cost when the spec
carries no context) restore it around execution, so nested submits chain
parent ids across processes.

    tracing.enable_tracing()                      # or exporter=fn
    with tracing.trace_span("pipeline"):
        ray_tpu.get(step.remote(x))               # child span in the worker
    tracing.flush()
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

_ctx: "contextvars.ContextVar[Optional[Dict[str, str]]]" = contextvars.ContextVar(
    "ray_tpu_trace_ctx", default=None
)

_lock = threading.Lock()
_buffer: List[Dict[str, Any]] = []
_exporter: Optional[Callable[[List[Dict[str, Any]]], None]] = None
_enabled = False
_patched = False


def jsonl_exporter(path: Optional[str] = None) -> Callable:
    path = path or os.path.join(
        os.environ.get("RAY_TPU_SESSION_DIR", "/tmp"),
        f"trace-{os.getpid()}.jsonl")

    def export(spans: List[Dict[str, Any]]) -> None:
        with open(path, "a") as f:
            for s in spans:
                f.write(json.dumps(s) + "\n")

    export.path = path  # type: ignore[attr-defined]
    return export


def enable_tracing(exporter: Optional[Callable] = None) -> None:
    """Turn on span recording + trace propagation in THIS process (driver
    or worker). ``exporter`` receives batches of span dicts at flush()."""
    global _enabled, _exporter
    _exporter = exporter or jsonl_exporter()
    _enabled = True
    _patch_submission()


def set_exporter(exporter: Callable) -> None:
    """Install an exporter WITHOUT enabling tracing. Workers use this: spans
    are then recorded only for tasks whose spec carries a __trace_ctx__
    (i.e. the DRIVER opted in), so untraced clusters pay nothing."""
    global _exporter
    _exporter = exporter
    _patch_submission()  # nested submits must still forward inherited ctx


def is_enabled() -> bool:
    return _enabled


def current_trace_context() -> Optional[Dict[str, str]]:
    return _ctx.get()


def set_trace_context(ctx: Optional[Dict[str, str]]) -> None:
    _ctx.set(ctx)


def _record(span: Dict[str, Any]) -> None:
    with _lock:
        _buffer.append(span)


def flush() -> int:
    """Export buffered spans; returns the count."""
    with _lock:
        spans, _buffer[:] = list(_buffer), []
    if spans and _exporter is not None:
        _exporter(spans)
    return len(spans)


@contextlib.contextmanager
def trace_span(name: str, attrs: Optional[Dict[str, Any]] = None,
               force_record: bool = False):
    """Record one span; nested spans (and remote calls made inside) chain
    off it. Works whether or not enable_tracing ran (no-op buffer-less when
    disabled, unless force_record — the worker path for driver-initiated
    traces)."""
    parent = _ctx.get()
    span = {
        "trace_id": parent["trace_id"] if parent else uuid.uuid4().hex,
        "span_id": uuid.uuid4().hex[:16],
        "parent_id": parent["span_id"] if parent else None,
        "name": name,
        "start_s": time.time(),
        "attrs": dict(attrs or {}),
    }
    token = _ctx.set({"trace_id": span["trace_id"], "span_id": span["span_id"]})
    try:
        yield span
    finally:
        _ctx.reset(token)
        span["end_s"] = time.time()
        if _enabled or force_record:
            _record(span)


def _patch_submission() -> None:
    """Stamp the current trace context into outgoing task specs (once).
    __trace_ctx__ rides runtime_env's internal ("__"-prefixed) key space,
    which _prepare_runtime_env forwards verbatim to the worker's spec."""
    global _patched
    if _patched:
        return
    _patched = True
    from ray_tpu.core import remote_function as rf

    original = rf.RemoteFunction.remote

    def traced_remote(self, *args, **kwargs):
        ctx = _ctx.get()
        if ctx is not None:
            renv = dict(self._options.get("runtime_env") or {})
            renv["__trace_ctx__"] = ctx
            return original(self.options(runtime_env=renv), *args, **kwargs)
        return original(self, *args, **kwargs)

    rf.RemoteFunction.remote = traced_remote


def restore_from_spec(spec: Dict[str, Any]) -> Optional[Dict[str, str]]:
    """Worker-side: pull the submitter's trace context out of a task spec
    (returns it; caller sets/uses via task_execution_span)."""
    renv = spec.get("runtime_env") or {}
    ctx = renv.get("__trace_ctx__")
    if isinstance(ctx, dict) and "trace_id" in ctx and "span_id" in ctx:
        return {"trace_id": str(ctx["trace_id"]),
                "span_id": str(ctx["span_id"])}
    return None


@contextlib.contextmanager
def task_execution_span(spec: Dict[str, Any]):
    """Wrap a task execution: restores the submitter's context (if any) and
    records an execute span under it. Cheap no-op when the spec carries no
    context and tracing is off — so untraced clusters record nothing and
    pay no per-task flush RPC."""
    ctx = restore_from_spec(spec)
    if ctx is None and not _enabled:
        yield None
        return
    token = _ctx.set(ctx) if ctx is not None else None
    try:
        with trace_span(f"task:{spec.get('name', '?')}",
                        {"task_id": spec.get("task_id", "")},
                        force_record=ctx is not None) as span:
            yield span
    finally:
        if token is not None:
            _ctx.reset(token)
