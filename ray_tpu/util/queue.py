"""Distributed queue over an actor.

Reference capability: python/ray/util/queue.py (Queue — an asyncio.Queue
hosted in an actor; Empty/Full mirror the stdlib). Blocking get/put use the
actor's max_concurrency so a blocked consumer doesn't wedge producers.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int = 0):
        import asyncio

        self._q: "asyncio.Queue" = asyncio.Queue(maxsize=maxsize)

    async def put(self, item: Any, timeout: Optional[float] = None) -> bool:
        import asyncio

        if timeout is None:
            await self._q.put(item)
            return True
        try:
            await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    def put_nowait(self, item: Any) -> bool:
        import asyncio

        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get(self, timeout: Optional[float] = None):
        import asyncio

        if timeout is None:
            return (True, await self._q.get())
        try:
            return (True, await asyncio.wait_for(self._q.get(), timeout))
        except asyncio.TimeoutError:
            return (False, None)

    def get_nowait(self):
        import asyncio

        try:
            return (True, self._q.get_nowait())
        except asyncio.QueueEmpty:
            return (False, None)

    def qsize(self) -> int:
        return self._q.qsize()

    def maxsize(self) -> int:
        return self._q.maxsize


class Queue:
    """Process-safe FIFO queue usable from any driver/task/actor.

        q = Queue(maxsize=100)
        q.put(1); q.get()          # blocking with optional timeout
        refs = [worker.remote(q) for _ in range(8)]
    """

    def __init__(self, maxsize: int = 0, *, actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("max_concurrency", 8)  # blocked gets don't wedge puts
        self.actor = _QueueActor.options(**opts).remote(maxsize)

    # blocking calls wait in bounded chunks: a permanently-blocked call would
    # pin one of the actor's concurrency threads, and max_concurrency blocked
    # consumers would then starve every put (deadlock). Chunked waits free
    # the thread between chunks, so producers always get a turn.
    _WAIT_CHUNK_S = 2.0

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full()
            return
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            chunk = self._WAIT_CHUNK_S if deadline is None else max(
                0.001, min(self._WAIT_CHUNK_S, deadline - time.monotonic()))
            if ray_tpu.get(self.actor.put.remote(item, chunk),
                           timeout=chunk + 30):
                return
            if deadline is not None and time.monotonic() >= deadline:
                raise Full()

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty()
            return item
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            chunk = self._WAIT_CHUNK_S if deadline is None else max(
                0.001, min(self._WAIT_CHUNK_S, deadline - time.monotonic()))
            ok, item = ray_tpu.get(self.actor.get.remote(chunk),
                                   timeout=chunk + 30)
            if ok:
                return item
            if deadline is not None and time.monotonic() >= deadline:
                raise Empty()

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        maxsize = ray_tpu.get(self.actor.maxsize.remote())
        return maxsize > 0 and self.qsize() >= maxsize

    def put_batch(self, items: List[Any]) -> None:
        for i in items:
            self.put(i)

    def shutdown(self) -> None:
        ray_tpu.kill(self.actor)
