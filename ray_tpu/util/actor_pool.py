"""ActorPool: fan work out over a fixed set of actors, harvesting results
in submission order or completion order.

Reference capability: python/ray/util/actor_pool.py (same public API; the
bookkeeping here is sequence-number based — each dispatched call gets a
monotonically increasing ticket, and ordered consumption walks the ticket
counter past any entries already taken by unordered consumption).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, Iterable, List, Optional, Tuple, TypeVar

from ray_tpu import api
from ray_tpu.core.object_ref import ObjectRef

V = TypeVar("V")


@dataclass
class _Ticket:
    seq: int
    actor: Any
    ref: ObjectRef


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._free: Deque[Any] = deque(actors)
        self._backlog: Deque[Tuple[Callable, Any]] = deque()
        self._tickets: Dict[int, _Ticket] = {}  # seq -> in-flight call
        self._seq_of: Dict[ObjectRef, int] = {}
        self._issued = 0  # next ticket number to assign
        self._cursor = 0  # next ticket get_next() emits

    def submit(self, fn: Callable[[Any, V], ObjectRef], value: V) -> None:
        """fn(actor, value) -> ObjectRef. Queued if every actor is busy."""
        if not self._free:
            self._backlog.append((fn, value))
            return
        actor = self._free.popleft()
        ticket = _Ticket(self._issued, actor, fn(actor, value))
        self._issued += 1
        self._tickets[ticket.seq] = ticket
        self._seq_of[ticket.ref] = ticket.seq

    def has_next(self) -> bool:
        return bool(self._tickets or self._backlog)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in SUBMISSION order."""
        if not self.has_next():
            raise StopIteration("No more results to get")
        while self._cursor not in self._tickets and self._cursor < self._issued:
            self._cursor += 1  # skip tickets consumed by get_next_unordered
        ticket = self._tickets[self._cursor]
        # get() first: on timeout the cursor must NOT advance, so a retry can
        # still collect this result and return the actor
        value = api.get(ticket.ref, timeout=timeout)
        self._cursor += 1
        self._retire(ticket)
        return value

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Whichever pending result completes first."""
        if not self.has_next():
            raise StopIteration("No more results to get")
        ready, _ = api.wait(
            [t.ref for t in self._tickets.values()], num_returns=1, timeout=timeout
        )
        if not ready:
            raise TimeoutError("Timed out waiting for result")
        ticket = self._tickets[self._seq_of[ready[0]]]
        value = api.get(ticket.ref)
        self._retire(ticket)
        return value

    def _retire(self, ticket: _Ticket) -> None:
        del self._tickets[ticket.seq]
        del self._seq_of[ticket.ref]
        self._free.append(ticket.actor)
        self._drain_backlog()

    def _drain_backlog(self) -> None:
        while self._backlog and self._free:
            fn, value = self._backlog.popleft()
            self.submit(fn, value)

    def map(self, fn: Callable, values: Iterable[V]) -> Iterable[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[V]) -> Iterable[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._free)

    def pop_idle(self) -> Optional[Any]:
        return self._free.pop() if self._free else None

    def push(self, actor: Any) -> None:
        self._free.append(actor)
        self._drain_backlog()
