"""ActorPool: round-robin work distribution over a fixed set of actors
(reference capability: python/ray/util/actor_pool.py)."""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional, TypeVar

from ray_tpu import api
from ray_tpu.core.object_ref import ObjectRef

V = TypeVar("V")


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: List[tuple] = []

    def submit(self, fn: Callable[[Any, V], ObjectRef], value: V) -> None:
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future) or bool(self._pending_submits)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        if not self.has_next():
            raise StopIteration("No more results to get")
        future = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        value = api.get(future, timeout=timeout)
        _, actor = self._future_to_actor.pop(future)
        self._return_actor(actor)
        return value

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        if not self.has_next():
            raise StopIteration("No more results to get")
        ready, _ = api.wait(list(self._future_to_actor), num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("Timed out waiting for result")
        future = ready[0]
        i, actor = self._future_to_actor.pop(future)
        del self._index_to_future[i]
        if i == self._next_return_index:
            while self._next_return_index in self._future_to_actor:
                self._next_return_index += 1
            self._next_return_index = max(self._next_return_index, i + 1)
        self._return_actor(actor)
        return api.get(future)

    def _return_actor(self, actor: Any) -> None:
        self._idle.append(actor)
        while self._pending_submits and self._idle:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def map(self, fn: Callable, values: Iterable[V]) -> Iterable[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[V]) -> Iterable[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._idle)

    def pop_idle(self) -> Optional[Any]:
        return self._idle.pop() if self._idle else None

    def push(self, actor: Any) -> None:
        self._return_actor(actor)
