"""joblib backend: ``with joblib.parallel_backend("ray_tpu"): ...``.

Reference capability: python/ray/util/joblib/ (register_ray — routes
sklearn/joblib Parallel loops onto the cluster). The backend subclasses
joblib's threading backend but executes each joblib batch as a task, so
n_jobs spans the cluster while joblib keeps its own batching/dispatch
logic.
"""

from __future__ import annotations

from typing import Any

import ray_tpu


def register_ray_tpu() -> None:
    """Register the 'ray_tpu' joblib parallel backend (idempotent)."""
    from joblib import register_parallel_backend
    from joblib._parallel_backends import FallbackToBackend, SequentialBackend, ThreadingBackend

    class RayTpuBackend(ThreadingBackend):
        supports_timeout = True

        def configure(self, n_jobs: int = 1, parallel: Any = None, **kw):
            n_jobs = self.effective_n_jobs(n_jobs)
            if n_jobs == 1:
                raise FallbackToBackend(SequentialBackend(
                    nesting_level=self.nesting_level))
            self.parallel = parallel
            self._n_jobs = n_jobs
            return n_jobs

        def effective_n_jobs(self, n_jobs: int) -> int:
            if n_jobs == 1:
                return 1
            try:
                cpus = int(ray_tpu.cluster_resources().get("CPU", 2))
            except Exception:  # noqa: BLE001
                cpus = 2
            if n_jobs in (None, -1):
                return max(2, cpus)
            return n_jobs

        def apply_async(self, func, callback=None):
            # func is a joblib BatchedCalls: ship the whole batch as ONE task
            @ray_tpu.remote
            def _run_batch(batch):
                return batch()

            ref = _run_batch.remote(func)
            out = _RayFuture(ref)
            if callback is not None:
                # joblib only needs the callback after the result lands;
                # resolve lazily on retrieval is not enough for its dispatch
                # accounting, so collect on a worker thread
                import threading

                def waiter():
                    try:
                        out.get()
                    finally:
                        callback(out)

                threading.Thread(target=waiter, daemon=True).start()
            return out

    class _RayFuture:
        def __init__(self, ref):
            self._ref = ref
            self._value = None
            self._done = False

        def get(self, timeout: Any = None):
            if not self._done:
                self._value = ray_tpu.get(self._ref, timeout=timeout)
                self._done = True
            return self._value

    register_parallel_backend("ray_tpu", RayTpuBackend)
