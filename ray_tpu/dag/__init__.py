"""Classic lazy DAG API: bind() builds a graph, execute() runs it.

Reference capability: python/ray/dag/dag_node.py (DAGNode base + execute),
function_node.py, class_node.py, input_node.py, output_node.py. Redesign:
a small, explicit node tree over the existing task/actor API — bind is pure
graph construction (no submission); execute walks the graph once, submits
each task with its parents' ObjectRefs as arguments (so the data plane
chains refs, never materializing intermediates at the driver), and returns
the root's ObjectRef.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "DAGNode", "FunctionNode", "InputNode", "InputAttributeNode",
    "ClassNode", "ClassMethodNode", "MultiOutputNode",
]


class DAGNode:
    """Base graph node. Subclasses implement _execute_impl(resolver)."""

    def execute(self, *args, **kwargs):
        """Run the DAG rooted at this node; returns ObjectRef(s) of this
        node's result (a list for MultiOutputNode). ``args`` feed any
        InputNode in the graph."""
        ctx = _ExecutionContext(args, kwargs)
        return self._resolve(ctx)

    def _resolve(self, ctx: "_ExecutionContext"):
        if self in ctx.memo:
            return ctx.memo[self]
        out = self._execute_impl(ctx)
        ctx.memo[self] = out
        return out

    def _execute_impl(self, ctx: "_ExecutionContext"):
        raise NotImplementedError

    def experimental_compile(self, max_buffer_bytes: int = 8 << 20,
                             timeout_s: float = 3600.0):
        """Compile this DAG into channel-wired persistent actor loops
        (reference: compiled_dag_node.py:664). Steady-state execution does
        zero control-plane RPCs per call."""
        from ray_tpu.dag.compiled import experimental_compile

        return experimental_compile(self, max_buffer_bytes=max_buffer_bytes,
                                    timeout_s=timeout_s)

    # graph introspection (reference: DAGNode._get_all_child_nodes)
    def _children(self) -> List["DAGNode"]:
        return []

    def walk(self) -> List["DAGNode"]:
        """All nodes reachable from this root (depth-first, deduped)."""
        seen: List[DAGNode] = []

        def visit(n: DAGNode) -> None:
            if any(n is s for s in seen):
                return
            for c in n._children():
                visit(c)
            seen.append(n)

        visit(self)
        return seen


class _ExecutionContext:
    def __init__(self, args: tuple, kwargs: dict):
        self.args = args
        self.kwargs = kwargs
        self.memo: Dict[DAGNode, Any] = {}


def _resolve_args(ctx, args, kwargs) -> Tuple[tuple, dict]:
    def r(v):
        return v._resolve(ctx) if isinstance(v, DAGNode) else v

    return tuple(r(a) for a in args), {k: r(v) for k, v in kwargs.items()}


def _collect_children(args, kwargs) -> List[DAGNode]:
    out = [a for a in args if isinstance(a, DAGNode)]
    out += [v for v in kwargs.values() if isinstance(v, DAGNode)]
    return out


class InputNode(DAGNode):
    """Placeholder for execute()-time arguments (reference: input_node.py).
    Usable as a context manager for parity with the reference syntax:

        with InputNode() as inp:
            dag = f.bind(inp)
        dag.execute(5)
    """

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def __getattr__(self, key: str) -> "InputAttributeNode":
        if key.startswith("_"):
            raise AttributeError(key)
        return InputAttributeNode(self, key)

    def __getitem__(self, key) -> "InputAttributeNode":
        return InputAttributeNode(self, key)

    def _execute_impl(self, ctx: _ExecutionContext):
        if len(ctx.args) == 1 and not ctx.kwargs:
            return ctx.args[0]
        if not ctx.args and ctx.kwargs:
            return dict(ctx.kwargs)
        return ctx.args


class InputAttributeNode(DAGNode):
    """inp.key / inp[idx]: one field of the execute() input."""

    def __init__(self, parent: InputNode, key):
        self._parent = parent
        self._key = key

    def _children(self) -> List[DAGNode]:
        return [self._parent]

    def _execute_impl(self, ctx: _ExecutionContext):
        base = self._parent._resolve(ctx)
        if isinstance(self._key, str) and isinstance(base, dict):
            return base[self._key]
        if isinstance(self._key, int):
            return base[self._key]
        return getattr(base, self._key)


class FunctionNode(DAGNode):
    """fn.bind(*args): a task invocation node (reference: function_node.py)."""

    def __init__(self, remote_fn, args: tuple, kwargs: dict):
        self._fn = remote_fn
        self._args = args
        self._kwargs = kwargs

    def _children(self) -> List[DAGNode]:
        return _collect_children(self._args, self._kwargs)

    def _execute_impl(self, ctx: _ExecutionContext):
        args, kwargs = _resolve_args(ctx, self._args, self._kwargs)
        return self._fn.remote(*args, **kwargs)

    def __repr__(self) -> str:
        return f"FunctionNode({getattr(self._fn, '_name', '?')})"


class ClassNode(DAGNode):
    """Actor.bind(*args): an actor-creation node; method calls on it create
    ClassMethodNodes (reference: class_node.py)."""

    def __init__(self, actor_cls, args: tuple, kwargs: dict):
        self._cls = actor_cls
        self._args = args
        self._kwargs = kwargs

    def _children(self) -> List[DAGNode]:
        return _collect_children(self._args, self._kwargs)

    def __getattr__(self, name: str) -> "_ClassMethodBinder":
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClassMethodBinder(self, name)

    def _execute_impl(self, ctx: _ExecutionContext):
        args, kwargs = _resolve_args(ctx, self._args, self._kwargs)
        return self._cls.remote(*args, **kwargs)


class _ClassMethodBinder:
    def __init__(self, class_node: ClassNode, method: str):
        self._node = class_node
        self._method = method

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._node, self._method, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method: str, args: tuple, kwargs: dict):
        self._class_node = class_node
        self._method = method
        self._args = args
        self._kwargs = kwargs

    def _children(self) -> List[DAGNode]:
        return [self._class_node] + _collect_children(self._args, self._kwargs)

    def _execute_impl(self, ctx: _ExecutionContext):
        actor = self._class_node._resolve(ctx)
        args, kwargs = _resolve_args(ctx, self._args, self._kwargs)
        return getattr(actor, self._method).remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Bundle several leaves as the DAG output (reference: output_node.py)."""

    def __init__(self, outputs: List[DAGNode]):
        self._outputs = list(outputs)

    def _children(self) -> List[DAGNode]:
        return list(self._outputs)

    def _execute_impl(self, ctx: _ExecutionContext):
        return [o._resolve(ctx) for o in self._outputs]
