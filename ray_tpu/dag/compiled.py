"""Compiled DAGs: channel-wired persistent actor loops (the aDAG analogue).

Reference capability: python/ray/dag/compiled_dag_node.py:664 (CompiledDAG —
`experimental_compile()` pre-provisions per-actor execution loops connected
by mutable-object channels so steady-state execution does ZERO control-plane
RPCs per call; `execute:2118`). Redesign: stages are ClassMethodNodes bound
to long-lived actors; each stage runs `__rtpu_channel_loop__` (a worker-side
hook) that blocks on its input channels, runs the bound method, and writes
the result channel — the data plane is ray_tpu.experimental.channel (native
seqlock shm), the control plane is used only at compile and teardown.

TPU note: *within* one jit program, pipeline stages compose with
`parallel.pipeline` (collective_permute over the mesh — no host hop at
all). Compiled DAGs are the HOST-LEVEL pipeline: chaining separately-jitted
programs living in different processes (e.g. pp stages too big for one
process, or mixed preprocess->train->postprocess loops), the role
torch-tensor NCCL channels play in the reference.

Supported graph shape (v1, mirrors the reference's constraints): InputNode
(+ attribute projections) feeding ClassMethodNodes on distinct actors,
arbitrary depth/fan-out, optional MultiOutputNode root. Each actor may own
at most one stage (an actor's loop is dedicated, like the reference's
per-actor compiled loop).
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.dag import (
    ClassMethodNode, ClassNode, DAGNode, InputAttributeNode, InputNode,
    MultiOutputNode,
)
from ray_tpu.experimental.channel import Channel, ChannelClosed, ChannelError
from ray_tpu.utils.logging import get_logger

logger = get_logger("dag.compiled")


class _StageError:
    """Error marker flowing through channels (poisons downstream stages)."""

    def __init__(self, stage: str, err: BaseException):
        self.stage = stage
        self.error = err

    def raise_(self):
        raise RuntimeError(
            f"compiled DAG stage '{self.stage}' failed: {self.error!r}"
        ) from self.error


def channel_loop(instance, plan: Dict[str, Any]) -> str:
    """Worker-side stage loop (dispatched by the __rtpu_channel_loop__ hook
    in worker_main). Reads every input channel, applies the bound method,
    writes the output channel; exits when an upstream channel closes."""
    method = getattr(instance, plan["method"])
    out = Channel.open(plan["out"], reader_slot=None) if plan.get("out") else None
    # out channel: this stage is the WRITER; reader_slot None (we never read)
    ins: List[Tuple[str, Any, Optional[Any], Optional[int]]] = []
    # each arg spec: ("const", value) | ("chan", Channel, key)
    opened: Dict[str, Channel] = {}

    def open_chan(handle, slot):
        c = opened.get(handle.path)
        if c is None:
            c = Channel.open(handle, reader_slot=slot)
            opened[handle.path] = c
        return c

    arg_specs = []
    for spec in plan["args"]:
        if spec[0] == "const":
            arg_specs.append(("const", spec[1], None))
        else:  # ("chan", handle, slot, key)
            arg_specs.append(("chan", open_chan(spec[1], spec[2]), spec[3]))
    if not opened:
        # no channel inputs: nothing can tick this stage (validated at
        # compile time; defensive here)
        if out is not None:
            out.close()
        return "done"
    try:
        while True:
            # read one version from every distinct input channel
            try:
                values = {path: c.read(timeout_s=plan.get("timeout_s", 3600.0))
                          for path, c in opened.items()}
            except ChannelClosed:
                break
            poison = next((v for v in values.values()
                           if isinstance(v, _StageError)), None)
            if poison is not None:
                if out is not None:
                    out.write(poison)
                continue
            args = []
            for kind, v, key in arg_specs:
                if kind == "const":
                    args.append(v)
                else:
                    val = values[v.handle.path]
                    args.append(val[key] if key is not None else val)
            try:
                result = method(*args)
            except BaseException as e:  # noqa: BLE001 - poison downstream
                result = _StageError(plan["label"], e)
            if out is not None:
                out.write(result)
    finally:
        if out is not None:
            out.close()
    return "done"


class CompiledDAGRef:
    """Future for one execute() call (version-indexed channel read)."""

    def __init__(self, dag: "CompiledDAG", version: int):
        self._dag = dag
        self._version = version

    def get(self, timeout: Optional[float] = None):
        return self._dag._get_output(self._version, timeout)


class CompiledDAG:
    def __init__(self, root: DAGNode, max_buffer_bytes: int = 8 << 20,
                 timeout_s: float = 3600.0):
        self._root = root
        self._cap = max_buffer_bytes
        self._timeout_s = timeout_s
        self._lock = threading.Lock()
        self._submitted = 0
        self._results: Dict[int, Any] = {}  # version -> output (buffered)
        self._next_to_read = 1
        self._torn_down = False
        self._build()

    # ------------------------------------------------------------- planning
    def _build(self) -> None:
        import ray_tpu

        nodes = self._root.walk()
        outputs = (self._root._outputs if isinstance(self._root, MultiOutputNode)
                   else [self._root])
        stages = [n for n in nodes if isinstance(n, ClassMethodNode)]
        if not stages:
            raise ChannelError("experimental_compile needs >=1 actor method node")
        for n in nodes:
            if not isinstance(n, (ClassMethodNode, ClassNode, InputNode,
                                  InputAttributeNode, MultiOutputNode)):
                raise ChannelError(
                    f"unsupported node in compiled DAG: {type(n).__name__} "
                    "(function nodes run per-call; bind them to an actor)")
        for o in outputs:
            if not isinstance(o, ClassMethodNode):
                raise ChannelError("compiled DAG outputs must be actor methods")

        # one actor per ClassNode (created once, with constant args)
        self._actors: Dict[int, Any] = {}
        owners: Dict[int, ClassMethodNode] = {}
        for s in stages:
            cn = s._class_node
            if id(cn) in owners:
                raise ChannelError(
                    "one actor cannot own two stages of a compiled DAG "
                    "(its loop is dedicated)")
            owners[id(cn)] = s
            if id(cn) not in self._actors:
                if any(isinstance(a, DAGNode) for a in cn._args) or any(
                        isinstance(v, DAGNode) for v in cn._kwargs.values()):
                    raise ChannelError(
                        "actor constructor args must be constants in a "
                        "compiled DAG")
                self._actors[id(cn)] = cn._cls.remote(*cn._args, **cn._kwargs)

        # channels: stage -> consumers; input -> consumers
        def producers_of(n: ClassMethodNode) -> List[Tuple[Any, Optional[Any]]]:
            """For each positional arg: ("const", v) or (producer, key)."""
            specs = []
            for a in n._args:
                if isinstance(a, ClassMethodNode):
                    specs.append((a, None))
                elif isinstance(a, InputAttributeNode):
                    specs.append((a._parent, a._key))
                elif isinstance(a, InputNode):
                    specs.append((a, None))
                elif isinstance(a, DAGNode):
                    raise ChannelError(
                        f"unsupported arg node {type(a).__name__}")
                else:
                    specs.append(("const", a))
            if n._kwargs:
                raise ChannelError("kwargs not supported in compiled DAGs (v1)")
            return specs

        consumers: Dict[int, List[ClassMethodNode]] = {}
        plans: Dict[int, Dict[str, Any]] = {}
        for s in stages:
            for spec in producers_of(s):
                if spec[0] != "const" and not isinstance(spec[0], tuple):
                    prod = spec[0]
                    consumers.setdefault(id(prod), [])
                    if s not in consumers[id(prod)]:
                        consumers[id(prod)].append(s)

        # driver reads every output stage
        out_readers: Dict[int, int] = {}
        for o in outputs:
            consumers.setdefault(id(o), [])

        self._chans: Dict[int, Channel] = {}   # producer node id -> channel
        self._all_channels: List[Channel] = []
        for pid, cons in consumers.items():
            n_readers = len(cons) + (1 if any(id(o) == pid for o in outputs) else 0)
            ch = Channel.create(capacity=self._cap, num_readers=max(1, n_readers),
                                name=f"rtpu-cdag-{uuid.uuid4().hex[:12]}")
            self._chans[pid] = ch
            self._all_channels.append(ch)
            if any(id(o) == pid for o in outputs):
                out_readers[pid] = len(cons)  # driver takes the LAST slot

        # reader slot assignment per (producer, consumer)
        slot_of: Dict[Tuple[int, int], int] = {}
        for pid, cons in consumers.items():
            for i, c in enumerate(cons):
                slot_of[(pid, id(c))] = i

        # input channel: the InputNode's "producer" is the driver
        self._input_nodes = [n for n in nodes if isinstance(n, InputNode)]
        if not self._input_nodes:
            raise ChannelError(
                "a compiled DAG requires an InputNode (stages are driven by "
                "versions arriving on channels; without one nothing ticks)")
        inp = self._input_nodes[0]
        self._input_chan = self._chans.get(id(inp))
        if self._input_chan is None:
            raise ChannelError("InputNode present but unused")

        # stage plans + loop dispatch
        self._loop_refs = []
        for s in stages:
            args_spec = []
            for spec in producers_of(s):
                if spec[0] == "const":
                    args_spec.append(("const", spec[1]))
                else:
                    prod, key = spec
                    ch = self._chans[id(prod)]
                    args_spec.append(
                        ("chan", ch.handle, slot_of[(id(prod), id(s))], key))
            if not any(a[0] == "chan" for a in args_spec):
                raise ChannelError(
                    f"stage '{s._method}' has no channel inputs; every stage "
                    "must consume the InputNode or an upstream stage")
            plan = {
                "method": s._method,
                "label": f"{type(s).__name__}:{s._method}",
                "args": args_spec,
                "out": self._chans[id(s)].handle if id(s) in self._chans else None,
                "timeout_s": self._timeout_s,
            }
            from ray_tpu.core.actor import ActorMethod

            actor = self._actors[id(s._class_node)]
            # dunder names are blocked on ActorHandle.__getattr__; the
            # worker-side dispatch hook recognizes this one specially
            ref = ActorMethod(actor, "__rtpu_channel_loop__").remote(plan)
            self._loop_refs.append(ref)

        # driver-side output readers (the last slot of each output channel)
        self._out_readers: List[Channel] = []
        for o in outputs:
            ch = self._chans[id(o)]
            self._out_readers.append(
                Channel.open(ch.handle, reader_slot=out_readers[id(o)]))
        self._multi = isinstance(self._root, MultiOutputNode)

    # ------------------------------------------------------------ execution
    def execute(self, *args, **kwargs) -> CompiledDAGRef:
        if self._torn_down:
            raise ChannelError("compiled DAG torn down")
        with self._lock:
            # in-flight cap: channels buffer depth 1 each, so submitting more
            # than the pipeline can hold without a get() would deadlock the
            # driver inside write_acquire (reference: CompiledDAG bounds
            # max in-flight executions the same way)
            in_flight = self._submitted - (self._next_to_read - 1)
            if in_flight >= len(self._all_channels) + 1:
                raise ChannelError(
                    f"{in_flight} executions in flight fill the pipeline "
                    f"(depth {len(self._all_channels) + 1}); call .get() on "
                    "earlier refs before submitting more")
            if self._input_chan is not None:
                if len(args) == 1 and not kwargs:
                    payload = args[0]
                elif kwargs and not args:
                    payload = dict(kwargs)
                else:
                    payload = args
                self._input_chan.write(payload, timeout_s=self._timeout_s)
            self._submitted += 1
            return CompiledDAGRef(self, self._submitted)

    def _get_output(self, version: int, timeout: Optional[float]):
        with self._lock:
            while version not in self._results:
                if version < self._next_to_read:
                    raise ChannelError(f"version {version} already consumed")
                outs = [r.read(timeout_s=timeout if timeout is not None
                               else self._timeout_s)
                        for r in self._out_readers]
                self._results[self._next_to_read] = outs
                self._next_to_read += 1
            outs = self._results.pop(version)
        for o in outs:
            if isinstance(o, _StageError):
                o.raise_()
        return outs if self._multi else outs[0]

    # ------------------------------------------------------------- teardown
    def teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        if self._input_chan is not None:
            self._input_chan.close()  # cascades: every stage loop drains+exits
        import ray_tpu

        try:
            ray_tpu.get(self._loop_refs, timeout=30)
        except Exception:  # noqa: BLE001 - best effort drain
            logger.warning("compiled-loop drain failed", exc_info=True)
        for ch in self._all_channels:
            ch.destroy()

    def __del__(self):
        try:
            self.teardown()
        except Exception:  # noqa: BLE001
            pass


def experimental_compile(node: DAGNode, max_buffer_bytes: int = 8 << 20,
                         timeout_s: float = 3600.0) -> CompiledDAG:
    return CompiledDAG(node, max_buffer_bytes=max_buffer_bytes,
                       timeout_s=timeout_s)
