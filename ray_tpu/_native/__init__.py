"""ctypes bindings for the native runtime components (librtpu_native.so).

The native layer implements the pieces that stay native in the reference —
the object-store arena allocator (plasma_allocator.cc / dlmalloc.cc) and
the mutable-object channel atomics (experimental_mutable_object_manager.h)
— behind a C ABI. No pybind11 in the image, so binding is plain ctypes.

The library is built lazily on first import (one `make` shelling out to
g++, cached next to the sources); if the toolchain is missing the package
degrades gracefully: ``available()`` returns False and pure-Python
fallbacks take over (per-object shm segments; RPC-based channels).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "librtpu_native.so")

_lib: Optional[ctypes.CDLL] = None
_build_lock = threading.Lock()
_build_failed = False


def _try_build(force: bool = False) -> bool:
    srcs = [os.path.join(_DIR, f) for f in ("arena.cc", "channel.cc")]
    if not force and os.path.exists(_SO) and all(
        os.path.getmtime(_SO) >= os.path.getmtime(s) for s in srcs
    ):
        return True
    try:
        out = subprocess.run(
            ["make", "-C", _DIR] + (["-B"] if force else []),
            capture_output=True, text=True, timeout=120,
        )
        return out.returncode == 0 and os.path.exists(_SO)
    except Exception:  # noqa: BLE001 - missing make/g++ etc.
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    with _build_lock:
        if _lib is not None:
            return _lib
        if not _try_build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            # a stale/foreign-arch .so (e.g. copied checkout): rebuild from
            # source once before giving up on the native backend
            if not _try_build(force=True):
                _build_failed = True
                return None
            try:
                lib = ctypes.CDLL(_SO)
            except OSError:
                _build_failed = True
                return None
        c = ctypes
        # arena
        lib.rtpu_arena_create.argtypes = [c.c_char_p, c.c_uint64]
        lib.rtpu_arena_create.restype = c.c_int64
        lib.rtpu_arena_attach.argtypes = [c.c_char_p]
        lib.rtpu_arena_attach.restype = c.c_int64
        lib.rtpu_arena_base.argtypes = [c.c_int64]
        lib.rtpu_arena_base.restype = c.c_void_p
        lib.rtpu_arena_capacity.argtypes = [c.c_int64]
        lib.rtpu_arena_capacity.restype = c.c_uint64
        lib.rtpu_arena_alloc.argtypes = [c.c_int64, c.c_char_p, c.c_uint64]
        lib.rtpu_arena_alloc.restype = c.c_int64
        lib.rtpu_arena_free.argtypes = [c.c_int64, c.c_uint64]
        lib.rtpu_arena_free.restype = c.c_int
        lib.rtpu_arena_validate.argtypes = [c.c_int64, c.c_char_p, c.c_uint64,
                                            c.c_uint64]
        lib.rtpu_arena_validate.restype = c.c_int
        lib.rtpu_arena_used.argtypes = [c.c_int64]
        lib.rtpu_arena_used.restype = c.c_uint64
        lib.rtpu_arena_num_free_blocks.argtypes = [c.c_int64]
        lib.rtpu_arena_num_free_blocks.restype = c.c_uint64
        lib.rtpu_arena_largest_free.argtypes = [c.c_int64]
        lib.rtpu_arena_largest_free.restype = c.c_uint64
        lib.rtpu_arena_close.argtypes = [c.c_int64]
        lib.rtpu_arena_close.restype = None
        lib.rtpu_arena_unlink.argtypes = [c.c_char_p]
        lib.rtpu_arena_unlink.restype = c.c_int
        # channel
        lib.rtpu_chan_header_size.argtypes = []
        lib.rtpu_chan_header_size.restype = c.c_uint64
        lib.rtpu_chan_init.argtypes = [c.c_void_p]
        lib.rtpu_chan_init.restype = None
        lib.rtpu_chan_write_acquire.argtypes = [c.c_void_p, c.c_int, c.c_uint64]
        lib.rtpu_chan_write_acquire.restype = c.c_int64
        lib.rtpu_chan_write_release.argtypes = [c.c_void_p, c.c_uint64]
        lib.rtpu_chan_write_release.restype = None
        lib.rtpu_chan_read_acquire.argtypes = [c.c_void_p, c.c_uint64,
                                               c.POINTER(c.c_uint64), c.c_uint64]
        lib.rtpu_chan_read_acquire.restype = c.c_int64
        lib.rtpu_chan_read_validate.argtypes = [c.c_void_p, c.c_uint64]
        lib.rtpu_chan_read_validate.restype = c.c_int
        lib.rtpu_chan_read_ack.argtypes = [c.c_void_p, c.c_int, c.c_uint64]
        lib.rtpu_chan_read_ack.restype = None
        lib.rtpu_chan_close.argtypes = [c.c_void_p]
        lib.rtpu_chan_close.restype = None
        lib.rtpu_chan_is_closed.argtypes = [c.c_void_p]
        lib.rtpu_chan_is_closed.restype = c.c_int
        lib.rtpu_chan_version.argtypes = [c.c_void_p]
        lib.rtpu_chan_version.restype = c.c_uint64
        _lib = lib
        return _lib


def available() -> bool:
    """True if the native library is (or can be) loaded."""
    return _load() is not None


def lib() -> ctypes.CDLL:
    l = _load()
    if l is None:
        raise RuntimeError(
            "librtpu_native.so unavailable (no g++/make?); use the "
            "pure-Python fallbacks"
        )
    return l


class Arena:
    """Owner-side (allocating) or attached (read/write) view of one arena."""

    def __init__(self, path: str, capacity: Optional[int] = None,
                 create: bool = False):
        self._lib = lib()
        self.path = path
        if create:
            assert capacity is not None
            self._h = self._lib.rtpu_arena_create(path.encode(), capacity)
        else:
            self._h = self._lib.rtpu_arena_attach(path.encode())
        if self._h < 0:
            raise OSError(f"arena {'create' if create else 'attach'} failed: {path}")
        self.owner = create
        self.capacity = self._lib.rtpu_arena_capacity(self._h)
        base = self._lib.rtpu_arena_base(self._h)
        # one zero-copy view over the whole arena; object views are slices
        self._buf = (ctypes.c_char * self.capacity).from_address(base)
        self.view: memoryview = memoryview(self._buf).cast("B")

    # ---- owner ops --------------------------------------------------------
    def alloc(self, oid24: bytes, size: int) -> int:
        """Returns the payload offset, or -1 if no block fits."""
        return self._lib.rtpu_arena_alloc(self._h, oid24, size)

    def free(self, offset: int) -> bool:
        return self._lib.rtpu_arena_free(self._h, offset) == 0

    def used(self) -> int:
        return self._lib.rtpu_arena_used(self._h)

    def largest_free(self) -> int:
        return self._lib.rtpu_arena_largest_free(self._h)

    def num_free_blocks(self) -> int:
        return self._lib.rtpu_arena_num_free_blocks(self._h)

    # ---- shared ops -------------------------------------------------------
    def validate(self, oid24: bytes, offset: int, size: int) -> bool:
        return self._lib.rtpu_arena_validate(self._h, oid24, offset, size) == 1

    def slice(self, offset: int, size: int) -> memoryview:
        return self.view[offset : offset + size]

    def close(self) -> None:
        if self._h >= 0:
            try:
                self.view.release()
            except BufferError:
                # live views still alias the mapping: munmap would turn their
                # next access into SIGSEGV. Leak the mapping instead (the OS
                # reclaims at process exit) — mirror of ShmSegment.close.
                self._h = -1
                return
            self._lib.rtpu_arena_close(self._h)
            self._h = -1

    def unlink(self) -> None:
        self._lib.rtpu_arena_unlink(self.path.encode())
