// Mutable-object channels: versioned single-writer shared-memory slots with
// acquire/release semantics, for compiled-DAG style actor pipelines.
//
// Reference capability: src/ray/core_worker/experimental_mutable_object_
// manager.h:48 (WriteAcquire :153 / ReadAcquire — versioned mutable plasma
// buffers backing aDAG channels). Redesign: a channel is a fixed shm region
// [128B control block][payload]; the control block holds a C++11 atomic
// sequence counter (seqlock protocol) that Python cannot express — this is
// precisely the piece that must be native. Readers/writers in DIFFERENT
// processes map the same region; release stores publish, acquire loads
// observe (std::memory_order on lock-free 64-bit atomics over shared
// memory).
//
// Protocol (single writer, N readers, bounded wait):
//   seq % 2 == 0  -> stable version seq/2 published, len bytes valid
//   seq % 2 == 1  -> writer mid-update; readers spin/sleep
// A reader that wants "the next version after v" blocks until seq/2 > v.
// Writers overwrite freely (latest-value channel); for lossless pipelines
// the Python layer adds per-reader ack counters in the control block
// (num_read slots) so the writer can wait for all readers to consume the
// previous version before overwriting (bounded queue of depth 1, exactly
// the reference's WriteAcquire blocking semantics).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <ctime>

namespace {

struct Control {
  std::atomic<uint64_t> seq;        // seqlock: 2*version (+1 while writing)
  std::atomic<uint64_t> len;        // payload bytes of the published version
  std::atomic<uint64_t> acks[8];    // per-reader: last version consumed
  std::atomic<uint64_t> closed;     // writer hung up
  uint64_t reserved[4];
};
static_assert(sizeof(Control) <= 128, "control block must fit 128 bytes");

inline Control* ctl(void* base) { return reinterpret_cast<Control*>(base); }

inline void nap() {
  struct timespec ts = {0, 50000};  // 50us
  ::nanosleep(&ts, nullptr);
}

inline uint64_t now_ms() {
  struct timespec ts;
  ::clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

}  // namespace

extern "C" {

// The payload region starts 128 bytes into the channel mapping.
uint64_t rtpu_chan_header_size() { return 128; }

void rtpu_chan_init(void* base) {
  std::memset(base, 0, 128);
  ctl(base)->seq.store(0, std::memory_order_release);
}

// Writer: begin an update. If `wait_readers` > 0, blocks until every reader
// slot [0, wait_readers) has acked the current version (depth-1 queue /
// lossless mode). Returns the version being written, or -1 on timeout,
// -2 if the channel is closed.
int64_t rtpu_chan_write_acquire(void* base, int wait_readers,
                                uint64_t timeout_ms) {
  Control* c = ctl(base);
  if (c->closed.load(std::memory_order_acquire)) return -2;
  uint64_t deadline = now_ms() + timeout_ms;
  uint64_t seq = c->seq.load(std::memory_order_acquire);
  uint64_t current = seq / 2;
  if (wait_readers > 0 && current > 0) {
    for (;;) {
      bool all = true;
      for (int r = 0; r < wait_readers && r < 8; ++r) {
        if (c->acks[r].load(std::memory_order_acquire) < current) {
          all = false;
          break;
        }
      }
      if (all) break;
      if (c->closed.load(std::memory_order_acquire)) return -2;
      if (now_ms() > deadline) return -1;
      nap();
    }
  }
  c->seq.store(seq + 1, std::memory_order_release);  // odd: writing
  return static_cast<int64_t>(current + 1);
}

// Writer: publish `len` payload bytes as the new version.
void rtpu_chan_write_release(void* base, uint64_t len) {
  Control* c = ctl(base);
  c->len.store(len, std::memory_order_release);
  uint64_t seq = c->seq.load(std::memory_order_relaxed);
  c->seq.store(seq + 1, std::memory_order_release);  // even: published
}

// Reader: block until a version newer than `last_version` is published.
// Returns the new version (payload length in *len_out), or -1 on timeout,
// -2 if closed with no newer version coming.
int64_t rtpu_chan_read_acquire(void* base, uint64_t last_version,
                               uint64_t* len_out, uint64_t timeout_ms) {
  Control* c = ctl(base);
  uint64_t deadline = now_ms() + timeout_ms;
  for (;;) {
    uint64_t seq = c->seq.load(std::memory_order_acquire);
    if (seq % 2 == 0 && seq / 2 > last_version) {
      *len_out = c->len.load(std::memory_order_acquire);
      return static_cast<int64_t>(seq / 2);
    }
    if (c->closed.load(std::memory_order_acquire)) return -2;
    if (now_ms() > deadline) return -1;
    nap();
  }
}

// Reader: re-check that `version` is still the published one (no writer
// started since read_acquire). 1 = consistent read, 0 = torn (retry).
int rtpu_chan_read_validate(void* base, uint64_t version) {
  uint64_t seq = ctl(base)->seq.load(std::memory_order_acquire);
  return (seq % 2 == 0 && seq / 2 == version) ? 1 : 0;
}

// Reader `slot` marks `version` consumed (lossless mode handshake).
void rtpu_chan_read_ack(void* base, int slot, uint64_t version) {
  if (slot >= 0 && slot < 8)
    ctl(base)->acks[slot].store(version, std::memory_order_release);
}

void rtpu_chan_close(void* base) {
  ctl(base)->closed.store(1, std::memory_order_release);
}

int rtpu_chan_is_closed(void* base) {
  return ctl(base)->closed.load(std::memory_order_acquire) ? 1 : 0;
}

uint64_t rtpu_chan_version(void* base) {
  return ctl(base)->seq.load(std::memory_order_acquire) / 2;
}

}  // extern "C"
