// Shared-memory arena allocator: the native core of the object store.
//
// Reference capability: src/ray/object_manager/plasma/{plasma_allocator.cc,
// dlmalloc.cc, object_store.cc} — one mmap'd arena per node, objects carved
// out of it by a native allocator, readers attach the single segment and get
// zero-copy views. Redesign for this framework: the allocator is
// boundary-tag first-fit with eager coalescing (objects here are few and
// large — task returns / tensor blocks — so a size-class allocator like
// dlmalloc buys nothing over simple coalescing, and first-fit keeps the
// arena compact for the LRU evictor); allocation METADATA lives in the
// owning (node-agent) process, not in shared memory, because exactly one
// process allocates — workers only attach for the base pointer and
// read/write payload bytes at offsets the agent hands out via RPC.
//
// Each allocation is prefixed by a 64-byte in-arena header holding the
// 24-byte object id and the payload size. Readers validate the header
// against the id they expect; a mismatch means the slot was evicted and
// reused between the metadata RPC and the read, and surfaces as a clean
// "object missing" instead of silently returning another object's bytes.
//
// C ABI throughout (loaded via ctypes — no pybind11 in the image).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <map>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr uint64_t kAlign = 64;          // TPU-friendly / cacheline alignment
constexpr uint64_t kHeaderSize = 64;     // in-arena per-object header

struct FreeBlock {
  uint64_t size;  // bytes, including any header space of the block
};

struct Arena {
  void* base = nullptr;
  uint64_t capacity = 0;
  bool owner = false;  // created (allocates) vs attached (read/write only)
  std::string path;
  // free list keyed by offset -> size; allocated keyed by offset -> size.
  // Only the owner touches these; guarded for safety anyway.
  std::map<uint64_t, uint64_t> free_blocks;
  std::map<uint64_t, uint64_t> alloc_blocks;
  uint64_t used = 0;
  std::mutex mu;
};

std::mutex g_mu;
std::vector<Arena*> g_arenas;

Arena* get(int64_t h) {
  std::lock_guard<std::mutex> g(g_mu);
  if (h < 0 || h >= static_cast<int64_t>(g_arenas.size())) return nullptr;
  return g_arenas[h];
}

int64_t put(Arena* a) {
  std::lock_guard<std::mutex> g(g_mu);
  for (size_t i = 0; i < g_arenas.size(); ++i) {
    if (g_arenas[i] == nullptr) {
      g_arenas[i] = a;
      return static_cast<int64_t>(i);
    }
  }
  g_arenas.push_back(a);
  return static_cast<int64_t>(g_arenas.size() - 1);
}

uint64_t round_up(uint64_t n, uint64_t a) { return (n + a - 1) / a * a; }

}  // namespace

extern "C" {

// Create a fresh arena file of `capacity` bytes at `path` (a /dev/shm file).
// An existing file at the path (stale predecessor) is replaced. Returns a
// handle >= 0, or -1 (errno left set by the failing syscall).
int64_t rtpu_arena_create(const char* path, uint64_t capacity) {
  ::unlink(path);
  int fd = ::open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return -1;
  if (::ftruncate(fd, static_cast<off_t>(capacity)) != 0) {
    ::close(fd);
    ::unlink(path);
    return -1;
  }
  void* base =
      ::mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (base == MAP_FAILED) {
    ::unlink(path);
    return -1;
  }
  Arena* a = new Arena();
  a->base = base;
  a->capacity = capacity;
  a->owner = true;
  a->path = path;
  a->free_blocks[0] = capacity;
  return put(a);
}

// Attach an existing arena (worker side). Returns handle or -1.
int64_t rtpu_arena_attach(const char* path) {
  int fd = ::open(path, O_RDWR, 0600);
  if (fd < 0) return -1;
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    ::close(fd);
    return -1;
  }
  void* base = ::mmap(nullptr, static_cast<size_t>(st.st_size),
                      PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (base == MAP_FAILED) return -1;
  Arena* a = new Arena();
  a->base = base;
  a->capacity = static_cast<uint64_t>(st.st_size);
  a->owner = false;
  a->path = path;
  return put(a);
}

void* rtpu_arena_base(int64_t h) {
  Arena* a = get(h);
  return a ? a->base : nullptr;
}

uint64_t rtpu_arena_capacity(int64_t h) {
  Arena* a = get(h);
  return a ? a->capacity : 0;
}

// Allocate header+payload for `payload_size` bytes; writes the 24-byte
// object id into the header. Returns the PAYLOAD offset (64-aligned), or
// -1 if no free block fits (caller evicts and retries).
int64_t rtpu_arena_alloc(int64_t h, const uint8_t* oid24,
                         uint64_t payload_size) {
  Arena* a = get(h);
  if (a == nullptr || !a->owner) return -1;
  uint64_t need = round_up(kHeaderSize + payload_size, kAlign);
  std::lock_guard<std::mutex> g(a->mu);
  // first fit
  for (auto it = a->free_blocks.begin(); it != a->free_blocks.end(); ++it) {
    if (it->second < need) continue;
    uint64_t off = it->first;
    uint64_t remain = it->second - need;
    a->free_blocks.erase(it);
    if (remain > 0) a->free_blocks[off + need] = remain;
    a->alloc_blocks[off] = need;
    a->used += need;
    // header: [24B oid][8B payload size][32B reserved/zero]
    uint8_t* hdr = static_cast<uint8_t*>(a->base) + off;
    std::memcpy(hdr, oid24, 24);
    std::memcpy(hdr + 24, &payload_size, 8);
    std::memset(hdr + 32, 0, kHeaderSize - 32);
    return static_cast<int64_t>(off + kHeaderSize);
  }
  return -1;
}

// Free the block whose PAYLOAD starts at `payload_off`. Scrubs the header
// (so stale readers fail validation) and coalesces with neighbours.
// Returns 0 on success, -1 if the offset is unknown.
int rtpu_arena_free(int64_t h, uint64_t payload_off) {
  Arena* a = get(h);
  if (a == nullptr || !a->owner || payload_off < kHeaderSize) return -1;
  uint64_t off = payload_off - kHeaderSize;
  std::lock_guard<std::mutex> g(a->mu);
  auto it = a->alloc_blocks.find(off);
  if (it == a->alloc_blocks.end()) return -1;
  uint64_t size = it->second;
  a->alloc_blocks.erase(it);
  a->used -= size;
  std::memset(static_cast<uint8_t*>(a->base) + off, 0, kHeaderSize);
  // coalesce with the next free block
  auto next = a->free_blocks.lower_bound(off);
  if (next != a->free_blocks.end() && next->first == off + size) {
    size += next->second;
    a->free_blocks.erase(next);
  }
  // coalesce with the previous free block
  auto prev = a->free_blocks.lower_bound(off);
  if (prev != a->free_blocks.begin()) {
    --prev;
    if (prev->first + prev->second == off) {
      prev->second += size;
      return 0;
    }
  }
  a->free_blocks[off] = size;
  return 0;
}

// Validate that the header before `payload_off` holds `oid24` and a size
// of exactly `expect_size`. 1 = valid, 0 = mismatch (evicted/reused slot).
int rtpu_arena_validate(int64_t h, const uint8_t* oid24, uint64_t payload_off,
                        uint64_t expect_size) {
  Arena* a = get(h);
  if (a == nullptr || payload_off < kHeaderSize ||
      payload_off + expect_size > a->capacity)
    return 0;
  const uint8_t* hdr =
      static_cast<const uint8_t*>(a->base) + (payload_off - kHeaderSize);
  if (std::memcmp(hdr, oid24, 24) != 0) return 0;
  uint64_t stored;
  std::memcpy(&stored, hdr + 24, 8);
  return stored == expect_size ? 1 : 0;
}

uint64_t rtpu_arena_used(int64_t h) {
  Arena* a = get(h);
  if (a == nullptr) return 0;
  std::lock_guard<std::mutex> g(a->mu);
  return a->used;
}

uint64_t rtpu_arena_num_free_blocks(int64_t h) {
  Arena* a = get(h);
  if (a == nullptr) return 0;
  std::lock_guard<std::mutex> g(a->mu);
  return a->free_blocks.size();
}

// Largest single allocatable payload right now (fragmentation probe).
uint64_t rtpu_arena_largest_free(int64_t h) {
  Arena* a = get(h);
  if (a == nullptr) return 0;
  std::lock_guard<std::mutex> g(a->mu);
  uint64_t best = 0;
  for (auto& kv : a->free_blocks)
    if (kv.second > best) best = kv.second;
  return best > kHeaderSize ? best - kHeaderSize : 0;
}

void rtpu_arena_close(int64_t h) {
  std::lock_guard<std::mutex> g(g_mu);
  if (h < 0 || h >= static_cast<int64_t>(g_arenas.size())) return;
  Arena* a = g_arenas[h];
  g_arenas[h] = nullptr;
  if (a == nullptr) return;
  ::munmap(a->base, a->capacity);
  delete a;
}

int rtpu_arena_unlink(const char* path) { return ::unlink(path); }

}  // extern "C"
