from ray_tpu.train.config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train.session import Checkpoint, get_checkpoint, get_context, report, world_rank, world_size
from ray_tpu.train.step import TrainState, make_eval_step, make_train_state_factory, make_train_step, default_optimizer
from ray_tpu.train.trainer import Result, TpuTrainer

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "FailureConfig",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TpuTrainer",
    "TrainState",
    "default_optimizer",
    "get_checkpoint",
    "get_context",
    "make_eval_step",
    "make_train_state_factory",
    "make_train_step",
    "report",
    "world_rank",
    "world_size",
]
