from ray_tpu.train.step import TrainState, make_eval_step, make_train_state_factory, make_train_step

__all__ = ["TrainState", "make_eval_step", "make_train_state_factory", "make_train_step"]
