"""TpuTrainer: distributed training orchestration over the actor runtime.

Reference capability: python/ray/train/data_parallel_trainer.py +
_internal/backend_executor.py (BackendExecutor.start:135 placement group +
WorkerGroup, rank assignment :369, start_training:451, lockstep result
collection get_next_results:578, restart-from-checkpoint loop :759) — with a
JAX/TPU backend instead of torch.distributed:

- each worker is one HOST of the gang (on real TPU pods: one process per
  host, chips via ``tpus_per_worker``); worker 0's address seeds
  ``jax.distributed.initialize`` so the gang forms one jax runtime whose
  ``jax.devices()`` spans the slice;
- placement uses a PACK placement group over per-worker bundles (same ICI
  domain when slice resources are used);
- ``FailureConfig(max_failures)``: on any worker failure the whole group is
  torn down and restarted from the latest checkpoint (restart-based
  elasticity, matching the reference's semantics).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.train.config import FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train.session import Checkpoint, TrainContext, _Session
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.utils.logging import get_logger

logger = get_logger("train")


@dataclass
class Result:
    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    error: Optional[BaseException] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        return self.checkpoint


@ray_tpu.remote
class TrainWorker:
    """One gang member; hosts the user training thread + session."""

    def __init__(self, rank: int, world_size: int, ctx_kwargs: Dict[str, Any]):
        self.rank = rank
        self.world_size = world_size
        self.ctx_kwargs = ctx_kwargs
        self.session = None
        self.thread = None

    def get_address(self) -> str:
        """Worker 0 provides the jax.distributed coordinator address."""
        import socket

        hostname = socket.gethostname()
        try:
            ip = socket.gethostbyname(hostname)
        except OSError:
            ip = "127.0.0.1"
        return f"{ip}:{29400 + (os.getpid() % 1000)}"

    def setup_jax(self, coordinator: str, use_distributed: bool) -> bool:
        """On real multi-host TPU gangs: form one jax runtime across hosts.
        On CI (cpu workers / single host) jax stays per-process."""
        if use_distributed:
            import jax

            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=self.world_size,
                process_id=self.rank,
            )
        return True

    def start_training(self, fn_payload: bytes, train_config: Dict[str, Any],
                       latest_checkpoint: Optional[str],
                       dataset_shards: Optional[bytes] = None) -> bool:
        import threading

        fn = cloudpickle.loads(fn_payload)
        ctx = TrainContext(
            world_rank=self.rank,
            world_size=self.world_size,
            local_rank=0,
            local_world_size=1,
            node_rank=self.rank,
            **self.ctx_kwargs,
        )
        shards = cloudpickle.loads(dataset_shards) if dataset_shards else {}
        self.session = _Session(
            ctx, Checkpoint(latest_checkpoint) if latest_checkpoint else None,
            dataset_shards=shards,
        )
        session = self.session

        def run() -> None:
            from ray_tpu.train.session import _bind_session_to_current_thread, _unbind_current_thread
            import inspect

            _bind_session_to_current_thread(session)
            try:
                sig = inspect.signature(fn)
                if len(sig.parameters) == 0:
                    fn()
                else:
                    fn(train_config)
            except BaseException as e:  # noqa: BLE001
                session.error = e
            finally:
                session.finished = True
                session.result_queue.put({"done": True})
                _unbind_current_thread()

        self.thread = threading.Thread(target=run, daemon=True, name="train-fn")
        self.thread.start()
        return True

    def next_result(self) -> Dict[str, Any]:
        """Blocks until the user fn reports or finishes."""
        item = self.session.result_queue.get()
        if item.get("done"):
            err = self.session.error
            return {
                "done": True,
                "error": cloudpickle.dumps(err) if err is not None else None,
            }
        self.session.continue_event.set()
        return item

    def shutdown(self) -> bool:
        return True


class TpuTrainer:
    """North-star API: TpuTrainer(fn, scaling_config=...).fit()
    (reference: DataParallelTrainer / TorchTrainer)."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        use_jax_distributed: bool = False,
    ):
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.use_jax_distributed = use_jax_distributed

    def fit(self, _tune_session=None, _resume_from: Optional[str] = None) -> Result:
        """Run the distributed training job.

        Routed through Tune when called without a session (reference:
        train/base_trainer.py:567 — ``Trainer.fit`` IS a 1-trial Tune run, so
        failure handling, experiment state, and result plumbing are shared
        with hyperparameter sweeps). The Tuner's trial actor calls back in
        with ``_tune_session`` set, which runs the gang directly and streams
        per-round metrics to the trial."""
        if _tune_session is None:
            from ray_tpu.tune.tuner import TuneConfig, Tuner

            tuner = Tuner(
                self,
                tune_config=TuneConfig(num_samples=1, max_concurrent_trials=1),
                run_config=self.run_config,
            )
            grid = tuner.fit()
            return grid[0]
        max_failures = self.run_config.failure_config.max_failures
        trial_dir = self.run_config.resolved_storage_path()
        os.makedirs(trial_dir, exist_ok=True)
        latest_checkpoint: Optional[str] = _resume_from
        history: List[Dict[str, Any]] = []
        failures = 0
        while True:
            try:
                result = self._run_attempt(trial_dir, latest_checkpoint, history,
                                           tune_session=_tune_session)
                return result
            except _AttemptFailed as e:
                failures += 1
                latest_checkpoint = e.latest_checkpoint or latest_checkpoint
                if max_failures >= 0 and failures > max_failures:
                    return Result(
                        metrics=history[-1] if history else {},
                        checkpoint=Checkpoint(latest_checkpoint) if latest_checkpoint else None,
                        error=e.error,
                        metrics_history=history,
                    )
                logger.warning(
                    "training attempt failed (%s); restarting from %s (failure %d/%d)",
                    e.error, latest_checkpoint, failures, max_failures,
                )

    # ------------------------------------------------------------------
    def _run_attempt(self, trial_dir: str, latest_checkpoint: Optional[str],
                     history: List[Dict[str, Any]], tune_session=None) -> Result:
        scaling = self.scaling
        pg = None
        workers: List[Any] = []
        try:
            pg = placement_group(scaling.bundles(), strategy=scaling.placement_strategy)
            pg.ready(timeout=60)
            ctx_kwargs = {
                "experiment_name": self.run_config.name or "train_run",
                "storage_path": self.run_config.resolved_storage_path(),
                "trial_dir": trial_dir,
            }
            for rank in range(scaling.num_workers):
                res = scaling.worker_resources()
                workers.append(
                    TrainWorker.options(
                        num_cpus=res.get("CPU", 0),
                        num_tpus=res.get("TPU", 0),
                        resources={k: v for k, v in res.items() if k not in ("CPU", "TPU")},
                        placement_group=pg,
                        placement_group_bundle_index=rank,
                    ).remote(rank, scaling.num_workers, ctx_kwargs)
                )
            # rendezvous: worker 0 coordinates (multi-host jax runtime)
            coordinator = ray_tpu.get(workers[0].get_address.remote(), timeout=120)
            ray_tpu.get(
                [w.setup_jax.remote(coordinator, self.use_jax_distributed) for w in workers],
                timeout=300,
            )
            payload = cloudpickle.dumps(self.train_loop)
            # per-worker dataset shards via streaming_split (reference:
            # DataConfig.configure + ray.train.get_dataset_shard)
            shard_table: List[Dict[str, Any]] = [{} for _ in range(scaling.num_workers)]
            for ds_name, ds in self.datasets.items():
                for rank, shard in enumerate(ds.streaming_split(scaling.num_workers)):
                    shard_table[rank][ds_name] = shard
            ray_tpu.get(
                [
                    w.start_training.remote(
                        payload, self.train_loop_config, latest_checkpoint,
                        cloudpickle.dumps(shard_table[rank]),
                    )
                    for rank, w in enumerate(workers)
                ],
                timeout=120,
            )
            final_error: Optional[BaseException] = None
            done = False
            while not done:
                try:
                    round_results = ray_tpu.get(
                        [w.next_result.remote() for w in workers], timeout=3600
                    )
                except (exc.ActorDiedError, exc.ActorUnavailableError, exc.GetTimeoutError) as e:
                    raise _AttemptFailed(e, latest_checkpoint) from e
                if any(r.get("done") for r in round_results):
                    done = True
                    for r in round_results:
                        if r.get("error"):
                            final_error = cloudpickle.loads(r["error"])
                    break
                rank0 = round_results[0]
                history.append(rank0["metrics"])
                ckpts = [r.get("checkpoint") for r in round_results]
                if ckpts and ckpts[0]:
                    latest_checkpoint = ckpts[0]  # rank 0's checkpoint wins
                elif any(ckpts):
                    latest_checkpoint = next(c for c in ckpts if c)
                self._apply_keep_policy(trial_dir)
                if tune_session is not None:
                    # stream the round to the owning Tune trial (lockstep,
                    # same contract as session.report)
                    tune_session.result_queue.put({
                        "metrics": dict(rank0["metrics"]),
                        "checkpoint": latest_checkpoint,
                        "done": False,
                    })
                    tune_session.continue_event.wait()
                    tune_session.continue_event.clear()
                    if tune_session.stop_requested:
                        from ray_tpu.train.session import SessionStopped

                        # unwind through _run_attempt's finally: gang +
                        # placement group released before the trial stops
                        raise SessionStopped()
            if final_error is not None:
                raise _AttemptFailed(final_error, latest_checkpoint)
            return Result(
                metrics=history[-1] if history else {},
                checkpoint=Checkpoint(latest_checkpoint) if latest_checkpoint else None,
                error=None,
                metrics_history=history,
            )
        finally:
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:  # noqa: BLE001
                    pass
            if pg is not None:
                try:
                    remove_placement_group(pg)
                except Exception:  # noqa: BLE001
                    pass

    def _apply_keep_policy(self, trial_dir: str) -> None:
        keep = self.run_config.checkpoint_config.num_to_keep
        if not keep:
            return
        import shutil

        # Dirs are checkpoint_<step>_rank<r>: group per STEP so num_to_keep
        # counts checkpoints, not per-rank shards (W ranks would otherwise
        # shrink the window to num_to_keep/W steps).
        groups: Dict[str, List[str]] = {}
        for entry in os.listdir(trial_dir):
            if not entry.startswith("checkpoint_"):
                continue
            step_key = entry.split("_rank")[0]
            groups.setdefault(step_key, []).append(entry)
        ordered = sorted(
            groups,
            key=lambda s: max(os.path.getmtime(os.path.join(trial_dir, e))
                              for e in groups[s]),
        )
        for stale_step in ordered[:-keep]:
            for entry in groups[stale_step]:
                shutil.rmtree(os.path.join(trial_dir, entry), ignore_errors=True)


class _AttemptFailed(Exception):
    def __init__(self, error: BaseException, latest_checkpoint: Optional[str]):
        self.error = error
        self.latest_checkpoint = latest_checkpoint
        super().__init__(str(error))
