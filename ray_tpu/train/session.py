"""Per-worker training session: report/checkpoint plumbing.

Reference capability: python/ray/train/_internal/session.py (_TrainSession:
ray.train.report:667 metrics+checkpoint queue between the user's training
thread and the worker actor; get_checkpoint:754). The user training function
runs on a thread inside the TrainWorker actor; ``report()`` hands
(metrics, checkpoint) to the actor, which the trainer collects in lockstep
rounds.
"""

from __future__ import annotations

import os
import queue
import shutil
import tempfile
import threading
import uuid
from dataclasses import dataclass
from typing import Any, Dict, Optional


class Checkpoint:
    """A directory of files on shared/local storage (reference:
    train/_checkpoint.py — pyarrow-fs backed; local fs tier here)."""

    def __init__(self, path: str):
        self.path = path

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(os.path.abspath(path))

    def to_directory(self, dest: Optional[str] = None) -> str:
        if dest is None:
            return self.path
        os.makedirs(dest, exist_ok=True)
        shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def as_directory(self):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            yield self.path

        return ctx()

    def __repr__(self) -> str:
        return f"Checkpoint({self.path})"


@dataclass
class TrainContext:
    world_rank: int
    world_size: int
    local_rank: int
    local_world_size: int
    node_rank: int
    experiment_name: str
    storage_path: str
    trial_dir: str


class SessionStopped(BaseException):
    """Raised inside the training thread when the controller stops the
    session (BaseException so user ``except Exception`` blocks can't swallow
    it; the stack unwinds through the trainable, releasing gangs/PGs)."""


class _Session:
    def __init__(self, ctx: TrainContext, latest_checkpoint: Optional[Checkpoint],
                 dataset_shards: Optional[Dict[str, Any]] = None):
        self.ctx = ctx
        self.latest_checkpoint = latest_checkpoint
        self.dataset_shards = dataset_shards or {}
        self.result_queue: "queue.Queue[Dict[str, Any]]" = queue.Queue()
        self.continue_event = threading.Event()
        self.finished = False
        self.stop_requested = False
        self.error: Optional[BaseException] = None

    def report(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None) -> None:
        persisted: Optional[str] = None
        if checkpoint is not None:
            # persist into the run's storage under a unique dir (all ranks may
            # report; rank subdir avoids clobbering — trainer keeps rank-0)
            step_dir = os.path.join(
                self.ctx.trial_dir,
                f"checkpoint_{metrics.get('step', metrics.get('epoch', uuid.uuid4().hex[:6]))}"
                f"_rank{self.ctx.world_rank}",
            )
            if os.path.abspath(checkpoint.path) != os.path.abspath(step_dir):
                os.makedirs(os.path.dirname(step_dir), exist_ok=True)
                shutil.copytree(checkpoint.path, step_dir, dirs_exist_ok=True)
            persisted = step_dir
        self.result_queue.put({"metrics": dict(metrics), "checkpoint": persisted, "done": False})
        # lockstep with the trainer's collection round
        self.continue_event.wait()
        self.continue_event.clear()
        if self.stop_requested:
            raise SessionStopped()

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.latest_checkpoint


# Sessions are keyed by the TRAINING THREAD (not process-global): in local
# mode several TrainWorker actors share one process, each running its user fn
# on its own thread, and report() must resolve to the caller's own session.
_sessions: Dict[int, _Session] = {}
_session_lock = threading.Lock()


def _bind_session_to_current_thread(s: _Session) -> None:
    with _session_lock:
        _sessions[threading.get_ident()] = s


def _unbind_current_thread() -> None:
    with _session_lock:
        _sessions.pop(threading.get_ident(), None)


def _get_session() -> Optional[_Session]:
    with _session_lock:
        return _sessions.get(threading.get_ident())


# ---------------------------------------------------------------- public api
def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None) -> None:
    s = _get_session()
    if s is None:
        raise RuntimeError("ray_tpu.train.report() called outside a training session")
    s.report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    s = _get_session()
    return s.get_checkpoint() if s else None


def get_context() -> TrainContext:
    s = _get_session()
    if s is None:
        raise RuntimeError("no active training session")
    return s.ctx


def world_rank() -> int:
    return get_context().world_rank


def world_size() -> int:
    return get_context().world_size


def get_dataset_shard(name: str = "train"):
    """This worker's shard of a dataset passed via TpuTrainer(datasets={...})
    (reference: ray.train.get_dataset_shard over streaming_split)."""
    s = _get_session()
    if s is None:
        raise RuntimeError("no active training session")
    shard = s.dataset_shards.get(name)
    if shard is None:
        raise KeyError(
            f"no dataset named {name!r}; available: {sorted(s.dataset_shards)}"
        )
    return shard
