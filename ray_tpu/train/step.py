"""Sharded train/eval step construction.

The compiled-step analogue of the reference's Train worker loop (reference:
python/ray/train/_internal/session.py — but there the step is torch eager +
NCCL allreduce; here the WHOLE step, gradients + optimizer + collectives, is
one pjit-compiled XLA program over the mesh: gradients reduce over (dp, fsdp)
via XLA's sharding propagation, parameters/optimizer state stay sharded per
the logical rules).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models.llama import LlamaConfig, cross_entropy_loss, llama_forward, llama_init, llama_logical_axes, llama_loss
from ray_tpu.parallel.sharding import (
    DEFAULT_LLM_RULES,
    ShardingRules,
    axes_is_leaf,
    logical_sharding,
)


class TrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def default_optimizer(
    lr: float = 3e-4,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
    warmup_steps: int = 100,
    total_steps: int = 10000,
):
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=lr, warmup_steps=warmup_steps,
        decay_steps=max(total_steps, warmup_steps + 1), end_value=lr * 0.1,
    )
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def state_logical_axes(config: LlamaConfig, optimizer, sample_params=None) -> Any:
    """Logical axes for the full TrainState: optimizer moments mirror the
    param axes; scalars (step, counts) carry no axes."""
    param_axes = llama_logical_axes(config)
    if sample_params is None:
        sample_params = jax.eval_shape(lambda k: llama_init(config, k), jax.random.key(0))
    opt_shape = jax.eval_shape(optimizer.init, sample_params)

    # Optimizer moments mirror the params pytree nested somewhere inside the
    # optax state (e.g. state[1][0].mu['layers']['wq']). Match each optimizer
    # leaf to a param by KEY-PATH SUFFIX (never by shape — square weights
    # like wq/wo are shape-ambiguous): the trailing path of a moment leaf
    # equals the param's path. Scalars (count, step) get None (replicated).
    from jax.tree_util import tree_flatten_with_path

    def path_key(entry):
        return getattr(entry, "key", getattr(entry, "name", getattr(entry, "idx", None)))

    param_paths = {}
    flat_axes, _ = tree_flatten_with_path(param_axes, is_leaf=lambda v: isinstance(v, tuple))
    for path, axes in flat_axes:
        param_paths[tuple(path_key(p) for p in path)] = axes
    flat_pshapes, _ = tree_flatten_with_path(sample_params)
    param_shape_by_path = {
        tuple(path_key(p) for p in path): tuple(leaf.shape) for path, leaf in flat_pshapes
    }

    flat_opt, opt_treedef = tree_flatten_with_path(opt_shape)
    opt_axes_leaves = []
    for path, leaf in flat_opt:
        keys = tuple(path_key(p) for p in path)
        axes = None
        for i in range(len(keys)):
            suffix = keys[i:]
            if suffix in param_paths and param_shape_by_path[suffix] == tuple(leaf.shape):
                axes = param_paths[suffix]
                break
        opt_axes_leaves.append(axes)
    opt_axes_tree = jax.tree_util.tree_unflatten(opt_treedef, opt_axes_leaves)
    return TrainState(step=None, params=param_axes, opt_state=opt_axes_tree)


def _state_shardings(axes_tree, mesh, rules):
    import jax

    def to_sharding(a):
        if a is None:
            return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
        return logical_sharding(mesh, rules, a)

    return jax.tree.map(to_sharding, axes_tree, is_leaf=axes_is_leaf)


def make_train_state_factory(
    config: LlamaConfig,
    optimizer,
    mesh=None,
    rules: ShardingRules = DEFAULT_LLM_RULES,
) -> Callable[[jax.Array], TrainState]:
    """Returns init(key) -> sharded TrainState; when a mesh is given, init is
    jitted with sharded out_shardings so parameters are created directly in
    their shards (no host-side full materialization)."""

    def init(key) -> TrainState:
        params = llama_init(config, key)
        opt_state = optimizer.init(params)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state)

    if mesh is None:
        return jax.jit(init)
    axes = state_logical_axes(config, optimizer)
    out_shardings = _state_shardings(axes, mesh, rules)
    return jax.jit(init, out_shardings=out_shardings)


def make_train_step(
    config: LlamaConfig,
    optimizer,
    mesh=None,
    rules: ShardingRules = DEFAULT_LLM_RULES,
    donate: bool = True,
):
    """(state, tokens, targets) -> (state, metrics). tokens/targets: [B, S]."""

    def step_fn(state: TrainState, tokens, targets) -> Tuple[TrainState, Dict[str, jax.Array]]:
        def loss_fn(params):
            return llama_loss(params, tokens, targets, config, mesh=mesh, rules=rules)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(step=state.step + 1, params=new_params, opt_state=new_opt)
        return new_state, {"loss": loss, "grad_norm": gnorm, "step": new_state.step}

    donate_argnums = (0,) if donate else ()
    if mesh is None:
        return jax.jit(step_fn, donate_argnums=donate_argnums)
    from ray_tpu.parallel.mesh import batch_sharding_spec

    batch_sh = jax.sharding.NamedSharding(mesh, batch_sharding_spec())
    axes = state_logical_axes(config, optimizer)
    state_sh = _state_shardings(axes, mesh, rules)
    return jax.jit(
        step_fn,
        in_shardings=(state_sh, batch_sh, batch_sh),
        out_shardings=(state_sh, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())),
        donate_argnums=donate_argnums,
    )


def make_eval_step(config: LlamaConfig, mesh=None, rules: ShardingRules = DEFAULT_LLM_RULES):
    def eval_fn(params, tokens, targets):
        logits = llama_forward(params, tokens, config, mesh=mesh, rules=rules)
        return cross_entropy_loss(logits, targets)

    return jax.jit(eval_fn)
