"""Train configuration dataclasses.

Reference capability: python/ray/air/config.py (ScalingConfig, RunConfig,
FailureConfig, CheckpointConfig) — resource/topology terms are TPU-native:
workers are HOSTS of a slice, each holding ``tpus_per_worker`` chips, and
placement uses STRICT_PACK-on-slice so the gang shares one ICI domain.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    tpus_per_worker: int = 0
    cpus_per_worker: float = 1.0
    resources_per_worker: Dict[str, float] = field(default_factory=dict)
    placement_strategy: str = "PACK"

    def worker_resources(self) -> Dict[str, float]:
        res = {"CPU": float(self.cpus_per_worker), **self.resources_per_worker}
        if self.use_tpu or self.tpus_per_worker:
            res["TPU"] = float(self.tpus_per_worker or 1)
        return {k: v for k, v in res.items() if v}

    def bundles(self) -> list:
        return [self.worker_resources() for _ in range(self.num_workers)]


@dataclass
class FailureConfig:
    max_failures: int = 0


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.expanduser("~/ray_tpu_results")
        name = self.name or "train_run"
        return os.path.join(base, name)
