"""Operator CLI: ``python -m ray_tpu <command>``.

Reference capability: python/ray/scripts/scripts.py:2592-2652 (``ray start/
stop/status``) + the state/job CLIs. Session bookkeeping lives in
``~/.ray_tpu/session`` (JSON: gcs address + process-group ids) so ``stop``
can tear down what ``start`` launched.

Commands:
    start --head [--num-cpus N] [--num-tpus N] [--port P] [--resources k=v]
    start --address HOST:PORT [--num-cpus N] ...      (join existing cluster)
    stop
    status
    list nodes|actors|objects|tasks|jobs|pgs
    submit [--working-dir D] [--no-wait] -- CMD...
    logs JOB_ID [--follow]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
import uuid
from typing import Any, Dict, List, Optional

SESSION_FILE = os.path.expanduser("~/.ray_tpu/session")


def _load_session() -> Optional[Dict[str, Any]]:
    try:
        with open(SESSION_FILE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _save_session(data: Dict[str, Any]) -> None:
    """Merge with any existing session so a second `start` on the same
    machine (head + worker) doesn't orphan the first node's processes."""
    prev = _load_session() or {}
    data = dict(data)
    data["pids"] = prev.get("pids", []) + data.get("pids", [])
    data.setdefault("gcs_address", prev.get("gcs_address"))
    os.makedirs(os.path.dirname(SESSION_FILE), exist_ok=True)
    with open(SESSION_FILE, "w") as f:
        json.dump(data, f)


def _resolve_address(args) -> str:
    addr = getattr(args, "address", None) or os.environ.get("RAY_TPU_ADDRESS")
    if not addr:
        sess = _load_session()
        addr = sess["gcs_address"] if sess else None
    if not addr:
        sys.exit("no cluster address: pass --address, set RAY_TPU_ADDRESS, "
                 "or run `ray_tpu start --head` first")
    return addr


def _wait_ready(path: str, proc: subprocess.Popen, what: str, timeout=40.0) -> str:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            content = open(path).read().strip()
            if content:
                return content
        if proc.poll() is not None:
            sys.exit(f"{what} exited with {proc.returncode}")
        time.sleep(0.05)
    sys.exit(f"{what} did not become ready in {timeout}s")


def cmd_start(args) -> None:
    session_dir = args.session_dir or f"/tmp/ray_tpu/session-{uuid.uuid4().hex[:8]}"
    os.makedirs(session_dir, exist_ok=True)
    env = dict(os.environ)
    env["RAY_TPU_SESSION_DIR"] = session_dir
    procs: List[int] = []

    if args.head:
        ready = os.path.join(session_dir, "gcs.ready")
        gcs_log = open(os.path.join(session_dir, "gcs.log"), "ab")
        cmd = [sys.executable, "-m", "ray_tpu.core.gcs.server", "--ready-file", ready]
        if args.port:
            cmd += ["--port", str(args.port)]
        gcs = subprocess.Popen(cmd, env=env, stdout=gcs_log,
                               stderr=subprocess.STDOUT, start_new_session=True)
        gcs_address = _wait_ready(ready, gcs, "GCS")
        procs.append(gcs.pid)
    else:
        gcs_address = _resolve_address(args)

    ready = os.path.join(session_dir, f"agent-{uuid.uuid4().hex[:6]}.ready")
    agent_log = open(os.path.join(session_dir, "agent.log"), "ab")
    cmd = [
        sys.executable, "-m", "ray_tpu.core.node.agent",
        "--gcs", gcs_address, "--session-dir", session_dir,
        "--ready-file", ready,
    ]
    if args.num_cpus is not None:
        cmd += ["--num-cpus", str(args.num_cpus)]
    if args.num_tpus:
        cmd += ["--num-tpus", str(args.num_tpus)]
    for kv in args.resources or []:
        cmd += ["--resource", kv]
    for kv in args.labels or []:
        cmd += ["--label", kv]
    if args.head:
        cmd += ["--head"]
    agent = subprocess.Popen(cmd, env=env, stdout=agent_log,
                             stderr=subprocess.STDOUT, start_new_session=True)
    _wait_ready(ready, agent, "node agent")
    procs.append(agent.pid)

    _save_session({"gcs_address": gcs_address, "pids": procs,
                   "session_dir": session_dir})
    role = "head" if args.head else "worker"
    print(f"started {role} node; GCS at {gcs_address}")
    print(f"session dir: {session_dir}")
    if args.head:
        print(f'connect with: ray_tpu.init(address="{gcs_address}") '
              f"or RAY_TPU_ADDRESS={gcs_address}")


def cmd_stop(_args) -> None:
    sess = _load_session()
    if not sess:
        print("no session on record")
        return
    # stop running jobs first: they live in their own process groups, so the
    # pid kills below would otherwise orphan them against a dead cluster
    try:
        from ray_tpu.job.sdk import JobStatus, JobSubmissionClient

        client = JobSubmissionClient(sess["gcs_address"])
        for job in client.list_jobs():
            if job.get("status") == JobStatus.RUNNING:
                client.stop_job(job["job_id"])
                print(f"stopped job {job['job_id']}")
        client.close()
    except Exception:  # noqa: BLE001 - cluster may already be half-dead
        pass
    for pid in reversed(sess.get("pids", [])):
        try:
            os.killpg(os.getpgid(pid), signal.SIGKILL)
            print(f"killed process group {pid}")
        except ProcessLookupError:
            pass
        except Exception as e:  # noqa: BLE001
            print(f"failed to kill {pid}: {e}")
    try:
        os.unlink(SESSION_FILE)
    except OSError:
        pass


def _connect(args):
    import ray_tpu

    ray_tpu.init(address=_resolve_address(args))


def cmd_status(args) -> None:
    _connect(args)
    from ray_tpu.util import state

    s = state.cluster_summary()
    print(f"nodes alive:     {s['nodes']}")
    total, avail = s["resources_total"], s["resources_available"]
    for k in sorted(total):
        if k.startswith("node:"):
            continue
        print(f"  {k:<20} {avail.get(k, 0.0):.1f} / {total[k]:.1f}")
    d = s["debug"]
    print(f"actors: {d['actors']}  objects: {d['objects']}  "
          f"pgs: {d['pgs']}  tracked refs: {d['tracked_refs']}")
    print(f"gcs uptime: {d['uptime_s']:.0f}s")


def cmd_list(args) -> None:
    _connect(args)
    from ray_tpu.util import state

    what = args.what
    rows: Any
    if what == "nodes":
        rows = state.list_nodes()
    elif what == "actors":
        rows = state.list_actors()
    elif what == "objects":
        rows = state.list_objects()
    elif what == "tasks":
        rows = state.list_tasks()
    elif what == "jobs":
        rows = state.list_jobs()
    elif what == "pgs":
        rows = state.list_placement_groups()
    else:  # pragma: no cover - argparse restricts choices
        sys.exit(f"unknown listing {what}")
    print(json.dumps(rows, indent=2, default=str))


def _dashboard_address(args) -> str:
    _connect(args)
    from ray_tpu.core.worker import require_worker

    raw = require_worker().runtime.kv_get("dashboard:address")
    if not raw:
        sys.exit("no dashboard registered (head started with dashboard_port=-1?)")
    return raw.decode()


def cmd_dashboard(args) -> None:
    print(_dashboard_address(args))


def cmd_timeline(args) -> None:
    """Dump the cluster's chrome-trace timeline (reference: `ray timeline`,
    _private/profiling.py:20-40) — open the file in ui.perfetto.dev."""
    import urllib.request

    addr = _dashboard_address(args)
    with urllib.request.urlopen(f"{addr}/api/timeline", timeout=30) as resp:
        data = resp.read()
    out = args.output or "ray-tpu-timeline.json"
    with open(out, "wb") as f:
        f.write(data)
    n = len(json.loads(data).get("traceEvents", []))
    print(f"wrote {n} trace events to {out} (load in ui.perfetto.dev)")


def _stream_job_logs(client, job_id: str) -> str:
    """Follow a job's log via absolute offsets (a sliding tail would stop
    advancing past the tail window) until it reaches a terminal status.
    Returns the final status."""
    from ray_tpu.job.sdk import JobStatus

    offset = 0
    while True:
        status = client.get_job_status(job_id)
        while True:
            text, offset = client.read_job_logs_from(job_id, offset)
            if not text:
                break
            sys.stdout.write(text)
            sys.stdout.flush()
        if status in (JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.STOPPED):
            return status
        time.sleep(0.3)


def cmd_submit(args) -> None:
    from ray_tpu.job.sdk import JobStatus, JobSubmissionClient

    import shlex

    if not args.cmd or not " ".join(args.cmd).strip():
        sys.exit("usage: ray_tpu submit [options] -- CMD [ARGS...]")
    client = JobSubmissionClient(_resolve_address(args))
    # shlex.join: the agent re-splits with shlex.split, so argv boundaries
    # (paths/args with spaces) must survive the round trip
    entrypoint = shlex.join(args.cmd)
    job_id = client.submit_job(entrypoint, working_dir=args.working_dir)
    print(f"submitted {job_id}: {entrypoint}")
    if args.no_wait:
        return
    status = _stream_job_logs(client, job_id)
    print(f"\njob {job_id}: {status}")
    sys.exit(0 if status == JobStatus.SUCCEEDED else 1)


def cmd_logs(args) -> None:
    from ray_tpu.job.sdk import JobSubmissionClient

    client = JobSubmissionClient(_resolve_address(args))
    if not args.follow:
        sys.stdout.write(client.get_job_logs(args.job_id))
        return
    _stream_job_logs(client, args.job_id)


def cmd_serve(args) -> None:
    """`serve deploy/status/rollback` (reference: serve CLI -> schema flow)."""
    import json

    import ray_tpu
    from ray_tpu.serve import schema

    ray_tpu.init(address=_resolve_address(args), log_to_driver=False)
    if args.serve_command == "deploy":
        cfg = schema.load_yaml(args.config_file)
        status = schema.apply_config(cfg, wait_for_ready=not args.no_wait)
        print(json.dumps(status, indent=2))
        sys.exit(1 if status["errors"] else 0)
    if args.serve_command == "status":
        from ray_tpu import serve
        from ray_tpu.serve import api as serve_api

        out = {"config": schema.current_config()}
        try:
            serve_api._state["controller"] = ray_tpu.get_actor(
                "SERVE_CONTROLLER", namespace="serve")
            out["applications"] = serve.status()
        except ValueError:
            out["applications"] = {}
        print(json.dumps(out, indent=2, default=str))
        return
    if args.serve_command == "rollback":
        print(json.dumps(schema.rollback(), indent=2))
        return


def cmd_stack(args) -> None:
    """All thread stacks of every cluster component (reference: `ray stack`
    py-spy dumps; here interpreter-level via dump_stacks RPCs)."""
    from ray_tpu.core.rpc import SyncRpcClient

    addr = _resolve_address(args)
    gcs = SyncRpcClient(addr)
    try:
        print(f"=== GCS {addr} ===")
        print(gcs.call("dump_stacks", timeout=15.0))
        for n in gcs.call("get_nodes"):
            if not n.get("Alive"):
                continue
            agent_addr = n["NodeManagerAddress"]
            print(f"=== node agent {n['NodeID'][:8]} @ {agent_addr} ===")
            agent = SyncRpcClient(agent_addr)
            try:
                print(agent.call("dump_stacks", timeout=15.0))
                for worker_id, dump in (agent.call(
                        "dump_worker_stacks", timeout=30.0) or {}).items():
                    print(f"=== worker {worker_id[:12]} "
                          f"(node {n['NodeID'][:8]}) ===")
                    print(dump)
            finally:
                agent.close()
    finally:
        gcs.close()


def cmd_memory(args) -> None:
    """Object-table dump with sizes/locations/holders (reference:
    `ray memory` ref-count debugging)."""
    from ray_tpu.core.rpc import SyncRpcClient

    gcs = SyncRpcClient(_resolve_address(args))
    try:
        objs = gcs.call("list_objects", limit=args.limit)
    finally:
        gcs.close()
    total = sum(o["size"] or 0 for o in objs)
    print(f"{'OBJECT':48}  {'SIZE':>12}  {'LOCS':>4}  {'HOLDERS':>7}  LINEAGE")
    for o in sorted(objs, key=lambda x: -(x["size"] or 0)):
        print(f"{o['object_id'][:48]:48}  {o['size'] or 0:>12}  "
              f"{len(o['locations']):>4}  {o['holders']:>7}  "
              f"{'yes' if o['has_lineage'] else ''}")
    print(f"-- {len(objs)} objects, {total / 1e6:.1f} MB total")


def cmd_up(args) -> None:
    from ray_tpu.autoscaler import launcher

    state = launcher.up(launcher.load_config(args.config_file))
    print(json.dumps({k: state[k] for k in
                      ("cluster_name", "gcs_address", "session_dir")}, indent=2))
    print(f"exec with: ray_tpu exec {state['cluster_name']} -- CMD")


def cmd_down(args) -> None:
    from ray_tpu.autoscaler import launcher

    launcher.down(args.cluster_name)
    print(f"cluster '{args.cluster_name}' torn down")


def cmd_exec(args) -> None:
    from ray_tpu.autoscaler import launcher

    if not args.cmd:
        sys.exit("usage: ray_tpu exec NAME -- CMD [ARGS...]")
    proc = launcher.exec_cmd(args.cluster_name, args.cmd)
    sys.exit(proc.returncode)


def cmd_attach(args) -> None:
    from ray_tpu.autoscaler import launcher

    sys.exit(launcher.attach(args.cluster_name))


def main(argv: Optional[List[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="ray_tpu", description=__doc__)
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("up", help="launch a cluster from a YAML config")
    p.add_argument("config_file")
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("down", help="tear down a launched cluster")
    p.add_argument("cluster_name")
    p.set_defaults(fn=cmd_down)

    p = sub.add_parser("exec", help="run a command against a launched cluster")
    p.add_argument("cluster_name")
    p.add_argument("cmd", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_exec)

    p = sub.add_parser("attach", help="shell with the cluster env exported")
    p.add_argument("cluster_name")
    p.set_defaults(fn=cmd_attach)

    p = sub.add_parser("stack", help="dump all thread stacks of every component")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_stack)

    p = sub.add_parser("memory", help="object table: sizes/locations/holders")
    p.add_argument("--address", default=None)
    p.add_argument("--limit", type=int, default=1000)
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("start", help="start a head or worker node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", default=None, help="GCS address to join")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-cpus", type=int, default=None)
    p.add_argument("--num-tpus", type=int, default=0)
    p.add_argument("--resources", action="append", default=[])
    p.add_argument("--labels", action="append", default=[])
    p.add_argument("--session-dir", default=None)
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop nodes started on this machine")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("status", help="cluster summary")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("list", help="list cluster state")
    p.add_argument("what", choices=["nodes", "actors", "objects", "tasks", "jobs", "pgs"])
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("dashboard", help="print the dashboard HTTP address")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_dashboard)

    p = sub.add_parser("timeline", help="dump chrome-trace timeline JSON")
    p.add_argument("--address", default=None)
    p.add_argument("-o", "--output", default=None)
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("submit", help="submit a driver script as a job")
    p.add_argument("--address", default=None)
    p.add_argument("--working-dir", default=None)
    p.add_argument("--no-wait", action="store_true")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="entrypoint, e.g. -- python train.py")
    p.set_defaults(fn=cmd_submit)

    p = sub.add_parser("logs", help="fetch or follow job logs")
    p.add_argument("job_id")
    p.add_argument("--address", default=None)
    p.add_argument("--follow", action="store_true")
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("serve", help="declarative serve deploy/status/rollback")
    serve_sub = p.add_subparsers(dest="serve_command", required=True)
    sp = serve_sub.add_parser("deploy", help="apply a YAML app config")
    sp.add_argument("config_file")
    sp.add_argument("--address", default=None)
    sp.add_argument("--no-wait", action="store_true")
    sp.set_defaults(fn=cmd_serve)
    sp = serve_sub.add_parser("status", help="declarative config + app status")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_serve)
    sp = serve_sub.add_parser("rollback", help="revert to the previous config")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_serve)

    args = parser.parse_args(argv)
    if getattr(args, "cmd", None) and args.cmd and args.cmd[0] == "--":
        args.cmd = args.cmd[1:]
    args.fn(args)


if __name__ == "__main__":
    main()
