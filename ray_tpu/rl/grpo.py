"""GRPO: Group Relative Policy Optimization for LLM RLHF.

Capability named in BASELINE.json ("PPO/GRPO RLHF"); the reference covers
this space with rllib/ (torch policy classes + NCCL). TPU-first redesign:

- the ENTIRE update — per-token logprobs, clipped surrogate, KL penalty
  against the frozen reference policy, optimizer — is ONE pjit-compiled XLA
  program over the mesh (no eager policy objects);
- no value network: advantages are group-relative (sample G completions per
  prompt, normalize rewards within the group), which removes the critic's
  memory footprint — the feature that makes GRPO the TPU-friendly choice;
- rollouts come from the serve plane's continuous-batching engine
  (serve/llm.py), so training and inference share one decode path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import LlamaConfig, llama_hidden


@dataclasses.dataclass(frozen=True)
class GRPOConfig:
    group_size: int = 4
    clip_eps: float = 0.2
    kl_coef: float = 0.02
    temperature: float = 1.0
    max_new_tokens: int = 64
    epochs_per_batch: int = 1


def compute_group_advantages(rewards: jax.Array, eps: float = 1e-6) -> jax.Array:
    """rewards: [num_prompts, group_size] -> advantages, same shape,
    normalized WITHIN each prompt's group (the GRPO baseline)."""
    mean = rewards.mean(axis=-1, keepdims=True)
    std = rewards.std(axis=-1, keepdims=True)
    return (rewards - mean) / (std + eps)


def make_logprob_fn(config: LlamaConfig, mesh=None):
    """Returns logprobs(params, tokens) -> per-token logprob [B, T-1] of
    token t+1 given prefix..t. Vocab reduction uses a one-hot select (tp-
    sharded vocab partitions cleanly; a gather would force replication)."""

    def logprobs(params, tokens):
        x = llama_hidden(params, tokens, config, mesh=mesh)
        head = params.get("lm_head")
        if head is None:
            head = params["embed_tokens"].T.astype(config.dtype)
        logits = jax.lax.dot_general(
            x, head, (((2,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [B, T, V] fp32
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        nxt = tokens[:, 1:]
        onehot = jax.nn.one_hot(nxt, logits.shape[-1], dtype=logits.dtype)
        gold = jnp.sum(logits[:, :-1] * onehot, axis=-1)
        return gold - logz[:, :-1]

    return jax.jit(logprobs)


def grpo_loss(
    params,
    tokens,          # [N, T] int32 (prompt + completion, right-padded)
    completion_mask,  # [N, T-1] 1.0 where position t PREDICTS a completion token
    advantages,      # [N] group-relative advantage per sequence
    old_logprobs,    # [N, T-1] logprobs under the rollout policy
    ref_logprobs,    # [N, T-1] logprobs under the frozen reference policy
    config: LlamaConfig,
    clip_eps: float,
    kl_coef: float,
    mesh=None,
):
    x = llama_hidden(params, tokens, config, mesh=mesh)
    head = params.get("lm_head")
    if head is None:
        head = params["embed_tokens"].T.astype(config.dtype)
    logits = jax.lax.dot_general(
        x, head, (((2,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    onehot = jax.nn.one_hot(tokens[:, 1:], logits.shape[-1], dtype=logits.dtype)
    logp = jnp.sum(logits[:, :-1] * onehot, axis=-1) - logz[:, :-1]  # [N, T-1]

    ratio = jnp.exp(logp - old_logprobs)
    adv = advantages[:, None]
    unclipped = ratio * adv
    clipped = jnp.clip(ratio, 1.0 - clip_eps, 1.0 + clip_eps) * adv
    denom = jnp.maximum(completion_mask.sum(), 1.0)
    pg_loss = -jnp.sum(jnp.minimum(unclipped, clipped) * completion_mask) / denom

    # k3 KL estimator (unbiased, positive): exp(r) - r - 1, r = ref - policy
    r = ref_logprobs - logp
    kl = jnp.sum((jnp.exp(r) - r - 1.0) * completion_mask) / denom

    loss = pg_loss + kl_coef * kl
    return loss, {"pg_loss": pg_loss, "kl": kl,
                  "ratio_mean": jnp.sum(ratio * completion_mask) / denom}


def make_grpo_step(
    config: LlamaConfig,
    optimizer,
    grpo: GRPOConfig,
    mesh=None,
    donate: bool = True,
):
    """(state, batch) -> (state, metrics); batch = dict(tokens,
    completion_mask, advantages, old_logprobs, ref_logprobs). One compiled
    XLA program (gradients + optimizer + collectives), like train/step.py."""
    import optax

    from ray_tpu.train.step import TrainState

    def step_fn(state: TrainState, batch):
        def loss_fn(params):
            return grpo_loss(
                params, batch["tokens"], batch["completion_mask"],
                batch["advantages"], batch["old_logprobs"],
                batch["ref_logprobs"], config, grpo.clip_eps, grpo.kl_coef,
                mesh=mesh,
            )

        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(step=state.step + 1, params=new_params, opt_state=new_opt)
        return new_state, {"loss": loss, **aux, "step": new_state.step}

    donate_argnums = (0,) if donate else ()
    return jax.jit(step_fn, donate_argnums=donate_argnums)
