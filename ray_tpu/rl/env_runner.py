"""Env-runner platform: distributed rollout collection with fault tolerance.

Reference capability: rllib/env/env_runner_group.py (EnvRunnerGroup) +
rllib/utils/actor_manager.py (FaultTolerantActorManager — probe health,
restart dead workers, keep sampling through failures). Redesign: runners
are plain actors hosting a vectorized env loop; the group broadcasts policy
params through the object store (one put per sync, every runner reads the
same ref — the arena store makes this zero-copy on-node) and gathers sample
batches, restarting any runner whose actor died and resubmitting its share.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl.env import make_env
from ray_tpu.utils.logging import get_logger

logger = get_logger("rl.env_runner")


@ray_tpu.remote
class EnvRunner:
    """One rollout worker: steps its env with an epsilon-greedy/sampled
    policy and returns transition batches (reference: single_agent_env_runner
    .py)."""

    def __init__(self, env_name: str, policy_builder: Callable,
                 env_config: Optional[Dict[str, Any]] = None,
                 worker_index: int = 0, seed: int = 0):
        self.env = make_env(env_name, **(env_config or {}))
        # policy_builder() -> callable(params, obs_batch) -> actions [B]
        self.policy = policy_builder()
        self.worker_index = worker_index
        self.rng = np.random.default_rng(seed + worker_index)
        self._obs, _ = self.env.reset(seed=seed + worker_index)
        self._episode_return = 0.0
        self._episode_len = 0
        self._completed: List[Dict[str, float]] = []

    def sample(self, params, num_steps: int,
               explore: float = 0.0) -> Dict[str, Any]:
        """Collect num_steps transitions (episodes roll over)."""
        obs_l, act_l, rew_l, next_l, done_l = [], [], [], [], []
        for _ in range(num_steps):
            if self.rng.random() < explore:
                action = int(self.rng.integers(self.env.num_actions))
            else:
                action = int(self.policy(params, self._obs[None])[0])
            nxt, reward, terminated, truncated, _ = self.env.step(action)
            obs_l.append(self._obs)
            act_l.append(action)
            rew_l.append(reward)
            next_l.append(nxt)
            done_l.append(terminated)
            self._episode_return += reward
            self._episode_len += 1
            if terminated or truncated:
                self._completed.append({
                    "episode_return": self._episode_return,
                    "episode_len": self._episode_len,
                })
                self._episode_return = 0.0
                self._episode_len = 0
                self._obs, _ = self.env.reset()
            else:
                self._obs = nxt
        episodes, self._completed = self._completed, []
        return {
            "obs": np.asarray(obs_l, np.float32),
            "actions": np.asarray(act_l, np.int64),
            "rewards": np.asarray(rew_l, np.float32),
            "next_obs": np.asarray(next_l, np.float32),
            "dones": np.asarray(done_l, np.float32),
            "episodes": episodes,
            "worker_index": self.worker_index,
        }

    def ping(self) -> str:
        return "ok"


class EnvRunnerGroup:
    """N EnvRunner actors with restart-on-failure sampling (reference:
    EnvRunnerGroup over FaultTolerantActorManager)."""

    def __init__(self, env_name: str, policy_builder: Callable,
                 num_runners: int = 2,
                 env_config: Optional[Dict[str, Any]] = None, seed: int = 0,
                 max_restarts: int = 3):
        self.env_name = env_name
        self.policy_builder = policy_builder
        self.env_config = env_config
        self.seed = seed
        self.max_restarts = max_restarts
        self._restarts = 0
        self._runners: List[Any] = [
            self._start(i) for i in range(num_runners)
        ]

    def _start(self, index: int):
        return EnvRunner.options(max_restarts=0).remote(
            self.env_name, self.policy_builder, self.env_config,
            worker_index=index, seed=self.seed,
        )

    def sample(self, params_ref, steps_per_runner: int,
               explore: float = 0.0,
               timeout: float = 120.0) -> List[Dict[str, Any]]:
        """One synchronous sampling round. A dead runner is restarted and
        its share re-collected (up to max_restarts per group lifetime)."""
        out: List[Dict[str, Any]] = []
        pending = list(range(len(self._runners)))
        while pending:
            refs = {i: self._runners[i].sample.remote(
                params_ref, steps_per_runner, explore) for i in pending}
            failed: List[int] = []
            for i, ref in refs.items():
                try:
                    out.append(ray_tpu.get(ref, timeout=timeout))
                except Exception:  # noqa: BLE001 - actor death / timeout
                    failed.append(i)
            if not failed:
                break
            if self._restarts + len(failed) > self.max_restarts:
                raise RuntimeError(
                    f"env runners failed more than {self.max_restarts} times")
            for i in failed:
                logger.warning("restarting env runner %d", i)
                try:
                    ray_tpu.kill(self._runners[i])
                except Exception:  # noqa: BLE001
                    pass
                self._runners[i] = self._start(i)
                self._restarts += 1
            pending = failed
        return out

    def stop(self) -> None:
        for r in self._runners:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass
