"""IMPALA: asynchronous off-policy actor-critic with V-trace correction.

Reference capability: rllib/algorithms/impala/impala.py (Espeholt '18 —
actors stream trajectory unrolls ahead of the learner; the learner corrects
the resulting off-policyness with V-trace importance weighting). Redesign
on this runtime's primitives:

- each actor is a ``num_returns="streaming"`` remote GENERATOR
  (core/streaming.py): it rolls its env forever and yields fixed-length
  unrolls, with generator backpressure bounding how far a runner can run
  ahead of the learner — the queue the reference builds from aioqueues
  falls out of the streaming machinery;
- behavior-policy logits ride inside each unroll, so the learner computes
  clipped importance ratios against its CURRENT policy (V-trace rho/c);
- runners refresh params from the GCS KV every few unrolls (stale-policy
  lag is the point of IMPALA — V-trace absorbs it);
- the update is ONE jitted program: forward over the [B, T] batch,
  V-trace via a backward ``lax.scan``, policy-gradient + value + entropy
  losses, optax step. TPU-first: batch unrolls, static [B, T] shapes.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl.env import make_env
from ray_tpu.utils.logging import get_logger

logger = get_logger("rl.impala")

PARAMS_KEY = "impala:params"


@dataclass
class ImpalaConfig:
    env: str = "CartPole-rt"
    env_config: Dict[str, Any] = field(default_factory=dict)
    hidden: tuple = (128, 128)
    lr: float = 5e-4
    gamma: float = 0.99
    unroll_len: int = 32           # T: steps per yielded trajectory piece
    num_runners: int = 2
    batch_unrolls: int = 8         # B: unrolls per learner update
    rho_clip: float = 1.0          # V-trace rho-bar (IS clip for deltas/pg)
    c_clip: float = 1.0            # V-trace c-bar (trace cutting)
    entropy_coef: float = 0.01
    value_coef: float = 0.5
    param_refresh_unrolls: int = 1  # runner pulls params every N unrolls
    max_queue_unrolls: int = 8     # backpressure: max unrolls a runner runs ahead
    seed: int = 0


# ------------------------------------------------------------ actor-critic
def ac_init(obs_dim: int, num_actions: int, hidden, key):
    import jax
    import jax.numpy as jnp

    sizes = (obs_dim,) + tuple(hidden)
    trunk = []
    for a, b in zip(sizes[:-1], sizes[1:]):
        key, k = jax.random.split(key)
        trunk.append({
            "w": jax.random.normal(k, (a, b), jnp.float32) * (2.0 / a) ** 0.5,
            "b": jnp.zeros((b,), jnp.float32),
        })
    key, k1, k2 = jax.random.split(key, 3)
    return {
        "trunk": trunk,
        "pi": {"w": jax.random.normal(k1, (sizes[-1], num_actions),
                                      jnp.float32) * 0.01,
               "b": jnp.zeros((num_actions,), jnp.float32)},
        "v": {"w": jax.random.normal(k2, (sizes[-1], 1), jnp.float32) * 0.01,
              "b": jnp.zeros((1,), jnp.float32)},
    }


def ac_forward(params, obs):
    """obs [..., obs_dim] -> (logits [..., A], value [...])."""
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(obs, jnp.float32)
    for layer in params["trunk"]:
        x = jax.nn.relu(x @ layer["w"] + layer["b"])
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["v"]["w"] + params["v"]["b"])[..., 0]
    return logits, value


# ----------------------------------------------------------------- V-trace
def vtrace(behavior_logp, target_logp, rewards, values, bootstrap, dones,
           gamma: float, rho_clip: float, c_clip: float):
    """All inputs [B, T] (bootstrap [B]). Returns (vs [B,T], pg_adv [B,T]).

    vs_t = V_t + sum_{k>=t} gamma^{k-t} (prod_{i<k} c_i) delta_k,
    delta_k = rho_k (r_k + gamma V_{k+1} (1-d_k) - V_k), computed with a
    single backward lax.scan (compiler-friendly, no python loop over T).
    """
    import jax
    import jax.numpy as jnp

    rho = jnp.minimum(jnp.exp(target_logp - behavior_logp), rho_clip)
    c = jnp.minimum(jnp.exp(target_logp - behavior_logp), c_clip)
    not_done = 1.0 - dones
    v_next = jnp.concatenate([values[:, 1:], bootstrap[:, None]], axis=1)
    deltas = rho * (rewards + gamma * v_next * not_done - values)

    def backward(acc, xs):
        delta_t, c_t, nd_t = xs
        acc = delta_t + gamma * nd_t * c_t * acc
        return acc, acc

    # scan over time reversed; per-batch handled by vmap-free transpose
    _, accs = jax.lax.scan(
        backward,
        jnp.zeros_like(bootstrap),
        (deltas.T[::-1], c.T[::-1], not_done.T[::-1]),
    )
    vs_minus_v = accs[::-1].T
    vs = values + vs_minus_v
    vs_next = jnp.concatenate([vs[:, 1:], bootstrap[:, None]], axis=1)
    pg_adv = rho * (rewards + gamma * vs_next * not_done - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


def make_impala_update(config: ImpalaConfig, optimizer):
    import jax
    import jax.numpy as jnp
    import optax

    def loss_fn(params, batch):
        # batch: obs [B,T,O], next_obs_last [B,O], actions [B,T],
        # rewards [B,T], dones [B,T], behavior_logits [B,T,A]
        logits, values = ac_forward(params, batch["obs"])
        _, bootstrap = ac_forward(params, batch["next_obs_last"])
        logp_all = jax.nn.log_softmax(logits)
        act = batch["actions"][..., None]
        target_logp = jnp.take_along_axis(logp_all, act, -1)[..., 0]
        behavior_logp = jnp.take_along_axis(
            jax.nn.log_softmax(batch["behavior_logits"]), act, -1)[..., 0]
        vs, pg_adv = vtrace(
            behavior_logp, target_logp, batch["rewards"], values,
            bootstrap, batch["dones"], config.gamma, config.rho_clip,
            config.c_clip,
        )
        pg_loss = -jnp.mean(target_logp * pg_adv)
        v_loss = 0.5 * jnp.mean((vs - values) ** 2)
        entropy = -jnp.mean(
            jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        loss = (pg_loss + config.value_coef * v_loss
                - config.entropy_coef * entropy)
        return loss, {"pg_loss": pg_loss, "v_loss": v_loss,
                      "entropy": entropy,
                      "mean_rho": jnp.mean(jnp.exp(target_logp - behavior_logp))}

    @jax.jit
    def update(params, opt_state, batch):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, aux

    return update


# ---------------------------------------------------------- streaming actor
def _make_rollout_stream(config: ImpalaConfig):
    """Returns the streaming remote function: an infinite generator of
    unrolls. Created per-trainer so backpressure rides the remote options."""

    @ray_tpu.remote(num_returns="streaming",
                    _generator_backpressure=config.max_queue_unrolls,
                    name="impala::rollout_stream")
    def rollout_stream(worker_index: int, num_unrolls: int):
        import cloudpickle
        import jax
        import jax.numpy as jnp

        env = make_env(config.env, **config.env_config)
        rng = np.random.default_rng(config.seed + worker_index)
        params = cloudpickle.loads(ray_tpu.kv_get(PARAMS_KEY))
        fwd = jax.jit(ac_forward)
        obs, _ = env.reset(seed=config.seed + worker_index)
        ep_ret, ep_len, completed = 0.0, 0, []
        for unroll_idx in range(num_unrolls):
            if unroll_idx % config.param_refresh_unrolls == 0 and unroll_idx:
                raw = ray_tpu.kv_get(PARAMS_KEY)
                if raw is not None:
                    params = cloudpickle.loads(raw)
            T = config.unroll_len
            obs_l, act_l, rew_l, done_l, logits_l = [], [], [], [], []
            for _ in range(T):
                logits, _v = fwd(params, jnp.asarray(obs[None]))
                logits = np.asarray(logits[0])
                # sample from the behavior policy (exploration comes from
                # the policy's own entropy, kept up by the entropy bonus)
                z = logits - logits.max()
                p = np.exp(z) / np.exp(z).sum()
                action = int(rng.choice(len(p), p=p))
                nxt, reward, terminated, truncated, _ = env.step(action)
                obs_l.append(obs)
                act_l.append(action)
                rew_l.append(reward)
                # truncations also cut the trace: the next stored obs is the
                # RESET state of a new episode, so bootstrapping across the
                # boundary would leak the wrong episode's value into V-trace
                # targets (slightly pessimistic near time limits, never biased
                # by cross-episode leakage)
                done_l.append(float(terminated or truncated))
                logits_l.append(logits)
                ep_ret += reward
                ep_len += 1
                if terminated or truncated:
                    completed.append({"episode_return": ep_ret,
                                      "episode_len": ep_len})
                    ep_ret, ep_len = 0.0, 0
                    obs, _ = env.reset()
                else:
                    obs = nxt
            episodes, completed = completed, []
            yield {
                "obs": np.asarray(obs_l, np.float32),
                "next_obs_last": np.asarray(obs, np.float32),
                "actions": np.asarray(act_l, np.int64),
                "rewards": np.asarray(rew_l, np.float32),
                "dones": np.asarray(done_l, np.float32),
                "behavior_logits": np.asarray(logits_l, np.float32),
                "episodes": episodes,
                "worker_index": worker_index,
            }

    return rollout_stream


class ImpalaTrainer:
    """Learner loop: consume unroll streams round-robin, batch them, run the
    jitted V-trace update, publish fresh params to KV. train() returns
    rllib-style result dicts (+ env_steps_per_s, the IMPALA headline)."""

    def __init__(self, config: ImpalaConfig, total_unrolls_per_runner: int = 10_000):
        import cloudpickle
        import jax
        import optax

        self.config = config
        probe = make_env(config.env, **config.env_config)
        self.params = ac_init(probe.obs_dim, probe.num_actions,
                              config.hidden, jax.random.key(config.seed))
        self.optimizer = optax.adamw(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._update = make_impala_update(config, self.optimizer)
        ray_tpu.kv_put(PARAMS_KEY, cloudpickle.dumps(
            jax.device_get(self.params)))
        stream_fn = _make_rollout_stream(config)
        self._streams = [
            iter(stream_fn.remote(i, total_unrolls_per_runner))
            for i in range(config.num_runners)
        ]
        self.iteration = 0
        self._episode_returns: List[float] = []
        self._env_steps = 0

    def _next_unrolls(self, n: int, timeout: float = 120.0) -> List[Dict]:
        """Round-robin pull across runner streams; a finished/failed stream
        is dropped (remaining runners keep the learner fed — the reference's
        aggregator keeps sampling through worker failures)."""
        out: List[Dict] = []
        while len(out) < n and self._streams:
            for it in list(self._streams):
                if len(out) >= n:
                    break
                try:
                    ref = next(it)
                    out.append(ray_tpu.get(ref, timeout=timeout))
                except StopIteration:
                    self._streams.remove(it)
                except Exception:  # noqa: BLE001 - runner died mid-stream
                    logger.warning("dropping failed rollout stream",
                                   exc_info=True)
                    self._streams.remove(it)
        if not out:
            raise RuntimeError("all rollout streams ended")
        return out

    def train(self) -> Dict[str, Any]:
        import cloudpickle
        import jax
        import numpy as np  # noqa: F811 - jitted closure uses module numpy

        c = self.config
        t0 = time.perf_counter()
        unrolls = self._next_unrolls(c.batch_unrolls)
        batch = {
            k: np.stack([u[k] for u in unrolls])
            for k in ("obs", "next_obs_last", "actions", "rewards", "dones",
                      "behavior_logits")
        }
        for u in unrolls:
            self._episode_returns.extend(
                e["episode_return"] for e in u["episodes"])
            self._env_steps += len(u["actions"])
        self.params, self.opt_state, loss, aux = self._update(
            self.params, self.opt_state, batch)
        ray_tpu.kv_put(PARAMS_KEY, cloudpickle.dumps(
            jax.device_get(self.params)))
        self.iteration += 1
        dt = time.perf_counter() - t0
        recent = self._episode_returns[-20:]
        return {
            "training_iteration": self.iteration,
            "loss": float(loss),
            "pg_loss": float(aux["pg_loss"]),
            "v_loss": float(aux["v_loss"]),
            "entropy": float(aux["entropy"]),
            "mean_rho": float(aux["mean_rho"]),
            "env_steps_total": self._env_steps,
            "env_steps_this_iter": c.batch_unrolls * c.unroll_len,
            "env_steps_per_s": c.batch_unrolls * c.unroll_len / max(dt, 1e-9),
            "episode_return_mean": float(np.mean(recent)) if recent else None,
            "num_episodes": len(self._episode_returns),
            "time_this_iter_s": dt,
        }

    def stop(self) -> None:
        for it in self._streams:
            try:
                it.close()
            except Exception:  # noqa: BLE001
                pass
        self._streams = []
