from ray_tpu.rl.dqn import DQNConfig, DQNTrainer
from ray_tpu.rl.env import CartPoleEnv, ChainEnv, make_env, register_env
from ray_tpu.rl.env_runner import EnvRunner, EnvRunnerGroup
from ray_tpu.rl.impala import (
    ImpalaConfig,
    ImpalaTrainer,
    ac_forward,
    ac_init,
    vtrace,
)
from ray_tpu.rl.grpo import (
    GRPOConfig,
    compute_group_advantages,
    make_grpo_step,
    make_logprob_fn,
)
from ray_tpu.rl.ppo import PPOConfig, gae_advantages, make_ppo_step
from ray_tpu.rl.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rl.trainer import GRPOTrainer

__all__ = [
    "CartPoleEnv", "ChainEnv", "DQNConfig", "DQNTrainer", "EnvRunner",
    "EnvRunnerGroup", "GRPOConfig", "GRPOTrainer", "ImpalaConfig",
    "ImpalaTrainer", "PPOConfig",
    "PrioritizedReplayBuffer", "ReplayBuffer", "ac_forward", "ac_init",
    "compute_group_advantages", "gae_advantages",
    "make_env", "make_grpo_step", "make_logprob_fn", "make_ppo_step",
    "register_env", "vtrace",
]
