from ray_tpu.rl.grpo import (
    GRPOConfig,
    compute_group_advantages,
    make_grpo_step,
    make_logprob_fn,
)
from ray_tpu.rl.ppo import PPOConfig, gae_advantages, make_ppo_step
from ray_tpu.rl.trainer import GRPOTrainer

__all__ = [
    "GRPOConfig", "GRPOTrainer", "PPOConfig",
    "compute_group_advantages", "gae_advantages",
    "make_grpo_step", "make_logprob_fn", "make_ppo_step",
]
