"""PPO for LLM policies: clipped surrogate + value head + GAE.

Reference capability: rllib PPO (rllib/algorithms/ppo) — torch policies,
sample batches, NCCL allreduce. TPU-first: the value function is a linear
head on the SAME trunk (no second model), GAE runs as a lax.scan, and the
whole update is one jitted program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import LlamaConfig, llama_hidden


@dataclasses.dataclass(frozen=True)
class PPOConfig:
    clip_eps: float = 0.2
    value_clip: float = 0.2
    value_coef: float = 0.5
    entropy_coef: float = 0.0
    gamma: float = 1.0
    lam: float = 0.95
    epochs_per_batch: int = 2


def init_value_head(config: LlamaConfig, key) -> Dict[str, Any]:
    h = config.hidden_size
    return {"w": (jax.random.normal(key, (h,), jnp.float32) * h**-0.5),
            "b": jnp.zeros((), jnp.float32)}


def value_estimates(params, value_head, tokens, config: LlamaConfig, mesh=None):
    """Per-position value V(s_t): linear head on the trunk hidden states."""
    x = llama_hidden(params, tokens, config, mesh=mesh)
    return x.astype(jnp.float32) @ value_head["w"] + value_head["b"]  # [B, T]


def gae_advantages(rewards, values, mask, gamma: float, lam: float):
    """Generalized Advantage Estimation over token positions.

    rewards/values/mask: [B, T] fp32 (mask zeros out padding). Returns
    (advantages [B, T], returns [B, T]). Runs as a reverse lax.scan — no
    per-token Python loop."""
    b, t = rewards.shape
    next_values = jnp.concatenate([values[:, 1:], jnp.zeros((b, 1))], axis=1)
    # Bootstrap with the validity of position t+1, not t: the last unmasked
    # step must bootstrap from 0, not from V evaluated on padding.
    next_mask = jnp.concatenate([mask[:, 1:], jnp.zeros((b, 1))], axis=1)
    deltas = (rewards + gamma * next_values * next_mask - values) * mask

    def body(carry, xs):
        delta_t, mask_t = xs
        carry = delta_t + gamma * lam * mask_t * carry
        return carry, carry

    _, adv_rev = jax.lax.scan(
        body, jnp.zeros(b), (deltas.T[::-1], mask.T[::-1])
    )
    advantages = adv_rev[::-1].T * mask
    return advantages, advantages + values * mask


def ppo_loss(params, value_head, batch, config: LlamaConfig, ppo: PPOConfig, mesh=None):
    tokens = batch["tokens"]              # [B, T]
    mask = batch["mask"]                  # [B, T-1] action positions
    old_logp = batch["old_logprobs"]      # [B, T-1]
    advantages = batch["advantages"]      # [B, T-1]
    returns = batch["returns"]            # [B, T-1]
    old_values = batch["old_values"]      # [B, T-1]

    x = llama_hidden(params, tokens, config, mesh=mesh)
    head = params.get("lm_head")
    if head is None:
        head = params["embed_tokens"].T.astype(config.dtype)
    logits = jax.lax.dot_general(
        x, head, (((2,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    onehot = jax.nn.one_hot(tokens[:, 1:], logits.shape[-1], dtype=logits.dtype)
    logp = jnp.sum(logits[:, :-1] * onehot, axis=-1) - logz[:, :-1]
    values = (x[:, :-1].astype(jnp.float32) @ value_head["w"] + value_head["b"])

    denom = jnp.maximum(mask.sum(), 1.0)
    # normalized advantages (standard PPO practice)
    amean = jnp.sum(advantages * mask) / denom
    astd = jnp.sqrt(jnp.sum(((advantages - amean) * mask) ** 2) / denom) + 1e-6
    adv = (advantages - amean) / astd

    ratio = jnp.exp(logp - old_logp)
    pg = -jnp.sum(jnp.minimum(
        ratio * adv, jnp.clip(ratio, 1 - ppo.clip_eps, 1 + ppo.clip_eps) * adv
    ) * mask) / denom

    v_clipped = old_values + jnp.clip(values - old_values,
                                      -ppo.value_clip, ppo.value_clip)
    v_loss = 0.5 * jnp.sum(
        jnp.maximum((values - returns) ** 2, (v_clipped - returns) ** 2) * mask
    ) / denom

    probs = jax.nn.softmax(logits[:, :-1], axis=-1)
    entropy = -jnp.sum(jnp.sum(probs * jnp.where(probs > 0, jnp.log(probs), 0.0), -1)
                       * mask) / denom

    loss = pg + ppo.value_coef * v_loss - ppo.entropy_coef * entropy
    return loss, {"pg_loss": pg, "value_loss": v_loss, "entropy": entropy}


def make_ppo_step(config: LlamaConfig, optimizer, ppo: PPOConfig, mesh=None,
                  donate: bool = True):
    """(state, value_head, vh_opt_state, batch) -> updated triple + metrics.
    Policy and value head update jointly in one compiled program."""
    import optax

    from ray_tpu.train.step import TrainState

    def step_fn(state: TrainState, value_head, vh_opt, batch):
        def loss_fn(params, vh):
            return ppo_loss(params, vh, batch, config, ppo, mesh=mesh)

        (loss, aux), grads = jax.value_and_grad(loss_fn, argnums=(0, 1),
                                                has_aux=True)(state.params, value_head)
        pgrads, vgrads = grads
        updates, new_opt = optimizer.update(pgrads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        vh_updates, new_vh_opt = optimizer.update(vgrads, vh_opt, value_head)
        new_vh = optax.apply_updates(value_head, vh_updates)
        new_state = TrainState(step=state.step + 1, params=new_params,
                               opt_state=new_opt)
        return new_state, new_vh, new_vh_opt, {"loss": loss, **aux}

    donate_argnums = (0, 1, 2) if donate else ()
    return jax.jit(step_fn, donate_argnums=donate_argnums)
