"""GRPOTrainer: rollout → reward → group advantage → jitted update loop.

Reference capability: rllib Algorithm.train() (rollout workers + learner);
here rollouts run on the serve plane's continuous-batching LLMEngine (the
same decode path production serving uses) and the learner is the one-program
GRPO step. Single-host by default; the learner step accepts a mesh for
sharded multi-chip updates (same TrainState plumbing as train/).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.models.llama import LlamaConfig
from ray_tpu.rl.grpo import (
    GRPOConfig,
    compute_group_advantages,
    make_grpo_step,
    make_logprob_fn,
)
from ray_tpu.utils.logging import get_logger

logger = get_logger("rl.trainer")


class GRPOTrainer:
    """reward_fn(prompt_tokens, completion_tokens) -> float."""

    def __init__(
        self,
        config: LlamaConfig,
        reward_fn: Callable[[List[int], List[int]], float],
        grpo: Optional[GRPOConfig] = None,
        optimizer=None,
        params=None,
        num_slots: int = 8,
        mesh=None,
    ):
        import jax

        from ray_tpu.serve.llm import LLMEngine
        from ray_tpu.train.step import TrainState, default_optimizer

        self.config = config
        self.grpo = grpo or GRPOConfig()
        self.reward_fn = reward_fn
        self.mesh = mesh
        optimizer = optimizer or default_optimizer(lr=1e-5, warmup_steps=1,
                                                   total_steps=10_000)
        self._optimizer = optimizer
        from ray_tpu.models.llama import llama_init

        params = params if params is not None else llama_init(config, jax.random.key(0))
        self.state = TrainState(
            step=jax.numpy.zeros((), jax.numpy.int32),
            params=params,
            opt_state=optimizer.init(params),
        )
        # frozen reference policy for the KL penalty
        self._ref_params = jax.tree.map(lambda x: x, params)
        self._logprob = make_logprob_fn(config, mesh=mesh)
        self._step = make_grpo_step(config, optimizer, self.grpo, mesh=mesh,
                                    donate=False)
        self.engine = LLMEngine(
            config, params=params, num_slots=num_slots,
            temperature=self.grpo.temperature,
        )

    # ------------------------------------------------------------- rollouts
    def _rollout(self, prompts: Sequence[List[int]]):
        """G completions per prompt via the continuous-batching engine."""
        G = self.grpo.group_size
        outs: List[List[int]] = []
        metas: List[Dict[str, Any]] = []
        for p in prompts:
            for _ in range(G):
                r = self.engine.generate(list(p), max_tokens=self.grpo.max_new_tokens)
                outs.append(r["tokens"])
                metas.append({"prompt_len": len(p)})
        return outs, metas

    def train_step(self, prompts: Sequence[List[int]]) -> Dict[str, Any]:
        import jax.numpy as jnp

        G = self.grpo.group_size
        completions, metas = self._rollout(prompts)
        rewards = np.asarray([
            self.reward_fn(list(p), c)
            for p, group in zip(prompts, _chunks(completions, G))
            for c in group
        ], np.float32).reshape(len(prompts), G)
        advantages = np.asarray(
            compute_group_advantages(jnp.asarray(rewards)))

        # pack sequences: [prompt + completion], right-padded
        seqs = [list(p) + c for p, group in zip(prompts, _chunks(completions, G))
                for c in group]
        T = max(len(s) for s in seqs)
        N = len(seqs)
        tokens = np.zeros((N, T), np.int32)
        comp_mask = np.zeros((N, T - 1), np.float32)
        for i, (s, meta) in enumerate(zip(seqs, metas)):
            tokens[i, :len(s)] = s
            # position t predicts token t+1: completion predictions start at
            # prompt_len-1 and stop before padding
            comp_mask[i, meta["prompt_len"] - 1:len(s) - 1] = 1.0

        tokens = jnp.asarray(tokens)
        comp_mask = jnp.asarray(comp_mask)
        old_logprobs = self._logprob(self.state.params, tokens)
        ref_logprobs = self._logprob(self._ref_params, tokens)
        batch = {
            "tokens": tokens,
            "completion_mask": comp_mask,
            "advantages": jnp.asarray(advantages.reshape(-1)),
            "old_logprobs": old_logprobs,
            "ref_logprobs": ref_logprobs,
        }
        metrics: Dict[str, Any] = {}
        for _ in range(self.grpo.epochs_per_batch):
            self.state, metrics = self._step(self.state, batch)
        # the engine serves the UPDATED policy for the next rollouts
        self.engine.params = self.state.params
        out = {k: float(v) for k, v in metrics.items()}
        out["reward_mean"] = float(rewards.mean())
        out["reward_std"] = float(rewards.std())
        return out

    def stop(self) -> None:
        self.engine.stop()


def _chunks(xs: List[Any], n: int):
    for i in range(0, len(xs), n):
        yield xs[i:i + n]
