"""DQN: double-Q learning over the env-runner/replay platform.

Reference capability: rllib/algorithms/dqn/ (double DQN per van Hasselt
'15, target network sync, prioritized replay, epsilon-greedy schedule).
TPU-first: the Q-network is a jitted MLP (bf16 is pointless at this size;
f32 on the MXU), the update step is ONE compiled program (forward + huber
TD loss + adamw via optax), and rollouts come from a fault-tolerant
EnvRunnerGroup with params broadcast through the object store.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl.env import make_env
from ray_tpu.rl.env_runner import EnvRunnerGroup
from ray_tpu.rl.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.utils.logging import get_logger

logger = get_logger("rl.dqn")


@dataclass
class DQNConfig:
    env: str = "CartPole-rt"
    env_config: Dict[str, Any] = field(default_factory=dict)
    hidden: tuple = (128, 128)
    lr: float = 1e-3
    gamma: float = 0.99
    buffer_capacity: int = 50_000
    prioritized: bool = True
    batch_size: int = 64
    num_runners: int = 2
    rollout_steps: int = 128       # per runner per iteration
    target_sync_interval: int = 8  # iterations between target syncs
    epsilon_start: float = 1.0
    epsilon_end: float = 0.05
    epsilon_decay_iters: int = 40
    learning_starts: int = 500     # min transitions before updates
    updates_per_iter: int = 32
    double_q: bool = True
    seed: int = 0


def q_init(obs_dim: int, num_actions: int, hidden, key):
    import jax
    import jax.numpy as jnp

    sizes = (obs_dim,) + tuple(hidden) + (num_actions,)
    params = []
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, k = jax.random.split(key)
        params.append({
            "w": jax.random.normal(k, (a, b), jnp.float32) * (2.0 / a) ** 0.5,
            "b": jnp.zeros((b,), jnp.float32),
        })
    return params


def q_forward(params, obs):
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(obs, jnp.float32)
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return x  # [B, A]


def make_policy_builder():
    """Greedy-argmax policy used inside env runners (exploration noise is
    added runner-side; the network shape rides in via ``params``). Builder
    pattern: the closure compiles lazily in the runner process."""

    def builder():
        import jax

        fwd = jax.jit(q_forward)

        def policy(params, obs_batch):
            return np.asarray(jax.numpy.argmax(fwd(params, obs_batch), -1))

        return policy

    return builder


def make_dqn_update(config: DQNConfig, optimizer):
    import jax
    import jax.numpy as jnp

    def loss_fn(params, target_params, batch):
        q = q_forward(params, batch["obs"])  # [B, A]
        qa = jnp.take_along_axis(q, batch["actions"][:, None], 1)[:, 0]
        qn_target = q_forward(target_params, batch["next_obs"])
        if config.double_q:
            # action selection by the ONLINE net, evaluation by the target
            best = jnp.argmax(q_forward(params, batch["next_obs"]), -1)
            qn = jnp.take_along_axis(qn_target, best[:, None], 1)[:, 0]
        else:
            qn = jnp.max(qn_target, -1)
        target = batch["rewards"] + config.gamma * qn * (1.0 - batch["dones"])
        td = qa - jax.lax.stop_gradient(target)
        huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td ** 2,
                          jnp.abs(td) - 0.5)
        w = batch.get("weights")
        loss = jnp.mean(huber * w) if w is not None else jnp.mean(huber)
        return loss, td

    @jax.jit
    def update(params, target_params, opt_state, batch):
        (loss, td), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, target_params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, td

    return update


class DQNTrainer:
    """Iteration = sample rollouts -> fill buffer -> K jitted updates ->
    (periodic) target sync. train() returns rllib-style result dicts."""

    def __init__(self, config: DQNConfig):
        import jax
        import optax

        self.config = config
        probe = make_env(config.env, **config.env_config)
        self.obs_dim = probe.obs_dim
        self.num_actions = probe.num_actions
        self.params = q_init(self.obs_dim, self.num_actions, config.hidden,
                             jax.random.key(config.seed))
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.optimizer = optax.adamw(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._update = make_dqn_update(config, self.optimizer)
        self.buffer = (PrioritizedReplayBuffer(config.buffer_capacity,
                                               seed=config.seed)
                       if config.prioritized
                       else ReplayBuffer(config.buffer_capacity,
                                         seed=config.seed))
        self.runners = EnvRunnerGroup(
            config.env,
            make_policy_builder(),
            num_runners=config.num_runners, env_config=config.env_config,
            seed=config.seed,
        )
        self.iteration = 0
        self._episode_returns: List[float] = []

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self.iteration / max(1, c.epsilon_decay_iters))
        return c.epsilon_start + frac * (c.epsilon_end - c.epsilon_start)

    def train(self) -> Dict[str, Any]:
        import jax

        c = self.config
        t0 = time.perf_counter()
        params_ref = ray_tpu.put(jax.device_get(self.params))
        batches = self.runners.sample(params_ref, c.rollout_steps,
                                      explore=self._epsilon())
        steps = 0
        for b in batches:
            self.buffer.add_batch(b)
            steps += len(b["obs"])
            self._episode_returns.extend(
                e["episode_return"] for e in b["episodes"])
        losses = []
        if len(self.buffer) >= c.learning_starts:
            for _ in range(c.updates_per_iter):
                batch = self.buffer.sample(c.batch_size)
                dev = {k: v for k, v in batch.items() if k != "indices"}
                self.params, self.opt_state, loss, td = self._update(
                    self.params, self.target_params, self.opt_state, dev)
                losses.append(float(loss))
                if isinstance(self.buffer, PrioritizedReplayBuffer):
                    self.buffer.update_priorities(batch["indices"],
                                                  np.asarray(td))
        self.iteration += 1
        if self.iteration % c.target_sync_interval == 0:
            self.target_params = jax.tree.map(lambda x: x, self.params)
        recent = self._episode_returns[-20:]
        return {
            "training_iteration": self.iteration,
            "env_steps_this_iter": steps,
            "buffer_size": len(self.buffer),
            "epsilon": self._epsilon(),
            "loss": float(np.mean(losses)) if losses else None,
            "episode_return_mean": float(np.mean(recent)) if recent else None,
            "num_episodes": len(self._episode_returns),
            "time_this_iter_s": time.perf_counter() - t0,
        }

    def stop(self) -> None:
        self.runners.stop()
