"""Replay buffers: uniform ring + proportional prioritized.

Reference capability: rllib/utils/replay_buffers/ (ReplayBuffer,
PrioritizedEpisodeReplayBuffer — proportional prioritization per
Schaul et al. '15 with importance weights). Redesign: flat numpy ring
buffers keyed by column (obs/actions/rewards/next_obs/dones) — batches go
straight into jitted update steps as device arrays; the prioritized
variant keeps priorities in a numpy array and samples by cumulative-sum
inversion (O(log n) via searchsorted), plenty at host-side buffer sizes.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform-sampling ring buffer."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._cols: Optional[Dict[str, np.ndarray]] = None
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        data = {k: np.asarray(v) for k, v in batch.items()
                if isinstance(v, (np.ndarray, list))
                and k in ("obs", "actions", "rewards", "next_obs", "dones")}
        n = len(data["obs"])
        if self._cols is None:
            self._cols = {
                k: np.zeros((self.capacity,) + v.shape[1:], v.dtype)
                for k, v in data.items()
            }
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in data.items():
            self._cols[k][idx] = v
        self._on_add(idx)
        self._next = int((self._next + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))

    def _on_add(self, idx: np.ndarray) -> None:
        pass

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        assert self._size > 0, "empty buffer"
        idx = self._rng.integers(0, self._size, batch_size)
        return self._gather(idx)

    def _gather(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        out = {k: v[idx] for k, v in self._cols.items()}
        out["indices"] = idx
        return out


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritization: P(i) ~ p_i^alpha, importance weights
    w_i = (N * P(i))^-beta / max w (Schaul et al. '15)."""

    def __init__(self, capacity: int, alpha: float = 0.6, beta: float = 0.4,
                 eps: float = 1e-6, seed: int = 0):
        super().__init__(capacity, seed=seed)
        self.alpha = alpha
        self.beta = beta
        self.eps = eps
        self._prior = np.zeros(capacity, np.float64)
        self._max_prior = 1.0

    def _on_add(self, idx: np.ndarray) -> None:
        self._prior[idx] = self._max_prior  # new samples get max priority

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        assert self._size > 0, "empty buffer"
        p = self._prior[: self._size] ** self.alpha
        cum = np.cumsum(p)
        targets = self._rng.random(batch_size) * cum[-1]
        idx = np.minimum(np.searchsorted(cum, targets), self._size - 1)
        out = self._gather(idx)
        probs = p[idx] / cum[-1]
        w = (self._size * probs) ** (-self.beta)
        out["weights"] = (w / w.max()).astype(np.float32)
        return out

    def update_priorities(self, indices: np.ndarray,
                          td_errors: np.ndarray) -> None:
        prior = np.abs(np.asarray(td_errors, np.float64)) + self.eps
        self._prior[np.asarray(indices)] = prior
        self._max_prior = max(self._max_prior, float(prior.max()))
