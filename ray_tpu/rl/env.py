"""Environment interface + built-in envs.

Reference capability: rllib/env/ (gym/gymnasium adapters, env registry).
The image bundles no gym, so the API IS the gymnasium core contract —
``reset() -> (obs, info)`` / ``step(a) -> (obs, reward, terminated,
truncated, info)`` — and any real gymnasium env drops in unchanged. Two
built-in envs cover the test/benchmark needs: CartPole (the classic
control benchmark, dynamics per Barto-Sutton-Anderson '83 as in gym's
cartpole.py) and a discrete ChainEnv (exploration stress)."""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

_REGISTRY: Dict[str, Callable[..., Any]] = {}


def register_env(name: str, creator: Callable[..., Any]) -> None:
    """Reference: ray.tune.registry.register_env."""
    _REGISTRY[name] = creator


def make_env(name: str, **kwargs) -> Any:
    if name in _REGISTRY:
        return _REGISTRY[name](**kwargs)
    try:  # a real gymnasium install takes precedence for unknown names
        import gymnasium

        return gymnasium.make(name, **kwargs)
    except ImportError:
        raise ValueError(
            f"unknown env '{name}' and gymnasium is not installed; "
            f"register_env() it (built-ins: {sorted(_REGISTRY)})"
        ) from None


class CartPoleEnv:
    """Classic cart-pole balancing (dynamics identical to gym CartPole-v1:
    4-d observation, 2 discrete actions, +1 reward per step, 500-step cap)."""

    num_actions = 2
    obs_dim = 4

    def __init__(self, max_steps: int = 500, seed: Optional[int] = None):
        self.max_steps = max_steps
        self._rng = np.random.default_rng(seed)
        self._state: Optional[np.ndarray] = None
        self._t = 0

    def reset(self, *, seed: Optional[int] = None) -> Tuple[np.ndarray, dict]:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        self._t = 0
        return self._state.copy(), {}

    def step(self, action: int):
        assert self._state is not None, "call reset() first"
        x, x_dot, theta, theta_dot = self._state
        force = 10.0 if action == 1 else -10.0
        g, mc, mp, length = 9.8, 1.0, 0.1, 0.5
        total = mc + mp
        pml = mp * length
        costh, sinth = math.cos(theta), math.sin(theta)
        temp = (force + pml * theta_dot ** 2 * sinth) / total
        theta_acc = (g * sinth - costh * temp) / (
            length * (4.0 / 3.0 - mp * costh ** 2 / total))
        x_acc = temp - pml * theta_acc * costh / total
        tau = 0.02
        x += tau * x_dot
        x_dot += tau * x_acc
        theta += tau * theta_dot
        theta_dot += tau * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot], np.float32)
        self._t += 1
        terminated = bool(abs(x) > 2.4 or abs(theta) > 12 * math.pi / 180)
        truncated = self._t >= self.max_steps
        return self._state.copy(), 1.0, terminated, truncated, {}


class ChainEnv:
    """N-state chain: action 1 walks right (reward at the end), action 0
    resets to start with a small reward — a standard exploration probe."""

    def __init__(self, n: int = 10, max_steps: int = 50,
                 seed: Optional[int] = None):
        self.n = n
        self.num_actions = 2
        self.obs_dim = n
        self.max_steps = max_steps
        self._pos = 0
        self._t = 0

    def _obs(self) -> np.ndarray:
        v = np.zeros(self.n, np.float32)
        v[self._pos] = 1.0
        return v

    def reset(self, *, seed: Optional[int] = None):
        self._pos = 0
        self._t = 0
        return self._obs(), {}

    def step(self, action: int):
        self._t += 1
        if action == 1:
            self._pos = min(self.n - 1, self._pos + 1)
            reward = 10.0 if self._pos == self.n - 1 else 0.0
        else:
            self._pos = 0
            reward = 0.1
        return self._obs(), reward, False, self._t >= self.max_steps, {}


register_env("CartPole-rt", lambda **kw: CartPoleEnv(**kw))
register_env("Chain-rt", lambda **kw: ChainEnv(**kw))
