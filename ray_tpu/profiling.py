"""User profile events: spans that land on the cluster timeline.

Reference capability: src/ray/core_worker/profile_event.{h,cc} +
python/ray/_private/profiling.py:20-40 — `with ray.profiling.profile("x"):`
inside a task records a span shipped to the observability backend and
rendered by `ray timeline`. Here: spans buffer thread-locally in the
worker, flush to the node agent when the task finishes (one RPC only when
profiling was used), and the dashboard's /api/timeline merges them as
cat="user" chrome-trace events next to the task-state spans.

    import ray_tpu

    @ray_tpu.remote
    def step():
        with ray_tpu.profile("load"):
            ...
        with ray_tpu.profile("compute", extra={"batch": 8}):
            ...
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

# process-wide buffer: async actor methods record on the event-loop thread
# while the flush runs on an executor thread, so the buffer must NOT be
# thread-local. Bounded: an unflushed producer (local runtime, long-lived
# profiling loop) can't grow memory without limit.
_MAX_PENDING = 20000
_spans: List[Dict[str, Any]] = []
_lock = threading.Lock()
# local-runtime sink (no agent to ship to): bounded in-process span log
_local_runtime_spans: List[Dict[str, Any]] = []


@contextmanager
def profile(name: str, extra: Optional[Dict[str, Any]] = None):
    """Record a named span for the cluster timeline."""
    start = time.time()
    try:
        yield
    finally:
        end = time.time()
        span: Dict[str, Any] = {"name": str(name), "start": start, "end": end}
        if extra:
            span["extra"] = {str(k): v for k, v in extra.items()}
        try:
            from ray_tpu.core.worker import global_worker

            w = global_worker()
            task_id = getattr(w, "current_task_id", None)
            if task_id is not None:
                span["task_id"] = task_id.hex() if hasattr(task_id, "hex") \
                    else str(task_id)
        except Exception:  # noqa: BLE001 - outside a runtime
            pass
        with _lock:
            _spans.append(span)
            del _spans[:-_MAX_PENDING]


def record_external_span(name: str, start: float, end: float,
                         extra: Optional[Dict[str, Any]] = None) -> None:
    """Record an already-timed span (the tracing bridge: util/tracing.py
    spans ride the same flush path to the agent/timeline)."""
    span: Dict[str, Any] = {"name": str(name), "start": start, "end": end}
    if extra:
        span["extra"] = {str(k): v for k, v in extra.items()}
    with _lock:
        _spans.append(span)
        del _spans[:-_MAX_PENDING]


def drain() -> List[Dict[str, Any]]:
    """Take (and clear) every recorded span (worker/local flush paths)."""
    global _spans
    with _lock:
        out, _spans = _spans, []
    return out


def flush_local() -> None:
    """Local-runtime sink: move pending spans into the in-process log
    (read back with local_spans(); there is no agent to ship to)."""
    spans = drain()
    if spans:
        with _lock:
            _local_runtime_spans.extend(spans)
            del _local_runtime_spans[:-_MAX_PENDING]


def local_spans() -> List[Dict[str, Any]]:
    with _lock:
        return list(_local_runtime_spans)
